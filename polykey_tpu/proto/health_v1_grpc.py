"""gRPC glue for grpc.health.v1.Health (hand-written; see proto/__init__.py).

The reference serves this protocol via grpc-go's bundled health server
(/root/reference/cmd/polykey/main.go:82-94); grpc_health_probe in the container
healthcheck speaks it (compose.yml:17-22).
"""

import grpc

from . import health_v1_pb2 as health_pb

SERVICE_NAME = "grpc.health.v1.Health"


class HealthStub:
    def __init__(self, channel: grpc.Channel):
        self.Check = channel.unary_unary(
            f"/{SERVICE_NAME}/Check",
            request_serializer=health_pb.HealthCheckRequest.SerializeToString,
            response_deserializer=health_pb.HealthCheckResponse.FromString,
        )
        self.Watch = channel.unary_stream(
            f"/{SERVICE_NAME}/Watch",
            request_serializer=health_pb.HealthCheckRequest.SerializeToString,
            response_deserializer=health_pb.HealthCheckResponse.FromString,
        )


class HealthServicer:
    def Check(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "Method not implemented!")

    def Watch(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "Method not implemented!")


def add_HealthServicer_to_server(servicer, server):
    rpc_method_handlers = {
        "Check": grpc.unary_unary_rpc_method_handler(
            servicer.Check,
            request_deserializer=health_pb.HealthCheckRequest.FromString,
            response_serializer=health_pb.HealthCheckResponse.SerializeToString,
        ),
        "Watch": grpc.unary_stream_rpc_method_handler(
            servicer.Watch,
            request_deserializer=health_pb.HealthCheckRequest.FromString,
            response_serializer=health_pb.HealthCheckResponse.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, rpc_method_handlers),)
    )
