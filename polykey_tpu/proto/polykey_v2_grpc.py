"""gRPC client stub + server registration for polykey.v2.PolykeyService.

Hand-written equivalent of grpc_tools protoc output (grpc_tools is not in the
image). Service/method names mirror the reference exactly: the Go server
registers ``polykey.v2.PolykeyService`` with method ``ExecuteTool``
(/root/reference/cmd/polykey/main.go:89-94, internal/server/server.go:27).
``ExecuteToolStream`` is this framework's streaming extension.
"""

import grpc

from . import polykey_v2_pb2 as pk

SERVICE_NAME = "polykey.v2.PolykeyService"


class PolykeyServiceStub:
    """Client-side stub."""

    def __init__(self, channel: grpc.Channel):
        self.ExecuteTool = channel.unary_unary(
            f"/{SERVICE_NAME}/ExecuteTool",
            request_serializer=pk.ExecuteToolRequest.SerializeToString,
            response_deserializer=pk.ExecuteToolResponse.FromString,
        )
        self.ExecuteToolStream = channel.unary_stream(
            f"/{SERVICE_NAME}/ExecuteToolStream",
            request_serializer=pk.ExecuteToolRequest.SerializeToString,
            response_deserializer=pk.ExecuteToolStreamChunk.FromString,
        )


class PolykeyServiceServicer:
    """Server-side service skeleton; subclass and override."""

    def ExecuteTool(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "Method not implemented!")

    def ExecuteToolStream(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "Method not implemented!")


def add_PolykeyServiceServicer_to_server(servicer, server):
    rpc_method_handlers = {
        "ExecuteTool": grpc.unary_unary_rpc_method_handler(
            servicer.ExecuteTool,
            request_deserializer=pk.ExecuteToolRequest.FromString,
            response_serializer=pk.ExecuteToolResponse.SerializeToString,
        ),
        "ExecuteToolStream": grpc.unary_stream_rpc_method_handler(
            servicer.ExecuteToolStream,
            request_deserializer=pk.ExecuteToolRequest.FromString,
            response_serializer=pk.ExecuteToolStreamChunk.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, rpc_method_handlers),)
    )
