"""Mixture-of-Experts layer (Mixtral-style top-k routing).

Two formulations:

- `moe_mlp` — einsum-dense: every token runs through every expert, weighted
  by the (sparse) combine matrix. Simple, fully differentiable, and shards
  cleanly: with the expert axis on ``ep`` (parallel/sharding.py), each device
  computes only its local experts' contributions and XLA reduces the combine
  over the ep axis — structurally the all-to-all-free "expert-replicated
  compute" layout. Cost: num_experts/top_k × the FLOPs of sparse dispatch
  (4× for Mixtral 8×7B's 8-choose-2) — acceptable for correctness paths and
  small batches.
- `moe_mlp_dispatch` — capacity-bucketed sparse dispatch: tokens gather into
  per-expert buckets (static capacity, dropped on overflow like GShard/
  Switch), experts run batched matmuls on their buckets only, results
  scatter-combine back. With experts on ``ep`` under jit, XLA emits the
  token all-to-all over ICI. This is the serving path for real MoE sizes.

Router math in fp32; combine weights renormalized over the selected top-k
(Mixtral convention).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..models.config import ModelConfig
from ..models.layers import _activate
from ..models.quant import qeinsum_expert


def _router_weights(
    layer_params: dict, h: jax.Array, cfg: ModelConfig
) -> tuple[jax.Array, jax.Array]:
    """Top-k routing: returns (combine [.., E] fp32, expert_idx [.., k])."""
    logits = jnp.einsum(
        "...h,he->...e", h, layer_params["router"],
        preferred_element_type=jnp.float32,
    )
    weights, idx = jax.lax.top_k(logits, cfg.num_experts_per_tok)   # [.., k]
    weights = jax.nn.softmax(weights, axis=-1)                      # renorm
    # Dense [.., E] combine matrix: one-hot scatter of the k weights.
    onehot = jax.nn.one_hot(idx, cfg.num_experts, dtype=jnp.float32)
    combine = jnp.sum(onehot * weights[..., None], axis=-2)
    return combine, idx


def moe_mlp(layer_params: dict, h: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Dense-compute MoE: [B, T, H] → [B, T, H]."""
    combine, _ = _router_weights(layer_params, h, cfg)              # [B,T,E]
    experts = layer_params["experts"]                               # stacked [E,...]

    up = qeinsum_expert("bth,ehi->beti", h, experts["up"], e_axis=1)
    gate = _activate(
        qeinsum_expert("bth,ehi->beti", h, experts["gate"], e_axis=1),
        cfg.activation,
    )
    out = qeinsum_expert(
        "beti,eih->beth", gate * up, experts["down"], e_axis=1
    )  # [B,E,T,H]
    return jnp.einsum(
        "beth,bte->bth", out.astype(jnp.float32), combine
    ).astype(h.dtype)


def moe_mlp_dispatch(
    layer_params: dict,
    h: jax.Array,                   # [B, T, H]
    cfg: ModelConfig,
    capacity_factor: float = 1.25,
) -> jax.Array:
    """Capacity-bucketed sparse dispatch (GShard-style).

    Static shapes: each expert processes a fixed-capacity bucket
    C = ceil(tokens · k / E · capacity_factor); tokens beyond an expert's
    capacity are dropped (their combine weight contributes nothing — the
    residual connection carries them).
    """
    B, T, H = h.shape
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    tokens = h.reshape(B * T, H)
    N = B * T
    capacity = max(1, int(N * k / E * capacity_factor))

    combine, idx = _router_weights(layer_params, tokens, cfg)       # [N,E],[N,k]

    # Position of each (token, choice) within its expert's bucket.
    onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)                # [N,k,E]
    flat_choice = onehot.reshape(N * k, E)
    position = jnp.cumsum(flat_choice, axis=0) * flat_choice - 1    # [N·k,E]
    position = position.reshape(N, k, E)
    slot = jnp.sum(position * onehot, axis=-1)                      # [N,k]
    expert = idx                                                    # [N,k]
    keep = slot < capacity

    # Gather tokens into buckets [E, C, H].
    buckets = jnp.zeros((E, capacity, H), h.dtype)
    flat_expert = expert.reshape(-1)
    flat_slot = jnp.where(keep, slot, capacity - 1).reshape(-1)
    flat_keep = keep.reshape(-1)
    src = jnp.repeat(tokens, k, axis=0)                             # [N·k,H]
    src = jnp.where(flat_keep[:, None], src, 0)
    buckets = buckets.at[flat_expert, flat_slot].add(src)

    # Expert compute on buckets.
    experts_p = layer_params["experts"]
    up = qeinsum_expert("ech,ehi->eci", buckets, experts_p["up"], e_axis=0)
    gate = _activate(
        qeinsum_expert("ech,ehi->eci", buckets, experts_p["gate"], e_axis=0),
        cfg.activation,
    )
    out = qeinsum_expert(
        "eci,eih->ech", gate * up, experts_p["down"], e_axis=0
    )  # [E,C,H]

    # Combine back: each (token, choice) reads its bucket slot.
    gathered = out[flat_expert, flat_slot].reshape(N, k, H)
    weight = jnp.take_along_axis(combine, expert, axis=-1)          # [N,k]
    weight = jnp.where(keep, weight, 0.0)
    mixed = jnp.sum(
        gathered.astype(jnp.float32) * weight[..., None], axis=1
    )                                                               # [N,H]
    return mixed.reshape(B, T, H).astype(h.dtype)
