"""Ring attention: sequence-parallel attention over the ICI ring.

Long-context prefill/training beyond one chip's HBM (SURVEY.md §5's
long-context obligation): queries stay put, KV chunks rotate around the
`sp` mesh axis via `lax.ppermute`, and each device folds every visiting
chunk into online-softmax state (running max m, denominator l, fp32
accumulator — the same recurrence as ops/flash_attention.py, one ring hop
per block). Peak memory per device is O(T_local·D + S_local·D); the full
[T, S] logits matrix never exists anywhere.

Two entry points:
- `ring_attention` — the per-device body; call it inside `shard_map` with
  the KV/sequence dimension sharded over `axis_name`.
- `ring_attention_spmd` — convenience wrapper that builds the `shard_map`
  over a mesh with the framework's standard axes (batch over dp, sequence
  over sp, heads over tp; parallel/mesh.py).

Masking is by absolute position (q_positions / kv_positions travel with
their chunks), so causality is independent of how the ring is laid out.
XLA overlaps the ppermute with the block compute where the schedule allows;
collectives ride ICI by construction (sp is an ICI mesh axis).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ..compat import shard_map

_NEG_INF = -1e30


def _block_update(
    q,            # [B, T, Hq, D] (original dtype; math in fp32)
    k, v,         # [B, S, Hk, D] current chunk
    q_pos,        # [B, T]
    kv_pos,       # [B, S]
    m, l, acc,    # [B, Hq, T], [B, Hq, T], [B, T, Hq, D] fp32
    *,
    scale: float,
    logit_softcap: Optional[float],
    window: Optional[jax.Array],
):
    B, T, Hq, D = q.shape
    Hk = k.shape[2]
    g = Hq // Hk

    qg = q.reshape(B, T, Hk, g, D)
    s = jnp.einsum(
        "bthgd,bshd->bhgts", qg, k, preferred_element_type=jnp.float32
    ) * scale                                           # [B, Hk, g, T, S]
    if logit_softcap is not None:
        s = logit_softcap * jnp.tanh(s / logit_softcap)

    mask = kv_pos[:, None, :] <= q_pos[:, :, None]      # [B, T, S]
    if window is not None:
        w = jnp.asarray(window, jnp.int32)
        mask &= (w <= 0) | (kv_pos[:, None, :] > q_pos[:, :, None] - w)
    s = jnp.where(mask[:, None, None, :, :], s, _NEG_INF)

    s = s.reshape(B, Hq, T, -1)
    m_cur = jnp.max(s, axis=-1)                         # [B, Hq, T]
    m_new = jnp.maximum(m, m_cur)
    # Explicit zero where masked: a fully-masked chunk has s == m_new ==
    # _NEG_INF and exp(0) would add spurious mass to l.
    p = jnp.exp(s - m_new[..., None])                   # [B, Hq, T, S]
    p = jnp.where(mask[:, None, :, :], p, 0.0)
    corr = jnp.exp(m - m_new)                           # [B, Hq, T]
    l_new = corr * l + jnp.sum(p, axis=-1)

    pg = p.reshape(B, Hk, g, T, -1)
    pv = jnp.einsum(
        "bhgts,bshd->bthgd", pg, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ).reshape(B, T, Hq, D)
    acc_new = acc * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def ring_attention(
    q: jax.Array,             # [B, T_local, Hq, D]
    k: jax.Array,             # [B, S_local, Hk, D]
    v: jax.Array,
    q_positions: jax.Array,   # [B, T_local] absolute positions
    kv_positions: jax.Array,  # [B, S_local]
    *,
    axis_name: str,
    axis_size: int,
    scale: float,
    logit_softcap: Optional[float] = None,
    window: Optional[jax.Array] = None,
) -> jax.Array:
    """Per-device ring attention body (call inside shard_map).

    Rotates (k, v, kv_positions) `axis_size - 1` times around `axis_name`;
    returns [B, T_local, Hq, D] in q.dtype.
    """
    B, T, Hq, D = q.shape

    m0 = jnp.full((B, Hq, T), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hq, T), jnp.float32)
    acc0 = jnp.zeros((B, T, Hq, D), jnp.float32)

    update = functools.partial(
        _block_update, scale=scale, logit_softcap=logit_softcap, window=window
    )
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, i):
        k_c, v_c, kvp_c, m, l, acc = carry
        m, l, acc = update(q, k_c, v_c, q_positions, kvp_c, m, l, acc)

        def rotate(args):
            k_c, v_c, kvp_c = args
            return (
                jax.lax.ppermute(k_c, axis_name, perm),
                jax.lax.ppermute(v_c, axis_name, perm),
                jax.lax.ppermute(kvp_c, axis_name, perm),
            )

        k_c, v_c, kvp_c = jax.lax.cond(
            i < axis_size - 1, rotate, lambda a: a, (k_c, v_c, kvp_c)
        )
        return (k_c, v_c, kvp_c, m, l, acc), None

    (_, _, _, m, l, acc), _ = jax.lax.scan(
        step,
        (k, v, kv_positions, m0, l0, acc0),
        jnp.arange(axis_size),
    )

    l = jnp.maximum(l, 1e-9).transpose(0, 2, 1)[..., None]  # [B, T, Hq, 1]
    return (acc / l).astype(q.dtype)


def ring_attention_spmd(
    q: jax.Array,             # [B, T, Hq, D] (global shapes)
    k: jax.Array,             # [B, S, Hk, D]
    v: jax.Array,
    q_positions: jax.Array,   # [B, T]
    kv_positions: jax.Array,  # [B, S]
    mesh: Mesh,
    *,
    scale: float,
    logit_softcap: Optional[float] = None,
    window: Optional[jax.Array] = None,
    seq_axis: str = "sp",
    batch_axis: str = "dp",
    head_axis: str = "tp",
) -> jax.Array:
    """shard_map wrapper: batch over dp, sequence over sp, heads over tp.

    GQA constraint: num_kv_heads must be divisible by the tp axis size (the
    same constraint parallel/sharding.py places on the projections).
    """
    axis_size = mesh.shape[seq_axis]
    qkv_spec = P(batch_axis, seq_axis, head_axis, None)
    pos_spec = P(batch_axis, seq_axis)

    inner = functools.partial(
        ring_attention,
        axis_name=seq_axis,
        axis_size=axis_size,
        scale=scale,
        logit_softcap=logit_softcap,
        window=window,
    )
    return shard_map(
        inner,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, pos_spec, pos_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )(q, k, v, q_positions, kv_positions)
