"""Pallas paged-KV WRITE kernel (TPU) — the decode-step scatter, done as DMA.

Why this exists: the XLA scatter in ops/paged_attention.paged_write
(`k_pages.at[page_ids, offsets].set(k_new)`) lowers on TPU to a
sequential per-row update loop — for a decode step that is
2 (k,v) x num_layers x B tiny dynamic-update-slices, measured at ~10 ms
of the ~21 ms step at 1B/B=32 geometry (scripts/profile_block_device.py,
PERF.md). The write itself moves only B x Hk x D x 2 bytes per layer
(~100 KB) — it is pure launch/serialization overhead.

A row cannot be DMA'd directly into its page: pool pages are tiled
(8, 128) in their last two dims, and DMA slices at arbitrary sublane
offsets (the row's position within the page) are illegal. So the kernel
does a two-wave page-granular read-modify-write, one program total:

  wave 1: start ALL page-read DMAs (pool page -> VMEM buffer) at once,
          across every pool and every lane;
  blend:  per lane (static unrolled loop), select the lane's row into
          the buffered page at its offset — pure vector ops;
  wave 2: start ALL page write-back DMAs, wait.

Every DMA in a wave is in flight concurrently, so the cost is ~two page
DMA latencies + B small vector blends, independent of B's serialization.
The pools are input_output_aliased — in place, no pool copy (the engine
donates the pool through every dispatch).

The kernel is generic over a LIST of (pool, rows) writes sharing one
(page, offset) index layout: the fp path writes [k, v] data pools
([N, ps, Hk*D] folded — heads into lanes, exactly like the read kernel
ops/paged_attention_kernel.py); the int8-KV path adds the bf16 scale
pools [N, ps, Hk] in the same waves.

Garbage-page collisions are intended: inactive lanes all target page 0
(engine convention, engine.py "Inactive slots"); several lanes then RMW
page 0 concurrently and *some* full page wins — page 0 is never read
unmasked. Active lanes never share a page (allocator invariant), so
their full-page write-backs cannot clobber each other.

Hk*D must be 128-aligned for the folded data-pool DMA — the same
`use_paged_kernel` gate as the read kernel. Off-TPU (and under
POLYKEY_DISABLE_PAGED_KERNEL=1) callers keep the XLA scatter.

Reference obligation: none — the reference has no KV cache at all
(SURVEY.md §2b "Paged KV cache" is north-star-owed); this is the
TPU-idiomatic half of that component.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _make_kernel(n_pools: int, B: int, ps: int):
    """Kernel body over `n_pools` (rows, pool_in, pool_out, buf, 2 sems)
    groups; arity varies with the pool list, so the body is built here."""

    def kernel(*refs):
        # Ref order: 2 scalar-prefetch, n rows, n pool inputs (aliased —
        # unused), n pool outputs, then scratch.
        pids_ref, offs_ref = refs[0], refs[1]
        rows = refs[2:2 + n_pools]
        outs = refs[2 + 2 * n_pools:2 + 3 * n_pools]
        scratch = refs[2 + 3 * n_pools:]
        bufs = scratch[:n_pools]
        r_sems = scratch[n_pools:2 * n_pools]
        w_sems = scratch[2 * n_pools:3 * n_pools]

        def read_dma(i, b):
            return pltpu.make_async_copy(
                outs[i].at[pids_ref[b]], bufs[i].at[b], r_sems[i].at[b]
            )

        def write_dma(i, b):
            return pltpu.make_async_copy(
                bufs[i].at[b], outs[i].at[pids_ref[b]], w_sems[i].at[b]
            )

        # Wave 1: every lane's page reads, all pools, all at once.
        for b in range(B):
            for i in range(n_pools):
                read_dma(i, b).start()

        sel = jax.lax.broadcasted_iota(jnp.int32, (ps, 1), 0)
        for b in range(B):
            for i in range(n_pools):
                read_dma(i, b).wait()
                bufs[i][b] = jnp.where(
                    sel == offs_ref[b], rows[i][b], bufs[i][b]
                )
                # Wave 2 starts per lane as soon as its blend lands.
                write_dma(i, b).start()

        for b in range(B):
            for i in range(n_pools):
                write_dma(i, b).wait()

    return kernel


def paged_write_rows_kernel(
    pools: list,              # data [N, ps, Hk, D] and/or scale [N, ps, Hk]
    rows: list,               # matching [B, 1, Hk, D] / [B, 1, Hk]
    page_ids: jax.Array,      # [B] int32
    offsets: jax.Array,       # [B] int32
    *,
    interpret: bool = False,
) -> tuple:
    """In-place page RMW of each (pool, rows) pair at one shared
    (page, offset) per lane; returns the (aliased) pools, same order."""
    n = len(pools)
    B = rows[0].shape[0]
    ps = pools[0].shape[1]

    folded_pools, folded_rows, shapes = [], [], []
    for p, r in zip(pools, rows):
        shapes.append(p.shape)
        if p.ndim == 4:
            N, _, Hk, D = p.shape
            folded_pools.append(p.reshape(N, ps, Hk * D))
            folded_rows.append(r.reshape(B, 1, Hk * D).astype(p.dtype))
        else:
            folded_pools.append(p)
            folded_rows.append(r.reshape(B, 1, p.shape[2]).astype(p.dtype))

    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    row_specs = [
        pl.BlockSpec(fr.shape, lambda *_: (0, 0, 0),
                     memory_space=pltpu.VMEM)
        for fr in folded_rows
    ]
    outs = pl.pallas_call(
        _make_kernel(n, B, ps),
        out_shape=tuple(
            jax.ShapeDtypeStruct(fp.shape, fp.dtype) for fp in folded_pools
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(),
            in_specs=row_specs + [any_spec] * n,
            out_specs=[any_spec] * n,
            scratch_shapes=(
                [pltpu.VMEM((B, ps, fp.shape[2]), fp.dtype)
                 for fp in folded_pools]
                + [pltpu.SemaphoreType.DMA((B,))] * (2 * n)
            ),
        ),
        # Flattened input positions incl. the 2 scalar-prefetch args:
        # pids=0 offs=1 rows=2..2+n-1 pools=2+n..2+2n-1.
        input_output_aliases={2 + n + i: i for i in range(n)},
        interpret=interpret,
    )(
        page_ids.astype(jnp.int32),
        offsets.astype(jnp.int32),
        *folded_rows,
        *folded_pools,
    )
    return tuple(o.reshape(sh) for o, sh in zip(outs, shapes))


def paged_write_decode_kernel(
    k_pages: jax.Array,       # [N, ps, Hk, D]
    v_pages: jax.Array,
    k_new: jax.Array,         # [B, 1, Hk, D] — single decode token per lane
    v_new: jax.Array,
    page_ids: jax.Array,      # [B] int32
    offsets: jax.Array,       # [B] int32
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """The fp two-pool case (kept as the named entry point the kernel
    check and tests exercise)."""
    kp, vp = paged_write_rows_kernel(
        [k_pages, v_pages], [k_new, v_new], page_ids, offsets,
        interpret=interpret,
    )
    return kp, vp
