"""Pallas paged-KV WRITE kernel (TPU) — the decode-step scatter, done as DMA.

Why this exists: the XLA scatter in ops/paged_attention.paged_write
(`k_pages.at[page_ids, offsets].set(k_new)`) lowers on TPU to a
sequential per-row update loop — for a decode step that is
2 (k,v) x num_layers x B tiny dynamic-update-slices, measured at ~10 ms
of the ~21 ms step at 1B/B=32 geometry (scripts/profile_block_device.py,
PERF.md). The write itself moves only B x Hk x D x 2 bytes per layer
(~100 KB) — it is pure launch/serialization overhead.

A row cannot be DMA'd directly into its page: pool pages are tiled
(8, 128) in their last two dims, and DMA slices at arbitrary sublane
offsets (the row's position within the page) are illegal. So the kernel
does a two-wave page-granular read-modify-write, one program total:

  wave 1: start ALL B page-read DMAs (pool page -> VMEM buffer) at once;
  blend:  per lane (static unrolled loop), select the lane's row into
          the buffered page at its offset — pure vector ops;
  wave 2: start ALL B page write-back DMAs, wait.

Every DMA in a wave is in flight concurrently, so the cost is ~two page
DMA latencies + B small vector blends, independent of B's serialization.
The pools are input_output_aliased — in place, no pool copy (the engine
donates the pool through every dispatch).

Garbage-page collisions are intended: inactive lanes all target page 0
(engine convention, engine.py "Inactive slots"); several lanes then RMW
page 0 concurrently and *some* full page wins — page 0 is never read
unmasked. Active lanes never share a page (allocator invariant), so
their full-page write-backs cannot clobber each other.

Layout: pools fold heads into lanes [N, ps, Hk*D] exactly like the read
kernel (ops/paged_attention_kernel.py) — Hk*D must be 128-aligned, the
same `use_paged_kernel` gate. Off-TPU (and under
POLYKEY_DISABLE_PAGED_KERNEL=1) callers keep the XLA scatter.

Reference obligation: none — the reference has no KV cache at all
(SURVEY.md §2b "Paged KV cache" is north-star-owed); this is the
TPU-idiomatic half of that component.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _write_kernel(
    # scalar prefetch
    pids_ref,      # [B] int32 destination page per lane (SMEM)
    offs_ref,      # [B] int32 destination row within the page (SMEM)
    # inputs
    knew_ref,      # [B, 1, HkD] VMEM — all lanes' new K rows (tiny)
    vnew_ref,      # [B, 1, HkD] VMEM
    kp_in,         # [N, ps, HkD] ANY (aliased with kp_out)
    vp_in,
    # outputs (aliased)
    kp_out,        # [N, ps, HkD] ANY
    vp_out,
    # scratch
    k_buf,         # [B, ps, HkD] VMEM — one buffered page per lane
    v_buf,
    kr_sems,       # [B] DMA semaphores (page reads)
    vr_sems,
    kw_sems,       # [B] DMA semaphores (page write-backs)
    vw_sems,
):
    del kp_in, vp_in
    B = k_buf.shape[0]
    ps = k_buf.shape[1]

    def read_dma(b, pages, buf, sems):
        return pltpu.make_async_copy(
            pages.at[pids_ref[b]], buf.at[b], sems.at[b]
        )

    def write_dma(b, buf, pages, sems):
        return pltpu.make_async_copy(
            buf.at[b], pages.at[pids_ref[b]], sems.at[b]
        )

    # Wave 1: every lane's page read goes out together.
    for b in range(B):
        read_dma(b, kp_out, k_buf, kr_sems).start()
        read_dma(b, vp_out, v_buf, vr_sems).start()

    rows = jax.lax.broadcasted_iota(jnp.int32, (ps, 1), 0)
    for b in range(B):
        read_dma(b, kp_out, k_buf, kr_sems).wait()
        read_dma(b, vp_out, v_buf, vr_sems).wait()
        sel = rows == offs_ref[b]                      # [ps, 1]
        k_buf[b] = jnp.where(sel, knew_ref[b], k_buf[b])
        v_buf[b] = jnp.where(sel, vnew_ref[b], v_buf[b])
        # Wave 2 starts per lane as soon as its blend lands.
        write_dma(b, k_buf, kp_out, kw_sems).start()
        write_dma(b, v_buf, vp_out, vw_sems).start()

    for b in range(B):
        write_dma(b, k_buf, kp_out, kw_sems).wait()
        write_dma(b, v_buf, vp_out, vw_sems).wait()


def paged_write_decode_kernel(
    k_pages: jax.Array,       # [N, ps, Hk, D]
    v_pages: jax.Array,
    k_new: jax.Array,         # [B, 1, Hk, D] — single decode token per lane
    v_new: jax.Array,
    page_ids: jax.Array,      # [B] int32
    offsets: jax.Array,       # [B] int32
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """In-place decode-step KV write; returns the (aliased) pools."""
    N, ps, Hk, D = k_pages.shape
    B = k_new.shape[0]
    HkD = Hk * D

    kp = k_pages.reshape(N, ps, HkD)
    vp = v_pages.reshape(N, ps, HkD)
    kn = k_new.reshape(B, 1, HkD)
    vn = v_new.reshape(B, 1, HkD)

    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    row_spec = pl.BlockSpec(
        (B, 1, HkD), lambda *_: (0, 0, 0), memory_space=pltpu.VMEM
    )
    kp, vp = pl.pallas_call(
        _write_kernel,
        out_shape=(
            jax.ShapeDtypeStruct(kp.shape, kp.dtype),
            jax.ShapeDtypeStruct(vp.shape, vp.dtype),
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(),
            in_specs=[row_spec, row_spec, any_spec, any_spec],
            out_specs=[any_spec, any_spec],
            scratch_shapes=[
                pltpu.VMEM((B, ps, HkD), kp.dtype),
                pltpu.VMEM((B, ps, HkD), vp.dtype),
                pltpu.SemaphoreType.DMA((B,)),
                pltpu.SemaphoreType.DMA((B,)),
                pltpu.SemaphoreType.DMA((B,)),
                pltpu.SemaphoreType.DMA((B,)),
            ],
        ),
        # Flattened input positions incl. the 2 scalar-prefetch args:
        # pids=0 offs=1 k_new=2 v_new=3 k_pages=4 v_pages=5.
        input_output_aliases={4: 0, 5: 1},
        interpret=interpret,
    )(
        page_ids.astype(jnp.int32),
        offsets.astype(jnp.int32),
        kn.astype(kp.dtype),
        vn.astype(vp.dtype),
        kp,
        vp,
    )
    return kp.reshape(N, ps, Hk, D), vp.reshape(N, ps, Hk, D)
