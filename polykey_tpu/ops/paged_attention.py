"""Paged attention: read KV through page-table indirection.

`paged_gather_kv` is the reference implementation (pure jnp): materialize the
per-sequence KV window by gathering whole pages, then run the standard masked
attention. Correct everywhere, but it streams the full gathered window
through HBM every step — the Pallas decode kernel (paged_attention_decode
with use_kernel=True, task: ops/paged_attention_kernel.py) replaces the
gather with per-page DMA so only valid pages move.

Page-table convention (engine/kv_cache.py): page_tables[b, j] is the page id
holding positions [j*page_size, (j+1)*page_size); unused tail entries point
at the reserved garbage page 0 and are excluded by the position mask.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def paged_gather_kv(
    k_pages: jax.Array,       # [num_pages, page_size, Hk, D]
    v_pages: jax.Array,
    page_tables: jax.Array,   # [B, P] int32
    ) -> tuple[jax.Array, jax.Array]:
    """Materialize [B, P*page_size, Hk, D] K/V windows from the pools."""
    B, P = page_tables.shape
    _, page_size, Hk, D = k_pages.shape
    k = k_pages[page_tables]  # [B, P, page_size, Hk, D]
    v = v_pages[page_tables]
    return (
        k.reshape(B, P * page_size, Hk, D),
        v.reshape(B, P * page_size, Hk, D),
    )


def paged_attention(
    q: jax.Array,             # [B, T, Hq, D]
    k_pages: jax.Array,       # [num_pages, page_size, Hk, D]
    v_pages: jax.Array,
    page_tables: jax.Array,   # [B, P]
    q_positions: jax.Array,   # [B, T] absolute positions of the queries
    *,
    scale: float,
    logit_softcap: Optional[float] = None,
    window: Optional[jax.Array] = None,
    mesh=None,
) -> jax.Array:
    """Attention over paged KV; returns [B, T, Hq, D].

    Slot j of the gathered window holds position j, so the absolute-position
    causal mask simultaneously hides unwritten slots and garbage-page tails —
    which also makes the gathered window a valid input for the blockwise
    flash kernel (ops/flash_attention.py): on TPU at prefill widths it takes
    the O(T·D + S·D)-traffic path instead of materializing [.., T, S] logits;
    off-TPU / tiny shapes it falls back to the reference mask internally.
    """
    from .flash_attention import flash_attention

    k, v = paged_gather_kv(k_pages, v_pages, page_tables)
    return flash_attention(
        q, k, v, q_positions,
        scale=scale, logit_softcap=logit_softcap, window=window, mesh=mesh,
    )


def paged_write(
    k_pages: jax.Array,       # [num_pages, page_size, Hk, D]
    v_pages: jax.Array,
    k_new: jax.Array,         # [B, T, Hk, D]
    v_new: jax.Array,
    page_tables: jax.Array,   # [B, P]
    positions: jax.Array,     # [B, T] absolute position of each new token
) -> tuple[jax.Array, jax.Array]:
    """Scatter new KV into their pages at (page_table[pos // ps], pos % ps)."""
    page_size = k_pages.shape[1]
    batch_idx = jnp.arange(page_tables.shape[0], dtype=jnp.int32)[:, None]
    page_ids = page_tables[batch_idx, positions // page_size]   # [B, T]
    offsets = positions % page_size                             # [B, T]
    k_pages = k_pages.at[page_ids, offsets].set(k_new)
    v_pages = v_pages.at[page_ids, offsets].set(v_new)
    return k_pages, v_pages
