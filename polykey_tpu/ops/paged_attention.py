"""Paged attention: read KV through page-table indirection.

`paged_gather_kv` is the reference implementation (pure jnp): materialize the
per-sequence KV window by gathering whole pages, then run the standard masked
attention. Correct everywhere, but it streams the full gathered window
through HBM every step — the Pallas decode kernel (paged_attention_decode
with use_kernel=True, task: ops/paged_attention_kernel.py) replaces the
gather with per-page DMA so only valid pages move.

Page-table convention (engine/kv_cache.py): page_tables[b, j] is the page id
holding positions [j*page_size, (j+1)*page_size); unused tail entries point
at the reserved garbage page 0 and are excluded by the position mask.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def paged_gather_kv(
    k_pages: jax.Array,       # [num_pages, page_size, Hk, D]
    v_pages: jax.Array,
    page_tables: jax.Array,   # [B, P] int32
    ) -> tuple[jax.Array, jax.Array]:
    """Materialize [B, P*page_size, Hk, D] K/V windows from the pools."""
    B, P = page_tables.shape
    _, page_size, Hk, D = k_pages.shape
    k = k_pages[page_tables]  # [B, P, page_size, Hk, D]
    v = v_pages[page_tables]
    return (
        k.reshape(B, P * page_size, Hk, D),
        v.reshape(B, P * page_size, Hk, D),
    )


def paged_attention(
    q: jax.Array,             # [B, T, Hq, D]
    k_pages: jax.Array,       # [num_pages, page_size, Hk, D]
    v_pages: jax.Array,
    page_tables: jax.Array,   # [B, P]
    q_positions: jax.Array,   # [B, T] absolute positions of the queries
    *,
    scale: float,
    logit_softcap: Optional[float] = None,
    window: Optional[jax.Array] = None,
    mesh=None,
) -> jax.Array:
    """Attention over paged KV; returns [B, T, Hq, D].

    Slot j of the gathered window holds position j, so the absolute-position
    causal mask simultaneously hides unwritten slots and garbage-page tails —
    which also makes the gathered window a valid input for the blockwise
    flash kernel (ops/flash_attention.py): on TPU at prefill widths it takes
    the O(T·D + S·D)-traffic path instead of materializing [.., T, S] logits;
    off-TPU / tiny shapes it falls back to the reference mask internally.
    """
    from .flash_attention import flash_attention

    k, v = paged_gather_kv(k_pages, v_pages, page_tables)
    return flash_attention(
        q, k, v, q_positions,
        scale=scale, logit_softcap=logit_softcap, window=window, mesh=mesh,
    )


def paged_write(
    k_pages: jax.Array,       # [num_pages, page_size, Hk, D]
    v_pages: jax.Array,
    k_new: jax.Array,         # [B, T, Hk, D]
    v_new: jax.Array,
    page_tables: jax.Array,   # [B, P]
    positions: jax.Array,     # [B, T] absolute position of each new token
    mesh=None,
) -> tuple[jax.Array, jax.Array]:
    """Write new KV into their pages at (page_table[pos // ps], pos % ps).

    Three paths, fastest applicable wins:
    - T == 1 on TPU: the Pallas DMA write kernel
      (ops/paged_write_kernel.py) — per-lane row DMAs into the aliased
      pools. The XLA scatter here lowers to a sequential per-row update
      loop that measured ~10 ms/step of a ~21 ms 1B decode step
      (scripts/profile_block_device.py); the kernel makes it ~free.
    - T > 1 with page-aligned consecutive rows (every engine prefill
      chunk: buckets and chunk starts are multiples of page_size): a
      page-granular scatter — T/ps big row updates per lane instead of
      T tiny ones. Picked by a runtime lax.cond so arbitrary callers
      (tests, non-bucket positions) still get exact semantics.
    - otherwise: the per-token XLA scatter.
    """
    page_size = k_pages.shape[1]
    B, T = positions.shape
    P = page_tables.shape[1]
    batch_idx = jnp.arange(B, dtype=jnp.int32)[:, None]
    page_ids = page_tables[batch_idx, positions // page_size]   # [B, T]
    offsets = positions % page_size                             # [B, T]

    if T == 1:
        from .paged_attention_kernel import use_paged_kernel

        Hk, D = k_pages.shape[2], k_pages.shape[3]
        pp = mesh.shape.get("pp", 1) if mesh is not None else 1
        if use_paged_kernel(Hk, D) and pp == 1:
            return _write_decode_kernel(
                k_pages, v_pages, k_new, v_new,
                page_ids[:, 0], offsets[:, 0], mesh,
            )

    def token_scatter(ops):
        kp, vp = ops
        return (
            kp.at[page_ids, offsets].set(k_new),
            vp.at[page_ids, offsets].set(v_new),
        )

    if T > 1 and T % page_size == 0:
        n_pg = T // page_size
        consecutive = jnp.all(
            positions == positions[:, :1] + jnp.arange(T, dtype=positions.dtype)
        )
        aligned = jnp.all(positions[:, 0] % page_size == 0) & consecutive

        def page_scatter(ops):
            kp, vp = ops
            first = positions[:, 0] // page_size                 # [B]
            pg_idx = first[:, None] + jnp.arange(n_pg, dtype=jnp.int32)
            pg_ids = jnp.take_along_axis(
                page_tables, jnp.clip(pg_idx, 0, P - 1), axis=1
            )                                                    # [B, n_pg]
            Hk, D = kp.shape[2], kp.shape[3]
            return (
                kp.at[pg_ids].set(k_new.reshape(B, n_pg, page_size, Hk, D)),
                vp.at[pg_ids].set(v_new.reshape(B, n_pg, page_size, Hk, D)),
            )

        return jax.lax.cond(
            aligned, page_scatter, token_scatter, (k_pages, v_pages)
        )

    return token_scatter((k_pages, v_pages))


def _write_decode_kernel(
    k_pages, v_pages, k_new, v_new, page_ids, offsets, mesh
):
    """Dispatch the Pallas write kernel, under shard_map when the mesh
    shards batch (dp) or heads (tp). Pools are replicated over dp/sp, so
    every replica must apply every lane's write: the dp-local updates
    all-gather (tiny — B rows) before the kernel writes the full batch
    into the local head shard. Mirrors paged_attention_decode's specs."""
    from .paged_write_kernel import paged_write_decode_kernel

    dp = mesh.shape.get("dp", 1) if mesh is not None else 1
    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    if dp <= 1 and tp <= 1:
        return paged_write_decode_kernel(
            k_pages, v_pages, k_new, v_new, page_ids, offsets
        )
    B, Hk = k_new.shape[0], k_new.shape[2]
    if B % dp or Hk % tp:
        # Same curated error as the read kernel (paged_attention_kernel
        # .py) — never let uneven sharding surface as an opaque shard_map
        # trace error with no pointer at the real cause.
        raise ValueError(
            f"paged write kernel on mesh: B={B} % dp={dp} and "
            f"Hk={Hk} % tp={tp} must divide evenly"
        )

    from jax.sharding import PartitionSpec as Pspec

    def inner(kp, vp, kn, vn, pid, off):
        if dp > 1:
            kn = jax.lax.all_gather(kn, "dp", axis=0, tiled=True)
            vn = jax.lax.all_gather(vn, "dp", axis=0, tiled=True)
            pid = jax.lax.all_gather(pid, "dp", axis=0, tiled=True)
            off = jax.lax.all_gather(off, "dp", axis=0, tiled=True)
        return paged_write_decode_kernel(kp, vp, kn, vn, pid, off)

    sm = jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            Pspec(None, None, "tp", None),     # k_pages
            Pspec(None, None, "tp", None),     # v_pages
            Pspec("dp", None, "tp", None),     # k_new [B, 1, Hk, D]
            Pspec("dp", None, "tp", None),     # v_new
            Pspec("dp"),                       # page_ids
            Pspec("dp"),                       # offsets
        ),
        out_specs=(
            Pspec(None, None, "tp", None),
            Pspec(None, None, "tp", None),
        ),
        check_vma=False,
    )
    return sm(k_pages, v_pages, k_new, v_new, page_ids, offsets)
