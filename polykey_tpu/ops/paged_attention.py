"""Paged attention: read KV through page-table indirection.

`paged_gather_kv` is the reference implementation (pure jnp): materialize the
per-sequence KV window by gathering whole pages, then run the standard masked
attention. Correct everywhere, but it streams the full gathered window
through HBM every step — the Pallas decode kernel (paged_attention_decode
with use_kernel=True, task: ops/paged_attention_kernel.py) replaces the
gather with per-page DMA so only valid pages move.

Page-table convention (engine/kv_cache.py): page_tables[b, j] is the page id
holding positions [j*page_size, (j+1)*page_size); unused tail entries point
at the reserved garbage page 0 and are excluded by the position mask.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from ..compat import shard_map


def paged_gather_kv(
    k_pages: jax.Array,       # [num_pages, page_size, Hk, D]
    v_pages: jax.Array,
    page_tables: jax.Array,   # [B, P] int32
    ) -> tuple[jax.Array, jax.Array]:
    """Materialize [B, P*page_size, Hk, D] K/V windows from the pools."""
    B, P = page_tables.shape
    _, page_size, Hk, D = k_pages.shape
    k = k_pages[page_tables]  # [B, P, page_size, Hk, D]
    v = v_pages[page_tables]
    return (
        k.reshape(B, P * page_size, Hk, D),
        v.reshape(B, P * page_size, Hk, D),
    )


def paged_attention(
    q: jax.Array,             # [B, T, Hq, D]
    k_pages: jax.Array,       # [num_pages, page_size, Hk, D]
    v_pages: jax.Array,
    page_tables: jax.Array,   # [B, P]
    q_positions: jax.Array,   # [B, T] absolute positions of the queries
    *,
    scale: float,
    logit_softcap: Optional[float] = None,
    window: Optional[jax.Array] = None,
    mesh=None,
) -> jax.Array:
    """Attention over paged KV; returns [B, T, Hq, D].

    Slot j of the gathered window holds position j, so the absolute-position
    causal mask simultaneously hides unwritten slots and garbage-page tails —
    which also makes the gathered window a valid input for the blockwise
    flash kernel (ops/flash_attention.py): on TPU at prefill widths it takes
    the O(T·D + S·D)-traffic path instead of materializing [.., T, S] logits;
    off-TPU / tiny shapes it falls back to the reference mask internally.
    """
    from .flash_attention import flash_attention

    if isinstance(k_pages, tuple):
        # int8 KV pools (values, scales): gather both, dequantize into
        # the compute dtype — the dequant is an elementwise producer XLA
        # fuses into the window consumers, and the pool-side HBM read
        # stays int8.
        (kq, ks_pool), (vq, vs_pool) = k_pages, v_pages
        k, v = paged_gather_kv(kq, vq, page_tables)
        B, P = page_tables.shape
        ps, Hk = kq.shape[1], kq.shape[2]
        ks = ks_pool[page_tables].reshape(B, P * ps, Hk)
        vs = vs_pool[page_tables].reshape(B, P * ps, Hk)
        k = dequantize_kv(k, ks, q.dtype)
        v = dequantize_kv(v, vs, q.dtype)
    else:
        k, v = paged_gather_kv(k_pages, v_pages, page_tables)
    return flash_attention(
        q, k, v, q_positions,
        scale=scale, logit_softcap=logit_softcap, window=window, mesh=mesh,
    )


def quantize_kv_rows(rows: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-(token, head) int8 quantization of KV rows
    [..., Hk, D] → (int8 values, bf16 scales [..., Hk]).

    Quantization divides by the bf16-ROUNDED scale — the value dequant
    will actually multiply by — so the scale's own rounding adds no
    systematic error (only the unavoidable LSB from the bf16 absmax
    step, vs up to 127·|Δscale| if q were computed from the f32 scale)."""
    absmax = jnp.max(jnp.abs(rows.astype(jnp.float32)), axis=-1)
    scale = (jnp.maximum(absmax, 1e-8) / 127.0).astype(jnp.bfloat16)
    q = jnp.clip(
        jnp.round(rows.astype(jnp.float32) / scale[..., None].astype(jnp.float32)),
        -127, 127,
    ).astype(jnp.int8)
    return q, scale


def dequantize_kv(values: jax.Array, scales: jax.Array, dtype) -> jax.Array:
    """[..., Hk, D] int8 + [..., Hk] scales → dtype."""
    return (values.astype(dtype) * scales[..., None].astype(dtype))


def paged_write(
    k_pages,                  # [num_pages, page_size, Hk, D], or a
                              # (values, scales) pair for int8 KV pools
    v_pages,
    k_new: jax.Array,         # [B, T, Hk, D]
    v_new: jax.Array,
    page_tables: jax.Array,   # [B, P]
    positions: jax.Array,     # [B, T] absolute position of each new token
    mesh=None,
):
    """Write new KV into their pages at (page_table[pos // ps], pos % ps).

    With int8 KV pools (`k_pages`/`v_pages` as (values, scales) pairs —
    engine/kv_cache.py PagedKV.quantized) the rows quantize at write time
    and the scale pools [N, ps, Hk] take the same write path as the data.

    Three paths, fastest applicable wins:
    - T == 1 on TPU: the Pallas DMA write kernel
      (ops/paged_write_kernel.py) — per-lane page RMW into the aliased
      pools. The XLA scatter here lowers to a sequential per-row update
      loop that measured ~10 ms/step of a ~21 ms 1B decode step
      (scripts/profile_block_device.py); the kernel makes it ~free.
    - T > 1 with page-aligned consecutive rows (every engine prefill
      chunk: buckets and chunk starts are multiples of page_size): a
      page-granular scatter — T/ps big row updates per lane instead of
      T tiny ones. Picked by a runtime lax.cond so arbitrary callers
      (tests, non-bucket positions) still get exact semantics.
    - otherwise: the per-token XLA scatter.
    """
    quantized = isinstance(k_pages, tuple)
    if quantized:
        (kq, ks_pool), (vq, vs_pool) = k_pages, v_pages
        k8, k_s = quantize_kv_rows(k_new)
        v8, v_s = quantize_kv_rows(v_new)
        # (pool, rows) pairs sharing one (page, offset) index layout.
        writes = [(kq, k8), (vq, v8),
                  (ks_pool, k_s.astype(ks_pool.dtype)),
                  (vs_pool, v_s.astype(vs_pool.dtype))]
        data_pool = kq
    else:
        writes = [(k_pages, k_new), (v_pages, v_new)]
        data_pool = k_pages

    page_size = data_pool.shape[1]
    B, T = positions.shape
    P = page_tables.shape[1]
    batch_idx = jnp.arange(B, dtype=jnp.int32)[:, None]
    page_ids = page_tables[batch_idx, positions // page_size]   # [B, T]
    offsets = positions % page_size                             # [B, T]

    def repack(pools):
        if quantized:
            return (pools[0], pools[2]), (pools[1], pools[3])
        return pools[0], pools[1]

    if T == 1:
        from .paged_attention_kernel import (
            use_paged_kernel,
            use_quantized_paged_kernel,
        )

        Hk, D = data_pool.shape[2], data_pool.shape[3]
        pp = mesh.shape.get("pp", 1) if mesh is not None else 1
        gate = use_quantized_paged_kernel if quantized else use_paged_kernel
        if gate(Hk, D) and pp == 1:
            return repack(_write_decode_kernel(
                writes, page_ids[:, 0], offsets[:, 0], mesh,
            ))

    def token_scatter(pools):
        return tuple(
            p.at[page_ids, offsets].set(r)
            for p, (_, r) in zip(pools, writes)
        )

    pools_in = tuple(p for p, _ in writes)
    if T > 1 and T % page_size == 0:
        n_pg = T // page_size
        consecutive = jnp.all(
            positions == positions[:, :1] + jnp.arange(T, dtype=positions.dtype)
        )
        aligned = jnp.all(positions[:, 0] % page_size == 0) & consecutive

        def page_scatter(pools):
            first = positions[:, 0] // page_size                 # [B]
            pg_idx = first[:, None] + jnp.arange(n_pg, dtype=jnp.int32)
            pg_ids = jnp.take_along_axis(
                page_tables, jnp.clip(pg_idx, 0, P - 1), axis=1
            )                                                    # [B, n_pg]
            return tuple(
                p.at[pg_ids].set(
                    r.reshape(B, n_pg, page_size, *r.shape[2:])
                )
                for p, (_, r) in zip(pools, writes)
            )

        return repack(jax.lax.cond(
            aligned, page_scatter, token_scatter, pools_in
        ))

    return repack(token_scatter(pools_in))


def _write_decode_kernel(writes, page_ids, offsets, mesh):
    """Dispatch the Pallas write kernel over (pool, rows) pairs, under
    shard_map when the mesh shards batch (dp) or heads (tp). Pools are
    replicated over dp/sp, so every replica must apply every lane's
    write: the dp-local updates all-gather (tiny — B rows) before the
    kernel writes the full batch into the local head shard. Mirrors
    paged_attention_decode's specs. Data pools are [N, ps, Hk, D]; int8
    KV adds scale pools [N, ps, Hk] — the head axis is last there, so
    its tp spec sits on the final dim."""
    from .paged_write_kernel import paged_write_rows_kernel

    pools = [p for p, _ in writes]
    rows = [r for _, r in writes]
    dp = mesh.shape.get("dp", 1) if mesh is not None else 1
    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    if dp <= 1 and tp <= 1:
        return paged_write_rows_kernel(pools, rows, page_ids, offsets)
    B, Hk = rows[0].shape[0], rows[0].shape[2]
    if B % dp or Hk % tp:
        # Same curated error as the read kernel (paged_attention_kernel
        # .py) — never let uneven sharding surface as an opaque shard_map
        # trace error with no pointer at the real cause.
        raise ValueError(
            f"paged write kernel on mesh: B={B} % dp={dp} and "
            f"Hk={Hk} % tp={tp} must divide evenly"
        )

    from jax.sharding import PartitionSpec as Pspec

    def pool_spec(p):
        # head axis: dim 2 of [N, ps, Hk, D]; dim 2 (last) of [N, ps, Hk]
        return (Pspec(None, None, "tp", None) if p.ndim == 4
                else Pspec(None, None, "tp"))

    def row_spec(r):
        return (Pspec("dp", None, "tp", None) if r.ndim == 4
                else Pspec("dp", None, "tp"))

    def inner(pools_l, rows_l, pid, off):
        if dp > 1:
            rows_l = [
                jax.lax.all_gather(r, "dp", axis=0, tiled=True)
                for r in rows_l
            ]
            pid = jax.lax.all_gather(pid, "dp", axis=0, tiled=True)
            off = jax.lax.all_gather(off, "dp", axis=0, tiled=True)
        return paged_write_rows_kernel(pools_l, rows_l, pid, off)

    sm = shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            [pool_spec(p) for p in pools],
            [row_spec(r) for r in rows],
            Pspec("dp"),
            Pspec("dp"),
        ),
        out_specs=tuple(pool_spec(p) for p in pools),
        check_vma=False,
    )
    return sm(pools, rows, page_ids, offsets)
