"""Pallas paged-attention decode kernel (TPU).

The gather path (ops/paged_attention.py) materializes each sequence's KV
window in HBM every decode step: `k_pages[page_tables]` reads the pages AND
writes a [B, P·page_size, Hk, D] copy, so the cache crosses HBM twice. This
kernel reads each valid page exactly once: one grid program per sequence,
a double-buffered DMA loop streams that sequence's pages HBM → VMEM while
the previous block's attention accumulates into online-softmax state
(running max m, denominator l, fp32 accumulator) — the same recurrence as
ops/flash_attention.py.

Pages stream in GROUPS of `pages_per_block` (G): each buffer slot holds G
pages, whose DMAs are all in flight together, so per-page DMA latency
(~µs for a 32 KB page — the dominant cost of a one-page-at-a-time loop)
amortizes G× and the per-group attention block is [G·page_size] wide —
MXU-shaped work instead of page_size-sliver matmuls. G consecutive page
table entries cover contiguous positions, so the group's mask is one iota.

Invalid page-table tails (the reserved garbage page 0) are never DMA'd:
the loop bound is ceil((position+1)/page_size), data-dependent per
sequence, and Gemma-2 sliding-window layers also skip pages wholly below
position - window. Buffer regions for pages outside [lo, hi) hold stale
VMEM; their logits are masked, and V is zeroed on those rows so masked
weights never multiply uninitialized data (0·NaN would poison the
accumulator).

The kernel emits UNNORMALIZED online-softmax state (acc, m, l) over a
page sub-range: the wrapper normalizes locally, or — context-parallel
decode, mesh sp>1 — each sp shard covers a contiguous slice of every
sequence's pages and partial states merge via pmax/psum before
normalizing (see paged_attention_decode).

Covers GQA, logit soft-capping, and dynamic sliding windows; falls back to
the gather implementation off-TPU (`use_kernel` dispatch in
paged_attention_decode, with the POLYKEY_DISABLE_PAGED_KERNEL
kill-switch).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from ..compat import shard_map, tpu_compiler_params

_NEG_INF = -1e30


def _kernel(
    # scalar prefetch
    pt_ref,        # [B, P] int32 page tables
    pos_ref,       # [B] int32 decode position per sequence
    win_ref,       # [1] int32 sliding window (<=0 → global)
    rng_ref,       # [2] int32 page sub-range [rlo, rhi) — CP shard's slice
    # then, positionally (arity varies with `quantized`):
    # inputs: q [1, Hq, D] VMEM block; k/v pages [N, ps, Hk·D] HBM
    #         (heads folded into lanes; manual DMA); quantized adds
    #         ks/vs scale pages [N, ps, Hk] HBM (bf16)
    # outputs: unnormalized online-softmax state — the wrapper
    #         normalizes, or merges across CP shards first (acc/l scale
    #         by exp(m - m_global)): acc [1, Hq, D] f32, m/l
    #         [1, Hq, MINOR] f32
    # scratch: k/v bufs [2, G, ps, Hk·D] VMEM (+ [2, G, ps, Hk] scale
    #         bufs when quantized) and matching DMA semaphores (2, G)
    *refs,
    scale: float,
    logit_softcap: Optional[float],
    page_size: int,
    num_tables: int,   # P — static max pages per sequence
    groups: int,       # Hq // Hk
    pages_per_block: int,   # G — pages per buffer slot (DMAs in flight)
    quantized: bool = False,
):
    if quantized:
        (q_ref, k_pages_ref, v_pages_ref, ks_pages_ref, vs_pages_ref,
         acc_ref, m_ref, l_ref,
         k_buf, v_buf, ks_buf, vs_buf,
         k_sems, v_sems, ks_sems, vs_sems) = refs
    else:
        (q_ref, k_pages_ref, v_pages_ref,
         acc_ref, m_ref, l_ref,
         k_buf, v_buf, k_sems, v_sems) = refs
        ks_pages_ref = vs_pages_ref = None
        ks_buf = vs_buf = ks_sems = vs_sems = None
    b = pl.program_id(0)
    q_pos = pos_ref[b]
    window = win_ref[0]
    G = pages_per_block
    n_blocks = (num_tables + G - 1) // G           # static

    # Pages [lo, hi) hold positions visible to this query, intersected
    # with this shard's page sub-range (context-parallel decode: each sp
    # shard covers a contiguous page range; [0, P) when unsharded).
    # Blocks [blo, bhi) are the G-page groups overlapping that range.
    hi = jnp.minimum(jax.lax.div(q_pos, page_size) + 1, rng_ref[1])
    lo = jnp.where(
        window > 0,
        jnp.maximum(jax.lax.div(q_pos - window + 1, page_size), 0),
        0,
    )
    lo = jnp.maximum(lo, rng_ref[0])
    blo = jax.lax.div(lo, G)
    bhi = jax.lax.div(hi + G - 1, G)

    def page_dma(p, slot, j, pages_ref, buf, sems):
        return pltpu.make_async_copy(
            pages_ref.at[pt_ref[b, p]], buf.at[slot, j], sems.at[slot, j]
        )

    def start_block(blk, slot):
        # All G page DMAs of the group go out together (latency overlaps);
        # pages outside [lo, hi) are skipped — their rows are masked below.
        for j in range(G):
            p = blk * G + j

            @pl.when((p >= lo) & (p < hi))
            def _go(p=p, j=j):
                page_dma(p, slot, j, k_pages_ref, k_buf, k_sems).start()
                page_dma(p, slot, j, v_pages_ref, v_buf, v_sems).start()
                if quantized:
                    page_dma(p, slot, j, ks_pages_ref, ks_buf,
                             ks_sems).start()
                    page_dma(p, slot, j, vs_pages_ref, vs_buf,
                             vs_sems).start()

    def wait_block(blk, slot):
        for j in range(G):
            p = blk * G + j

            @pl.when((p >= lo) & (p < hi))
            def _wait(p=p, j=j):
                page_dma(p, slot, j, k_pages_ref, k_buf, k_sems).wait()
                page_dma(p, slot, j, v_pages_ref, v_buf, v_sems).wait()
                if quantized:
                    page_dma(p, slot, j, ks_pages_ref, ks_buf,
                             ks_sems).wait()
                    page_dma(p, slot, j, vs_pages_ref, vs_buf,
                             vs_sems).wait()

    @pl.when((lo < hi) & (blo < bhi))
    def _first():
        start_block(blo, blo % 2)

    Hq, D = q_ref.shape[1], q_ref.shape[2]
    W = G * page_size                               # group window width
    q = q_ref[0].astype(jnp.float32) * scale                  # [Hq, D]

    def body(blk, carry):
        m, l, acc = carry

        def run(carry):
            m, l, acc = carry
            slot = blk % 2

            @pl.when(blk + 1 < bhi)
            def _next():
                start_block(blk + 1, (blk + 1) % 2)

            wait_block(blk, slot)
            # Buffers hold [G, ps, Hk*D] (heads folded into lanes so the
            # DMA slice stays 128-aligned for any head_dim); the G pages
            # cover contiguous positions, so they flatten to one [W, Hk*D]
            # block with a single iota mask.
            k = k_buf[slot].reshape(W, -1)
            v = v_buf[slot].reshape(W, -1)
            D = q.shape[1]
            num_kv = k.shape[1] // D
            if quantized:
                # Per-(position, head) dequant scales for this group —
                # applied on the per-head slices below, so the int8
                # pages stream at half the bf16 bytes and dequant rides
                # the matmul operand load.
                ks2 = ks_buf[slot].reshape(W, num_kv).astype(jnp.float32)
                vs2 = vs_buf[slot].reshape(W, num_kv).astype(jnp.float32)

            kv_pos1 = blk * W + jax.lax.broadcasted_iota(
                jnp.int32, (W, 1), dimension=0
            )                                                 # [W, 1]
            valid1 = (kv_pos1 >= lo * page_size) & (kv_pos1 < hi * page_size)
            # Rows of pages that were never DMA'd hold stale VMEM; zero V
            # there so masked-out weights cannot multiply NaN garbage.
            v = jnp.where(valid1, v.astype(jnp.float32), 0.0)
            if quantized:
                # The V-side matmul SUMS over rows, so stale scale rows
                # must be zeroed like v itself — 0·NaN from a stale bf16
                # pattern would poison every output. K-side NaNs stay
                # confined to their own masked logit column.
                vs2 = jnp.where(valid1, vs2, 0.0)

            # Mosaic lowers only plain 2D matmuls — unroll over kv heads
            # (q head h ↔ kv head h//groups, heads grouped contiguously).
            def k_head(h):
                kk = k[:, h * D:(h + 1) * D].astype(jnp.float32)
                if quantized:
                    kk = kk * ks2[:, h:h + 1]
                return kk

            s = jnp.concatenate(
                [
                    jax.lax.dot_general(
                        q[h * groups:(h + 1) * groups],       # [g, D]
                        k_head(h),
                        dimension_numbers=(((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                    for h in range(num_kv)
                ],
                axis=0,
            )                                                 # [Hq, W]
            if logit_softcap is not None:
                s = logit_softcap * jnp.tanh(s / logit_softcap)

            kv_pos = blk * W + jax.lax.broadcasted_iota(
                jnp.int32, (Hq, W), dimension=1
            )
            mask = kv_pos <= q_pos
            mask &= (window <= 0) | (kv_pos > q_pos - window)
            mask &= valid1.reshape(1, W)
            s = jnp.where(mask, s, _NEG_INF)

            m_cur = jnp.max(s, axis=1, keepdims=True)         # [Hq, 1]
            m_new = jnp.maximum(m, m_cur)
            pexp = jnp.where(mask, jnp.exp(s - m_new), 0.0)   # [Hq, W]
            corr = jnp.exp(m - m_new)
            l_new = corr * l + jnp.sum(pexp, axis=1, keepdims=True)
            def v_head(h):
                vv = v[:, h * D:(h + 1) * D]
                if quantized:
                    vv = vv * vs2[:, h:h + 1]
                return vv

            pv = jnp.concatenate(
                [
                    jax.lax.dot_general(
                        pexp[h * groups:(h + 1) * groups],    # [g, W]
                        v_head(h),
                        dimension_numbers=(((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                    for h in range(num_kv)
                ],
                axis=0,
            )                                                 # [Hq, D]
            acc_new = acc * corr + pv
            return m_new, l_new, acc_new

        return jax.lax.cond(
            (lo < hi) & (blk >= blo) & (blk < bhi), run, lambda c: c, carry
        )

    m0 = jnp.full((Hq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hq, 1), jnp.float32)
    acc0 = jnp.zeros((Hq, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))

    acc_ref[0] = acc
    minor = m_ref.shape[2]
    m_ref[0] = jnp.broadcast_to(m, (Hq, minor))
    l_ref[0] = jnp.broadcast_to(l, (Hq, minor))


_STAT_MINOR = 128   # lane width for the m/l stat outputs (tile-aligned)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "logit_softcap", "interpret", "pages_per_block"),
)
def _decode_call(
    q: jax.Array,             # [B, Hq, D]
    k_pages,                  # [N, ps, Hk, D], or (values, scales) pairs
    v_pages,                  #   for int8 KV (scales [N, ps, Hk] bf16)
    page_tables: jax.Array,   # [B, P] int32
    positions: jax.Array,     # [B] int32
    window: jax.Array,        # [1] int32
    page_range: jax.Array,    # [2] int32 — page sub-range [rlo, rhi)
    *,
    scale: float,
    logit_softcap: Optional[float],
    interpret: bool,
    pages_per_block: int = 0,   # 0 → auto
):
    """Returns UNNORMALIZED online-softmax state (acc [B,Hq,D] f32,
    m [B,Hq,1], l [B,Hq,1]) over the pages in `page_range` — the caller
    normalizes, or first merges partial states across context-parallel
    shards (acc/l scale by exp(m - m_global))."""
    quantized = isinstance(k_pages, tuple)
    if quantized:
        (k_pages, ks_pages), (v_pages, vs_pages) = k_pages, v_pages
    B, Hq, D = q.shape
    N, ps, Hk, _ = k_pages.shape
    P = page_tables.shape[1]
    if pages_per_block <= 0:
        # Target ~128 positions per block (one MXU tile of rows) with all
        # of a block's page DMAs in flight together; bounded by the table.
        pages_per_block = max(1, min(P, 128 // ps if ps <= 128 else 1))
    G = min(pages_per_block, P)
    # Fold heads into the lane dimension: [N, ps, Hk·D] keeps every DMA
    # slice 128-aligned regardless of head_dim (a contiguous reshape).
    k_pages = k_pages.reshape(N, ps, Hk * D)
    v_pages = v_pages.reshape(N, ps, Hk * D)

    kernel = functools.partial(
        _kernel,
        scale=scale,
        logit_softcap=logit_softcap,
        page_size=ps,
        num_tables=P,
        groups=Hq // Hk,
        pages_per_block=G,
        quantized=quantized,
    )
    stat_spec = pl.BlockSpec((1, Hq, _STAT_MINOR), lambda b, *_: (b, 0, 0))
    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    in_specs = [
        pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
        any_spec,
        any_spec,
    ]
    scratch = [
        pltpu.VMEM((2, G, ps, Hk * D), k_pages.dtype),
        pltpu.VMEM((2, G, ps, Hk * D), k_pages.dtype),
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        in_specs += [any_spec, any_spec]
        scratch += [
            pltpu.VMEM((2, G, ps, Hk), ks_pages.dtype),
            pltpu.VMEM((2, G, ps, Hk), vs_pages.dtype),
        ]
        operands = [q, k_pages, v_pages, ks_pages, vs_pages]
    n_sems = 4 if quantized else 2
    scratch += [pltpu.SemaphoreType.DMA((2, G))] * n_sems
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
            stat_spec,
            stat_spec,
        ],
        scratch_shapes=scratch,
    )
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, D), jnp.float32),
            jax.ShapeDtypeStruct((B, Hq, _STAT_MINOR), jnp.float32),
            jax.ShapeDtypeStruct((B, Hq, _STAT_MINOR), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(
        page_tables.astype(jnp.int32),
        positions.astype(jnp.int32),
        window,
        page_range.astype(jnp.int32),
        *operands,
    )
    return acc, m[..., :1], l[..., :1]


def use_quantized_paged_kernel(num_kv_heads: int, head_dim: int) -> bool:
    """Gate for the int8-KV kernel paths (read dequant stage + scale-page
    writes): same geometry rule as the data pools, plus the dedicated
    POLYKEY_DISABLE_KV_KERNEL kill-switch — the scale-page DMAs
    ([ps, Hk], minor dim far below lane width) are a separate Mosaic
    lowering surface, and a regression there must be containable without
    taking the WORKING fp kernels down with it (the quantized fallback
    is the int8 gather/scatter, still half the bf16 bytes)."""
    import os

    if os.environ.get("POLYKEY_DISABLE_KV_KERNEL", "").lower() in ("1", "true"):
        return False
    return use_paged_kernel(num_kv_heads, head_dim)


def use_paged_kernel(num_kv_heads: int, head_dim: int) -> bool:
    """The DMA kernel needs TPU hardware; the folded head-lane dimension
    (num_kv_heads · head_dim) must be 128-aligned for DMA tiling.
    POLYKEY_DISABLE_PAGED_KERNEL=1 is the operational kill-switch: the
    gather path serves every geometry, so a kernel-compile regression on
    new hardware must never take the whole TPU path down."""
    import os

    if os.environ.get("POLYKEY_DISABLE_PAGED_KERNEL", "").lower() in ("1", "true"):
        return False
    return jax.default_backend() == "tpu" and (num_kv_heads * head_dim) % 128 == 0


def paged_attention_decode(
    q: jax.Array,             # [B, 1, Hq, D] (single decode step)
    k_pages: jax.Array,       # [N, ps, Hk, D]
    v_pages: jax.Array,
    page_tables: jax.Array,   # [B, P]
    q_positions: jax.Array,   # [B, 1] absolute positions
    *,
    scale: float,
    logit_softcap: Optional[float] = None,
    window: Optional[jax.Array] = None,
    interpret: bool = False,
    force_kernel: bool = False,
    pages_per_block: int = 0,   # 0 → auto (~128 positions per block)
    mesh=None,                  # serving mesh → shard_map the kernel
) -> jax.Array:
    """Decode-step paged attention; returns [B, 1, Hq, D].

    Same contract as ops/paged_attention.paged_attention restricted to T=1.

    With a mesh whose dp/tp/sp extents exceed 1, the kernel runs under
    shard_map: batch (and page tables/positions) shard over dp, heads
    over tp — the engine's layout (parallel/sharding.py: pools
    P(None, None, 'tp', None), decode batch over dp). GSPMD cannot
    partition an opaque pallas_call, so without this it would all-gather
    the head-sharded pools. Attention is embarrassingly parallel over
    batch and (GQA-aligned) heads, so each shard runs the same kernel on
    its slice. sp > 1 context-parallelizes the page axis: each sp shard
    covers a contiguous page sub-range of every sequence (pools are
    sp-replicated — this shards the attention READS) and the partial
    online-softmax states merge via pmax/psum over sp. ep stays an
    unmentioned axis with replicated operands.
    """
    quantized = isinstance(k_pages, tuple)
    B = q.shape[0]
    data_pool = k_pages[0] if quantized else k_pages
    Hk, D = data_pool.shape[2], data_pool.shape[3]

    gate = use_quantized_paged_kernel if quantized else use_paged_kernel
    if not (force_kernel or interpret or gate(Hk, D)):
        from .paged_attention import paged_attention

        return paged_attention(
            q, k_pages, v_pages, page_tables, q_positions,
            scale=scale, logit_softcap=logit_softcap, window=window,
        )

    if window is None:
        win = jnp.zeros((1,), jnp.int32)
    else:
        win = jnp.asarray(window, jnp.int32).reshape(1)

    inner = functools.partial(
        _decode_call,
        scale=scale, logit_softcap=logit_softcap, interpret=interpret,
        pages_per_block=pages_per_block,
    )
    P_tables = page_tables.shape[1]

    def _normalize(acc, l, dtype):
        return (acc / jnp.maximum(l, 1e-9)).astype(dtype)

    dp = mesh.shape.get("dp", 1) if mesh is not None else 1
    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    if (dp > 1 or tp > 1 or sp > 1) and mesh.shape.get("pp", 1) > 1:
        # Under pp the per-layer pool slice is stage-local, not replicated
        # across pp — the shard_map specs below would be wrong. The gather
        # path is GSPMD-partitionable as-is, so pp>1 meshes take it.
        # Decided position (PERF.md "pp in serving"): pp is a capacity/
        # prefill axis; the ~3× attention-read traffic here is accepted,
        # and >HBM models should serve tp(+sp)-first instead.
        from .paged_attention import paged_attention

        return paged_attention(
            q, k_pages, v_pages, page_tables, q_positions,
            scale=scale, logit_softcap=logit_softcap, window=window,
        )
    if dp > 1 or tp > 1 or sp > 1:
        if B % dp or Hk % tp or q.shape[2] % tp:
            # Never fall through to an unwrapped pallas_call on sharded
            # operands — GSPMD would all-gather the head-sharded pools
            # every layer/step (or fail Mosaic compilation) with no
            # pointer at the real cause. The engine validates these up
            # front; direct callers get the explicit error.
            raise ValueError(
                f"paged decode kernel on mesh: B={B} %% dp={dp}, "
                f"Hk={Hk} / Hq={q.shape[2]} %% tp={tp} must divide evenly"
            )
        from jax.sharding import PartitionSpec as P

        def inner_sm(q2, kp2, vp2, pt2, pos2, win2):
            # Context-parallel decode: each sp shard covers a contiguous
            # page sub-range of every sequence (pools are sp-replicated,
            # so this shards the attention READS — the long-context
            # bandwidth bound — sp-fold), then partial online-softmax
            # states merge with a max/psum pair. sp=1 degenerates to the
            # full range and no collectives.
            if sp > 1:
                s = jax.lax.axis_index("sp")
                chunk = -(-P_tables // sp)
                rlo = (s * chunk).astype(jnp.int32)
                rhi = jnp.minimum(P_tables, rlo + chunk).astype(jnp.int32)
                rng = jnp.stack([rlo, rhi])
            else:
                rng = jnp.array([0, P_tables], jnp.int32)
            acc, m, l = inner(q2, kp2, vp2, pt2, pos2, win2, rng)
            if sp > 1:
                m_g = jax.lax.pmax(m, "sp")
                corr = jnp.exp(m - m_g)
                l = jax.lax.psum(l * corr, "sp")
                acc = jax.lax.psum(acc * corr, "sp")
            return _normalize(acc, l, q2.dtype)

        # Quantized pools are (values, scales) pairs: per-arg specs are
        # pytrees matching that structure (scale pools [N, ps, Hk]
        # head-shard on their LAST dim).
        pool_spec = (
            (P(None, None, "tp", None), P(None, None, "tp"))
            if quantized else P(None, None, "tp", None)
        )
        sm = shard_map(
            inner_sm,
            mesh=mesh,
            in_specs=(
                P("dp", "tp", None),          # q [B, Hq, D]
                pool_spec,                    # k_pages
                pool_spec,                    # v_pages
                P("dp", None),                # page_tables
                P("dp"),                      # positions
                P(None),                      # window
            ),
            out_specs=P("dp", "tp", None),
            check_vma=False,
        )
        out = sm(
            q[:, 0], k_pages, v_pages, page_tables,
            q_positions[:, 0].astype(jnp.int32), win,
        )
    else:
        acc, _, l = inner(
            q[:, 0], k_pages, v_pages, page_tables,
            q_positions[:, 0].astype(jnp.int32), win,
            jnp.array([0, P_tables], jnp.int32),
        )
        out = _normalize(acc, l, q.dtype)
    return out[:, None]
