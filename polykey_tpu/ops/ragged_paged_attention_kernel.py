"""Ragged paged attention: one kernel, one dispatch, for mixed
prefill+decode (ISSUE 12; PAPERS.md "Ragged Paged Attention").

The decode kernel (ops/paged_attention_kernel.py) serves ONE token per
sequence per dispatch, and prefill windows take a separate bucketed
gather dispatch — so every engine-loop iteration with admissions pays
two executables and the bucket table's padding. This kernel consumes a
FLAT token stream `[T, Hq, D]` covering both phases at once: each
sequence s owns the contiguous row range
``[seq_starts[s], seq_starts[s] + seq_lens[s])`` (a decode lane is a
ragged sequence of length 1; a prefill chunk is one of length `take`),
attends over its own paged KV window ``[0, kv_lens[s])`` through its
page-table row, and rows outside every range are padding that computes
masked garbage. One grid dimension tiles the token stream in
``token_tile``-row tiles; a tile may span several sequences (scalar-
prefetched ``tile_lo/tile_hi`` name the overlap range), so decode
singles PACK — 48 decode lanes cost ceil(48/tile) programs, not 48.

Per (tile, sequence) the kernel streams that sequence's visible pages
HBM → VMEM in double-buffered GROUPS of ``pages_per_block`` exactly as
the decode kernel does (per-page DMA latency amortizes G×, the group's
attention block is MXU-shaped), accumulating online-softmax state
(running max m, denominator l, fp32 accumulator) per (row, head). Rows
that do not belong to the sequence being processed see all-masked
logits, so their state passes through untouched — the row-disjointness
that makes a multi-sequence tile correct. The query position of row i
in sequence s is ``kv_lens[s] - seq_lens[s] + (i - seq_starts[s])``;
causal masking within a sequence's new tokens, GQA, logit soft-capping,
dynamic sliding windows, and the int8-KV quantized variant (scale-page
DMA + in-kernel dequant) all follow the decode kernel's recurrences.

Output is NORMALIZED ``[T, Hq, D]`` — the ragged batch is not
context-parallel-sharded (the engine's ragged mode serves tp-only
meshes; dp/sp route through the gather path), so no cross-shard
softmax merge is needed.

Falls back to the gather implementation off-TPU (`use_ragged_kernel`
gate, POLYKEY_DISABLE_RAGGED_KERNEL kill-switch — the
POLYKEY_DISABLE_PAGED_KERNEL pattern); the gather path
(`ragged_gather_attention`) reuses ops/paged_attention.paged_attention
with one row per token, which is the bit-identity reference: per token
it is EXACTLY the computation the bucketed engine paths run, so greedy
streams match token-for-token (tests/test_ragged.py pins this).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from ..compat import tpu_compiler_params

_NEG_INF = -1e30

# Default token-tile width: flat streams must be a multiple of this.
# Load-bearing beyond this module — the engine pads its ragged stream
# width against it (engine.py _ragged_width) and graphlint's contracts
# use it; change it HERE, not at call sites.
TOKEN_TILE = 8


def _ragged_kernel(
    # scalar prefetch
    starts_ref,    # [S] int32 flat-stream row where each sequence begins
    lens_ref,      # [S] int32 new-token count per sequence
    kv_ref,        # [S] int32 KV length per sequence (new tokens incl.)
    pt_ref,        # [S, P] int32 page tables
    tile_ref,      # [nT, 2] int32 per-tile sequence overlap [lo, hi)
    win_ref,       # [1] int32 sliding window (<=0 → global)
    # then positionally (arity varies with `quantized`):
    # inputs: q [TT, Hq, D] VMEM tile; k/v pages [N, ps, Hk·D] HBM
    #         (+ ks/vs scale pages [N, ps, Hk] when quantized)
    # outputs: out [TT, Hq, D] f32, normalized
    # scratch: k/v bufs [2, G, ps, Hk·D] (+ scale bufs) + DMA semaphores
    *refs,
    scale: float,
    logit_softcap: Optional[float],
    page_size: int,
    num_tables: int,        # P — static max pages per sequence
    groups: int,            # Hq // Hk
    pages_per_block: int,   # G — pages per buffer slot (DMAs in flight)
    token_tile: int,        # TT — flat-stream rows per grid program
    quantized: bool = False,
):
    if quantized:
        (q_ref, k_pages_ref, v_pages_ref, ks_pages_ref, vs_pages_ref,
         out_ref,
         k_buf, v_buf, ks_buf, vs_buf,
         k_sems, v_sems, ks_sems, vs_sems) = refs
    else:
        (q_ref, k_pages_ref, v_pages_ref, out_ref,
         k_buf, v_buf, k_sems, v_sems) = refs
        ks_pages_ref = vs_pages_ref = None
        ks_buf = vs_buf = ks_sems = vs_sems = None
    t = pl.program_id(0)
    s_lo = tile_ref[t, 0]
    s_hi = tile_ref[t, 1]
    window = win_ref[0]
    TT = token_tile
    G = pages_per_block
    W = G * page_size
    n_groups = (num_tables + G - 1) // G            # static
    Hq, D = q_ref.shape[1], q_ref.shape[2]
    Hk = Hq // groups
    g = groups

    # Per-head query blocks [TT·g, D]: head h's group of g query heads,
    # rows ordered (token, group-head) so a contiguous reshape recovers
    # [TT, g, D] at write-out. Mosaic lowers plain 2D matmuls only (the
    # decode kernel's constraint), so heads unroll statically.
    q_scaled = q_ref[...].astype(jnp.float32) * scale     # [TT, Hq, D]
    q_heads = [
        q_scaled[:, h * g:(h + 1) * g, :].reshape(TT * g, D)
        for h in range(Hk)
    ]
    # Flat-stream row index of each tile row, and its expansion over the
    # per-head row blocks (row r of a [TT·g, ·] block belongs to token
    # r // g).
    row_ids1 = t * TT + jax.lax.broadcasted_iota(
        jnp.int32, (TT, 1), dimension=0
    )                                                     # [TT, 1]
    rows_g = t * TT + jax.lax.div(
        jax.lax.broadcasted_iota(jnp.int32, (TT * g, 1), dimension=0), g
    )                                                     # [TT·g, 1]

    def page_dma(s, p, slot, j, pages_ref, buf, sems):
        return pltpu.make_async_copy(
            pages_ref.at[pt_ref[s, p]], buf.at[slot, j], sems.at[slot, j]
        )

    def start_group(s, blk, slot, lo, hi):
        for j in range(G):
            p = blk * G + j

            @pl.when((p >= lo) & (p < hi))
            def _go(p=p, j=j):
                page_dma(s, p, slot, j, k_pages_ref, k_buf, k_sems).start()
                page_dma(s, p, slot, j, v_pages_ref, v_buf, v_sems).start()
                if quantized:
                    page_dma(s, p, slot, j, ks_pages_ref, ks_buf,
                             ks_sems).start()
                    page_dma(s, p, slot, j, vs_pages_ref, vs_buf,
                             vs_sems).start()

    def wait_group(s, blk, slot, lo, hi):
        for j in range(G):
            p = blk * G + j

            @pl.when((p >= lo) & (p < hi))
            def _wait(p=p, j=j):
                page_dma(s, p, slot, j, k_pages_ref, k_buf, k_sems).wait()
                page_dma(s, p, slot, j, v_pages_ref, v_buf, v_sems).wait()
                if quantized:
                    page_dma(s, p, slot, j, ks_pages_ref, ks_buf,
                             ks_sems).wait()
                    page_dma(s, p, slot, j, vs_pages_ref, vs_buf,
                             vs_sems).wait()

    def seq_body(s, carry):
        # Rows of sequence s inside this tile, and their query positions
        # (kv_len - seq_len + row - seq_start). Unselected rows carry
        # garbage positions that the all-masked logits neutralize.
        start = starts_ref[s]
        length = lens_ref[s]
        kv_len = kv_ref[s]
        sel1 = (row_ids1 >= start) & (row_ids1 < start + length)  # [TT,1]
        pos1 = kv_len - length + (row_ids1 - start)               # [TT,1]
        pos_g = kv_len - length + (rows_g - start)                # [TT·g,1]
        sel_g = (rows_g >= start) & (rows_g < start + length)

        # Visible page range for THIS tile's rows of s: the newest
        # selected row bounds hi, the oldest (minus the window) bounds
        # lo. No selected rows → max_pos = -1 → empty range, loop skips.
        max_pos = jnp.max(jnp.where(sel1, pos1, -1))
        min_pos = jnp.min(jnp.where(sel1, pos1, jnp.int32(2 ** 30)))
        hi = jnp.minimum(
            jax.lax.div(max_pos, page_size) + 1, num_tables
        )
        hi = jnp.maximum(hi, 0)
        lo = jnp.where(
            window > 0,
            jnp.maximum(jax.lax.div(min_pos - window + 1, page_size), 0),
            0,
        )
        blo = jax.lax.div(lo, G)
        bhi = jax.lax.div(hi + G - 1, G)

        @pl.when(lo < hi)
        def _first():
            start_group(s, blo, blo % 2, lo, hi)

        def group_body(blk, carry):
            def run(carry):
                slot = blk % 2

                @pl.when(blk + 1 < bhi)
                def _next():
                    start_group(s, blk + 1, (blk + 1) % 2, lo, hi)

                wait_group(s, blk, slot, lo, hi)
                k = k_buf[slot].reshape(W, Hk * D)
                v = v_buf[slot].reshape(W, Hk * D)
                if quantized:
                    ks2 = ks_buf[slot].reshape(W, Hk).astype(jnp.float32)
                    vs2 = vs_buf[slot].reshape(W, Hk).astype(jnp.float32)

                kv_pos1 = blk * W + jax.lax.broadcasted_iota(
                    jnp.int32, (W, 1), dimension=0
                )                                             # [W, 1]
                valid1 = (
                    (kv_pos1 >= lo * page_size)
                    & (kv_pos1 < hi * page_size)
                )
                # Rows of pages never DMA'd hold stale VMEM; zero V (and
                # its scales) there so masked weights cannot multiply
                # NaN garbage — 0·NaN would poison the accumulator.
                v = jnp.where(valid1, v.astype(jnp.float32), 0.0)
                if quantized:
                    vs2 = jnp.where(valid1, vs2, 0.0)

                kv_pos_row = blk * W + jax.lax.broadcasted_iota(
                    jnp.int32, (TT * g, W), dimension=1
                )
                mask = sel_g & (kv_pos_row <= pos_g)
                mask &= (window <= 0) | (kv_pos_row > pos_g - window)
                mask &= valid1.reshape(1, W)

                new_carry = []
                for h in range(Hk):
                    m, l, acc = carry[h]
                    kk = k[:, h * D:(h + 1) * D].astype(jnp.float32)
                    vv = v[:, h * D:(h + 1) * D]
                    if quantized:
                        kk = kk * ks2[:, h:h + 1]
                        vv = vv * vs2[:, h:h + 1]
                    s_h = jax.lax.dot_general(
                        q_heads[h], kk,
                        dimension_numbers=(((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )                                         # [TT·g, W]
                    if logit_softcap is not None:
                        s_h = logit_softcap * jnp.tanh(s_h / logit_softcap)
                    s_h = jnp.where(mask, s_h, _NEG_INF)
                    # Online-softmax update. Rows outside sequence s are
                    # all-masked: m_cur = -inf → m_new = m, corr = 1,
                    # pexp = 0 → their state passes through untouched.
                    m_cur = jnp.max(s_h, axis=1, keepdims=True)
                    m_new = jnp.maximum(m, m_cur)
                    pexp = jnp.where(mask, jnp.exp(s_h - m_new), 0.0)
                    corr = jnp.exp(m - m_new)
                    l_new = corr * l + jnp.sum(pexp, axis=1, keepdims=True)
                    pv = jax.lax.dot_general(
                        pexp, vv,
                        dimension_numbers=(((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )                                         # [TT·g, D]
                    new_carry.append((m_new, l_new, acc * corr + pv))
                return tuple(new_carry)

            return jax.lax.cond(
                (lo < hi) & (blk >= blo) & (blk < bhi),
                run, lambda c: c, carry,
            )

        return jax.lax.fori_loop(0, n_groups, group_body, carry)

    init = tuple(
        (
            jnp.full((TT * g, 1), _NEG_INF, jnp.float32),
            jnp.zeros((TT * g, 1), jnp.float32),
            jnp.zeros((TT * g, D), jnp.float32),
        )
        for _ in range(Hk)
    )
    final = jax.lax.fori_loop(s_lo, s_hi, seq_body, init)
    for h in range(Hk):
        _, l, acc = final[h]
        # Padding rows (no sequence) keep l = 0 → output 0.
        out = (acc / jnp.maximum(l, 1e-9)).reshape(TT, g, D)
        out_ref[:, h * g:(h + 1) * g, :] = out


@functools.partial(
    jax.jit,
    static_argnames=(
        "scale", "logit_softcap", "interpret", "pages_per_block",
        "token_tile",
    ),
)
def _ragged_call(
    q: jax.Array,             # [T, Hq, D] flat token stream (tile-padded)
    k_pages,                  # [N, ps, Hk, D], or (values, scales) pairs
    v_pages,                  #   for int8 KV (scales [N, ps, Hk] bf16)
    page_tables: jax.Array,   # [S, P] int32
    seq_starts: jax.Array,    # [S] int32
    seq_lens: jax.Array,      # [S] int32
    kv_lens: jax.Array,       # [S] int32
    window: jax.Array,        # [1] int32
    *,
    scale: float,
    logit_softcap: Optional[float],
    interpret: bool,
    pages_per_block: int = 0,   # 0 → auto
    token_tile: int = TOKEN_TILE,
):
    """Returns NORMALIZED attention [T, Hq, D] f32 for every row that
    belongs to a sequence (padding rows read 0). T must be a multiple of
    `token_tile`; sequences must occupy ascending, non-overlapping row
    ranges (the engine's ragged batch builder guarantees both)."""
    quantized = isinstance(k_pages, tuple)
    if quantized:
        (k_pages, ks_pages), (v_pages, vs_pages) = k_pages, v_pages
    T, Hq, D = q.shape
    N, ps, Hk, _ = k_pages.shape
    S, P = page_tables.shape
    TT = token_tile
    if T % TT:
        raise ValueError(
            f"ragged token stream T={T} must be a multiple of "
            f"token_tile={TT} (the engine pads the stream)"
        )
    if pages_per_block <= 0:
        pages_per_block = max(1, min(P, 128 // ps if ps <= 128 else 1))
    G = min(pages_per_block, P)
    n_tiles = T // TT
    # Per-tile sequence overlap [lo, hi): tile t covers rows
    # [t·TT, (t+1)·TT); sequences with start < tile_end and end > tile
    # start overlap. Ranges are ascending, so two searchsorteds give the
    # bounds (O(nT·logS) on host-side values, traced here as jnp ops).
    seq_ends = seq_starts + seq_lens
    tile_row_lo = jnp.arange(n_tiles, dtype=jnp.int32) * TT
    tile_row_hi = tile_row_lo + TT
    tile_lo = jnp.searchsorted(seq_ends, tile_row_lo, side="right")
    tile_hi = jnp.searchsorted(seq_starts, tile_row_hi, side="left")
    tiles = jnp.stack(
        [tile_lo.astype(jnp.int32),
         jnp.maximum(tile_hi, tile_lo).astype(jnp.int32)], axis=1
    )                                                      # [nT, 2]

    # Fold heads into lanes: [N, ps, Hk·D] keeps DMA slices 128-aligned
    # for any head_dim (contiguous reshape — decode-kernel layout).
    k_pages = k_pages.reshape(N, ps, Hk * D)
    v_pages = v_pages.reshape(N, ps, Hk * D)

    kernel = functools.partial(
        _ragged_kernel,
        scale=scale,
        logit_softcap=logit_softcap,
        page_size=ps,
        num_tables=P,
        groups=Hq // Hk,
        pages_per_block=G,
        token_tile=TT,
        quantized=quantized,
    )
    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    in_specs = [
        pl.BlockSpec((TT, Hq, D), lambda t, *_: (t, 0, 0)),
        any_spec,
        any_spec,
    ]
    scratch = [
        pltpu.VMEM((2, G, ps, Hk * D), k_pages.dtype),
        pltpu.VMEM((2, G, ps, Hk * D), k_pages.dtype),
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        in_specs += [any_spec, any_spec]
        scratch += [
            pltpu.VMEM((2, G, ps, Hk), ks_pages.dtype),
            pltpu.VMEM((2, G, ps, Hk), vs_pages.dtype),
        ]
        operands = [q, k_pages, v_pages, ks_pages, vs_pages]
    n_sems = 4 if quantized else 2
    scratch += [pltpu.SemaphoreType.DMA((2, G))] * n_sems
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(n_tiles,),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((TT, Hq, D), lambda t, *_: (t, 0, 0))],
        scratch_shapes=scratch,
    )
    (out,) = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((T, Hq, D), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(
        seq_starts.astype(jnp.int32),
        seq_lens.astype(jnp.int32),
        kv_lens.astype(jnp.int32),
        page_tables.astype(jnp.int32),
        tiles,
        window,
        *operands,
    )
    return out


def use_ragged_kernel(num_kv_heads: int, head_dim: int) -> bool:
    """Gate for the ragged kernel path: TPU hardware, 128-aligned folded
    head-lane dimension (the DMA-tiling rule shared with the decode
    kernel), and the POLYKEY_DISABLE_RAGGED_KERNEL kill-switch — the
    ragged kernel is a separate Mosaic lowering surface from the decode
    kernel, so a regression there must be containable without taking the
    working decode path down (the gather fallback serves everything)."""
    import os

    if os.environ.get(
        "POLYKEY_DISABLE_RAGGED_KERNEL", ""
    ).lower() in ("1", "true"):
        return False
    from .paged_attention_kernel import use_paged_kernel

    return use_paged_kernel(num_kv_heads, head_dim)


def ragged_gather_attention(
    q: jax.Array,             # [T, Hq, D] flat token stream
    k_pages,                  # [N, ps, Hk, D] or int8 (values, scales)
    v_pages,
    token_tables: jax.Array,  # [T, P] int32 — each token's table row
    q_positions: jax.Array,   # [T] int32 absolute positions
    *,
    scale: float,
    logit_softcap: Optional[float] = None,
    window: Optional[jax.Array] = None,
) -> jax.Array:
    """The gather reference: one batch row per token through the
    existing paged_attention (B=T, T=1) — per token EXACTLY the math the
    bucketed engine paths run (decode gather fallback and prefill window
    attention reduce to the same per-row softmax over the same gathered
    window), which is what makes greedy streams bit-identical between
    the ragged and bucketed engine modes off-TPU."""
    from .paged_attention import paged_attention

    out = paged_attention(
        q[:, None], k_pages, v_pages, token_tables,
        q_positions[:, None].astype(jnp.int32),
        scale=scale, logit_softcap=logit_softcap, window=window,
    )
    return out[:, 0]


def ragged_paged_attention(
    q: jax.Array,             # [T, Hq, D] flat token stream (tile-padded)
    k_pages,                  # [N, ps, Hk, D] or int8 (values, scales)
    v_pages,
    page_tables: jax.Array,   # [S, P] int32 per-sequence tables
    seq_starts: jax.Array,    # [S] int32 row range starts (ascending)
    seq_lens: jax.Array,      # [S] int32 new-token counts
    kv_lens: jax.Array,       # [S] int32 KV lengths (new tokens incl.)
    *,
    scale: float,
    logit_softcap: Optional[float] = None,
    window: Optional[jax.Array] = None,
    interpret: bool = False,
    force_kernel: bool = False,
    pages_per_block: int = 0,
    token_tile: int = TOKEN_TILE,
) -> jax.Array:
    """Ragged paged attention over the flat stream; returns [T, Hq, D]
    in q.dtype. Kernel on TPU-eligible geometry (or `force_kernel` /
    `interpret`); gather fallback everywhere else. Rows outside every
    sequence range are padding (output unspecified — the engine masks
    them)."""
    quantized = isinstance(k_pages, tuple)
    data_pool = k_pages[0] if quantized else k_pages
    Hk, D = data_pool.shape[2], data_pool.shape[3]
    if window is None:
        win = jnp.zeros((1,), jnp.int32)
    else:
        win = jnp.asarray(window, jnp.int32).reshape(1)

    if force_kernel or interpret or use_ragged_kernel(Hk, D):
        out = _ragged_call(
            q, k_pages, v_pages, page_tables,
            seq_starts, seq_lens, kv_lens, win,
            scale=scale, logit_softcap=logit_softcap,
            interpret=interpret, pages_per_block=pages_per_block,
            token_tile=token_tile,
        )
        return out.astype(q.dtype)

    # Gather fallback: per-token table rows + positions from the
    # sequence metadata (ranges are ascending and non-overlapping).
    T = q.shape[0]
    rows = jnp.arange(T, dtype=jnp.int32)
    sid = jnp.clip(
        jnp.searchsorted(seq_starts, rows, side="right") - 1,
        0, page_tables.shape[0] - 1,
    ).astype(jnp.int32)
    in_seq = (rows >= seq_starts[sid]) & (
        rows < seq_starts[sid] + seq_lens[sid]
    )
    pos = kv_lens[sid] - seq_lens[sid] + (rows - seq_starts[sid])
    pos = jnp.where(in_seq, pos, 0)
    token_tables = jnp.where(
        in_seq[:, None], page_tables[sid],
        jnp.zeros_like(page_tables[sid]),
    )
    return ragged_gather_attention(
        q, k_pages, v_pages, token_tables, pos,
        scale=scale, logit_softcap=logit_softcap, window=window,
    )
