"""Reference attention (pure jnp) — the correctness oracle for the Pallas
kernels, and the CPU-mesh fallback path.

Supports the features the served families need (models/config.py): GQA
(num_kv_heads < num_heads), causal masking by absolute position, Gemma-2
attention-logit soft-capping, and sliding-window masking. Softmax runs in
fp32 regardless of activation dtype — bf16 softmax loses decode accuracy.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def make_attention_mask(
    q_positions: jax.Array,       # [B, T] absolute position of each query
    num_kv_slots: int,            # S — key/value slot count (slot s = pos s)
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Boolean [B, T, S] mask: True where the query may attend.

    Causality is by absolute position (slot s holds the token at position s),
    which covers right-padded prefill and single-token decode uniformly:
    padded/garbage slots beyond the query's position are never visible.
    """
    kv_pos = jnp.arange(num_kv_slots, dtype=jnp.int32)[None, None, :]
    q_pos = q_positions[:, :, None]
    mask = kv_pos <= q_pos
    if sliding_window is not None:
        mask &= kv_pos > q_pos - sliding_window
    return mask


def attention(
    q: jax.Array,                 # [B, T, num_heads, head_dim]
    k: jax.Array,                 # [B, S, num_kv_heads, head_dim]
    v: jax.Array,                 # [B, S, num_kv_heads, head_dim]
    mask: jax.Array,              # [B, T, S] bool
    *,
    scale: float,
    logit_softcap: Optional[float] = None,
) -> jax.Array:
    """Grouped-query attention; returns [B, T, num_heads, head_dim]."""
    B, T, num_heads, head_dim = q.shape
    num_kv_heads = k.shape[2]
    groups = num_heads // num_kv_heads

    qg = q.reshape(B, T, num_kv_heads, groups, head_dim)
    logits = jnp.einsum(
        "bthgd,bshd->bhgts", qg, k, preferred_element_type=jnp.float32
    )
    logits = logits * scale
    if logit_softcap is not None:
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)

    neg = jnp.finfo(jnp.float32).min
    logits = jnp.where(mask[:, None, None, :, :], logits, neg)

    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhgts,bshd->bthgd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, T, num_heads, head_dim).astype(q.dtype)
