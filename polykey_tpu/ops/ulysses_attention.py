"""Ulysses attention: head-sharded sequence parallelism via all-to-all.

The second long-context formulation SURVEY.md §5 owes (alongside ring
attention): instead of rotating KV chunks around the ring, one all-to-all
over the `sp` axis re-shards activations from sequence-sharded
[B, T/sp, H, D] to head-sharded [B, T, H/sp, D]; each device then runs
ordinary *local* full attention for its head subset over the whole
sequence, and a second all-to-all restores sequence sharding. Two
collectives per layer versus ring's sp-1 ppermutes — the better trade when
the head count covers the axis (H % sp == 0) and T fits per-device HBM at
H/sp heads; ring remains the fallback for very long T or few heads.

Masking is by absolute position (gathered alongside the exchange), so the
math is exactly the reference attention's — verified against it and
against the ring path in tests/test_ulysses.py.

No reference analog (the reference has no attention at all — SURVEY.md §5
long-context: "Absent"); design follows the DeepSpeed-Ulysses pattern from
PAPERS.md, re-expressed as jax.lax collectives under shard_map.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .attention import attention
from ..compat import shard_map


def ulysses_attention(
    q: jax.Array,             # [B, T_local, Hq, D] sequence-sharded
    k: jax.Array,             # [B, T_local, Hk, D]
    v: jax.Array,
    q_positions: jax.Array,   # [B, T_local] absolute positions
    kv_positions: jax.Array,  # [B, T_local]
    *,
    axis_name: str,
    axis_size: int,
    scale: float,
    logit_softcap: Optional[float] = None,
    window: Optional[jax.Array] = None,
) -> jax.Array:
    """Per-device Ulysses body (call inside shard_map).

    Requires Hq % axis_size == 0 and Hk % axis_size == 0 (head counts as
    seen inside the map, i.e. after any tp sharding).
    """
    B, T_local, Hq, D = q.shape
    Hk = k.shape[2]
    if Hq % axis_size or Hk % axis_size:
        raise ValueError(
            f"Ulysses needs head counts divisible by the sp axis: "
            f"Hq={Hq}, Hk={Hk}, sp={axis_size} (use ring attention instead)"
        )

    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                            tiled=True)
    # seq-sharded → head-sharded full sequence: [B, T, H/sp, D]
    q = a2a(q, split_axis=2, concat_axis=1)
    k = a2a(k, split_axis=2, concat_axis=1)
    v = a2a(v, split_axis=2, concat_axis=1)
    # Positions for the whole sequence travel with a (cheap) all-gather;
    # chunks concatenate in device order, matching the a2a's sequence
    # reassembly, so absolute-position masking is layout-independent.
    q_pos = jax.lax.all_gather(q_positions, axis_name, axis=1, tiled=True)
    kv_pos = jax.lax.all_gather(kv_positions, axis_name, axis=1, tiled=True)

    mask = kv_pos[:, None, :] <= q_pos[:, :, None]          # [B, T, T]
    if window is not None:
        w = jnp.asarray(window, jnp.int32)
        mask &= (w <= 0) | (kv_pos[:, None, :] > q_pos[:, :, None] - w)

    ctx = attention(q, k, v, mask, scale=scale, logit_softcap=logit_softcap)

    # head-sharded → seq-sharded: [B, T_local, Hq, D]
    return a2a(ctx, split_axis=1, concat_axis=2)


def ulysses_attention_spmd(
    q: jax.Array,             # [B, T, Hq, D] (global shapes)
    k: jax.Array,             # [B, T, Hk, D]
    v: jax.Array,
    q_positions: jax.Array,   # [B, T]
    kv_positions: jax.Array,  # [B, T]
    mesh: Mesh,
    *,
    scale: float,
    logit_softcap: Optional[float] = None,
    window: Optional[jax.Array] = None,
    seq_axis: str = "sp",
    batch_axis: str = "dp",
    head_axis: str = "tp",
) -> jax.Array:
    """shard_map wrapper with the framework's standard axes (same contract
    as ring_attention_spmd: batch over dp, sequence over sp, heads over tp).
    """
    axis_size = mesh.shape[seq_axis]
    qkv_spec = P(batch_axis, seq_axis, head_axis, None)
    pos_spec = P(batch_axis, seq_axis)

    inner = functools.partial(
        ulysses_attention,
        axis_name=seq_axis,
        axis_size=axis_size,
        scale=scale,
        logit_softcap=logit_softcap,
        window=window,
    )
    return shard_map(
        inner,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, pos_spec, pos_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )(q, k, v, q_positions, kv_positions)
