"""Pallas flash attention (TPU): blockwise online-softmax prefill kernel.

Replaces the O(T·S) materialized-logits reference (ops/attention.py) on the
prefill hot path: logits never leave VMEM, softmax statistics (running max m,
running denominator l) and the output accumulator live in per-block scratch,
and the S dimension streams through the innermost grid axis — HBM traffic is
O(T·D + S·D) instead of O(T·S).

Covers everything the served families need (models/config.py): GQA, causal
masking by absolute position, Gemma-2 attention-logit soft-capping and
(dynamic, per-layer) sliding windows. Numerics: q·kᵀ and the softmax run in
fp32 (preferred_element_type), matching the reference oracle; tests compare
the two directly.

The wrapper pads T/S to block multiples and falls back to the reference
implementation off-TPU or for tiny shapes, so every call site can use
`flash_attention` unconditionally.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import attention, make_attention_mask
from ..compat import shard_map, tpu_compiler_params

_NEG_INF = -1e30
# Lane width: the m/l scratch rows are (bq, 128) with the statistic
# replicated across the lane dimension (min tile constraint).
_LANES = 128


def _kernel(
    # inputs (blocked)
    q_ref,        # [1, 1, bq, D]
    k_ref,        # [1, 1, bk, D]
    v_ref,        # [1, 1, bk, D]
    qpos_ref,     # [1, 1, 1, bq] int32 (VMEM; shaped for tiling rules)
    win_ref,      # [1, 1] int32 (SMEM) — sliding window, <=0 means global
    # outputs
    out_ref,      # [1, 1, bq, D]
    # scratch
    m_ref,        # [bq, 128] fp32
    l_ref,        # [bq, 128] fp32
    acc_ref,      # [bq, D] fp32
    *,
    scale: float,
    logit_softcap: Optional[float],
    kv_len: int,  # true (unpadded) S
    bk: int,
):
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    bq = q_ref.shape[2]
    q_pos = qpos_ref[0, 0, 0][:, None]                        # [bq, 1]
    kv_pos = j * bk + jax.lax.broadcasted_iota(
        jnp.int32, (bq, bk), dimension=1
    )                                                         # [bq, bk]
    window = win_ref[0, 0]

    # Skip blocks fully outside [q_pos - window, q_pos]: no query row in this
    # q block can see any key in this k block (saves MXU work; the causal
    # upper-right triangle of blocks is ~half the grid).
    max_qpos = jnp.max(q_pos)
    min_qpos = jnp.min(jnp.where(q_pos < 0, jnp.int32(2**30), q_pos))
    block_lo, block_hi = j * bk, j * bk + bk - 1
    needed = (block_lo <= max_qpos) & (
        (window <= 0) | (block_hi > min_qpos - window)
    )

    @pl.when(needed)
    def _block():
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                     # [bq, bk]
        if logit_softcap is not None:
            s = logit_softcap * jnp.tanh(s / logit_softcap)

        mask = (kv_pos <= q_pos) & (kv_pos < kv_len)
        mask &= (window <= 0) | (kv_pos > q_pos - window)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, :1]                                 # [bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Explicit mask on p: when a block is fully masked, s - m_new == 0
        # everywhere and exp would contribute bk spurious units to l.
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)          # [bq, bk]
        corr = jnp.exp(m_prev - m_new)                        # [bq, 1]

        l_new = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v_ref[0, 0].astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-9)
        out_ref[0, 0] = (acc_ref[:] / l).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "scale", "logit_softcap", "kv_len", "block_q", "block_k", "interpret"
    ),
)
def _flash_bhsd(
    q: jax.Array,             # [B, Hq, Tp, D]
    k: jax.Array,             # [B, Hk, Sp, D]
    v: jax.Array,
    q_positions: jax.Array,   # [B, nq, 1, bq] int32 (padding rows = -1)
    window: jax.Array,        # [1, 1] int32 (<=0 → global)
    *,
    scale: float,
    logit_softcap: Optional[float],
    kv_len: int,
    block_q: int,
    block_k: int,
    interpret: bool,
) -> jax.Array:
    B, Hq, Tp, D = q.shape
    Hk, Sp = k.shape[1], k.shape[2]
    groups = Hq // Hk
    nq, nk = Tp // block_q, Sp // block_k

    grid = (B * Hq, nq, nk)
    kernel = functools.partial(
        _kernel,
        scale=scale,
        logit_softcap=logit_softcap,
        kv_len=kv_len,
        bk=block_k,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, D),
                lambda bh, i, j: (bh // Hq, bh % Hq, i, 0),
            ),
            pl.BlockSpec(
                (1, 1, block_k, D),
                lambda bh, i, j: (bh // Hq, (bh % Hq) // groups, j, 0),
            ),
            pl.BlockSpec(
                (1, 1, block_k, D),
                lambda bh, i, j: (bh // Hq, (bh % Hq) // groups, j, 0),
            ),
            pl.BlockSpec(
                (1, 1, 1, block_q), lambda bh, i, j: (bh // Hq, i, 0, 0)
            ),
            pl.BlockSpec(
                (1, 1), lambda bh, i, j: (0, 0), memory_space=pltpu.SMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, D), lambda bh, i, j: (bh // Hq, bh % Hq, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hq, Tp, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * Hq * Tp * Sp * D,
            bytes_accessed=(
                q.size + k.size + v.size + q.size
            ) * q.dtype.itemsize,
            transcendentals=B * Hq * Tp * Sp,
        ),
        interpret=interpret,
    )(q, k, v, q_positions, window)


def _pad_to(x: jax.Array, axis: int, multiple: int, value=0) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


# Head dims proven against Mosaic's 128-lane tiling (the served families
# use 64/128/256; an odd D like 40 or 72 must take the reference fallback
# rather than risk a kernel compile failure on hardware — ADVICE r1).
_FLASH_HEAD_DIMS = frozenset({64, 128, 256})


def use_flash(T: int, S: int, head_dim: int) -> bool:
    """Dispatch policy: the kernel wins when the logits matrix is large
    enough that not materializing it matters; the reference path keeps tiny
    shapes (decode against short caches, unit tests), unusual head dims,
    and non-TPU backends. POLYKEY_DISABLE_FLASH=1 is the operational
    kill-switch (the reference path serves every shape)."""
    import os

    if os.environ.get("POLYKEY_DISABLE_FLASH", "").lower() in ("1", "true"):
        return False
    return (
        jax.default_backend() == "tpu"
        and T >= 128
        and S >= 128
        and head_dim in _FLASH_HEAD_DIMS
    )


def flash_attention(
    q: jax.Array,             # [B, T, Hq, D]
    k: jax.Array,             # [B, S, Hk, D]
    v: jax.Array,
    q_positions: jax.Array,   # [B, T] absolute positions
    *,
    scale: float,
    logit_softcap: Optional[float] = None,
    window: Optional[jax.Array] = None,   # scalar; None/<=0 → global
    block_q: int = 512,
    block_k: int = 1024,
    interpret: bool = False,
    force_kernel: bool = False,
    mesh=None,                # serving mesh → shard_map the kernel
) -> jax.Array:
    """Blockwise attention; same contract as the reference `attention` but
    masking is derived from positions in-kernel. Returns [B, T, Hq, D].

    With a mesh whose sp/tp extents exceed 1 the kernel runs under
    shard_map: the query/time axis shards over sp (each shard computes
    its query block against the FULL key window — masks come from the
    global positions, so blockwise attention is embarrassingly parallel
    over T), heads over tp. GSPMD cannot partition an opaque pallas_call
    and would otherwise all-gather the sharded operands.
    """
    B, T, Hq, D = q.shape
    S = k.shape[1]

    if not (force_kernel or interpret or use_flash(T, S, D)):
        mask = make_attention_mask(q_positions, S)
        if window is not None:
            kv_pos = jnp.arange(S, dtype=jnp.int32)[None, None, :]
            w = jnp.asarray(window, jnp.int32)
            mask &= (w <= 0) | (kv_pos > q_positions[:, :, None] - w)
        return attention(
            q, k, v, mask, scale=scale, logit_softcap=logit_softcap
        )

    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    if (sp > 1 or tp > 1) and mesh.shape.get("pp", 1) > 1:
        # Per-layer activations are stage-local under pp, not replicated —
        # the shard_map specs below would be wrong (and check_vma=False
        # would hide it). The masked reference path is GSPMD-partitionable
        # as-is, so pp>1 meshes take it.
        mask = make_attention_mask(q_positions, S)
        if window is not None:
            kv_pos = jnp.arange(S, dtype=jnp.int32)[None, None, :]
            w = jnp.asarray(window, jnp.int32)
            mask &= (w <= 0) | (kv_pos > q_positions[:, :, None] - w)
        return attention(
            q, k, v, mask, scale=scale, logit_softcap=logit_softcap
        )
    if sp > 1 or tp > 1:
        if T % sp or Hq % tp or k.shape[2] % tp:
            # Never fall through to an unwrapped pallas_call on sharded
            # operands — GSPMD would all-gather them (or fail to compile)
            # with no pointer at the real cause.
            raise ValueError(
                f"flash kernel on mesh: T={T} %% sp={sp}, Hq={Hq} / "
                f"Hk={k.shape[2]} %% tp={tp} must divide evenly"
            )
        from jax.sharding import PartitionSpec as P

        def inner(q, k, v, qpos, w):
            # window passes as an explicit operand (it can be a traced
            # per-layer scalar — shard_map must not close over tracers);
            # the kernel treats w <= 0 as global attention.
            return flash_attention(
                q, k, v, qpos,
                scale=scale, logit_softcap=logit_softcap, window=w,
                block_q=block_q, block_k=block_k, interpret=interpret,
                force_kernel=True,  # dispatch decided here, global shapes
            )

        w = (jnp.zeros((1,), jnp.int32) if window is None
             else jnp.asarray(window, jnp.int32).reshape(1))
        sm = shard_map(
            inner,
            mesh=mesh,
            in_specs=(
                P(None, "sp", "tp", None),    # q
                P(None, None, "tp", None),    # k (full window per shard)
                P(None, None, "tp", None),    # v
                P(None, "sp"),                # q_positions
                P(None),                      # window
            ),
            out_specs=P(None, "sp", "tp", None),
            check_vma=False,
        )
        return sm(q, k, v, q_positions, w)

    # Shrink blocks toward small shapes, staying on 128-multiples (the
    # wrapper pads T/S up to one block in that case). Benchmarked on v5e:
    # 512x1024 blocks run ~26x faster than 128x128 (MXU utilization).
    def _fit(block: int, size: int) -> int:
        return min(block, ((size + 127) // 128) * 128)

    block_q = _fit(block_q, T)
    block_k = _fit(block_k, S)

    qt = _pad_to(jnp.transpose(q, (0, 2, 1, 3)), 2, block_q)
    kt = _pad_to(jnp.transpose(k, (0, 2, 1, 3)), 2, block_k)
    vt = _pad_to(jnp.transpose(v, (0, 2, 1, 3)), 2, block_k)
    qpos = _pad_to(q_positions.astype(jnp.int32), 1, block_q, value=-1)
    qpos = qpos.reshape(B, -1, 1, block_q)
    if window is None:
        win = jnp.zeros((1, 1), jnp.int32)
    else:
        win = jnp.asarray(window, jnp.int32).reshape(1, 1)

    out = _flash_bhsd(
        qt, kt, vt, qpos, win,
        scale=scale,
        logit_softcap=logit_softcap,
        kv_len=S,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
    )
    return jnp.transpose(out[:, :, :T], (0, 2, 1, 3))
