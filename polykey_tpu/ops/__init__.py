"""TPU compute ops: reference jnp implementations + Pallas kernels.

Every op has a pure-jnp reference implementation (runs anywhere, used on CPU
test meshes and as the correctness oracle) and, where it matters for HBM
bandwidth, a Pallas TPU kernel (paged attention decode, flash prefill).
Kernel/bandwidth tradeoffs follow the v5e numbers: MXU wants ≥128-wide tiles,
bf16 min tile (16, 128), ~16 MB VMEM per core.
"""
