"""Model families served by the engine.

The reference has no model code at all (SURVEY.md: the only backend is
internal/service/mock.go); these families come from the north-star serving
configs (BASELINE.json): Llama-3, Mixtral 8x7B (MoE), Gemma-2.

All models are functional JAX: parameters are plain pytrees (dicts of
arrays with layers stacked on a leading axis for `lax.scan`), forward passes
are pure functions, and sharding is applied externally via
`polykey_tpu.parallel` partition specs.
"""

from .config import MODEL_REGISTRY, ModelConfig, get_config  # noqa: F401
