"""Batch generation over the contiguous-cache path.

The simple serving loop (measurement config 2 in BASELINE.json: single-chip
greedy decode): jitted prefill writes the prompt into the cache and samples
the first token; a `lax.scan` decode loop generates the rest. Fixed shapes
throughout — (batch, max_len) are compile-time constants, per-row prompt
lengths arrive as data.

The continuous-batching engine (engine/engine.py) supersedes this for
serving; this path remains for tests, offline eval, and the bench harness.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..engine.sampling import SamplingParams, sample
from .config import ModelConfig
from .transformer import KVCache, forward, init_cache, unembed


@partial(jax.jit, static_argnames=("cfg",))
def prefill(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,       # [B, T] right-padded prompts
    seq_lens: jax.Array,     # [B] true prompt lengths
    cache: KVCache,
) -> tuple[jax.Array, KVCache]:
    """Write prompts into the cache; return fp32 logits at each row's last
    real token ([B, vocab]) and the updated cache."""
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    hidden, cache = forward(params, cfg, tokens, positions, cache)
    last = hidden[jnp.arange(B), seq_lens - 1]           # [B, H]
    return unembed(params, cfg, last), cache


@partial(jax.jit, static_argnames=("cfg",))
def decode_step(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,       # [B] last sampled token per row
    positions: jax.Array,    # [B] absolute position being generated
    cache: KVCache,
) -> tuple[jax.Array, KVCache]:
    """One decode step; returns fp32 logits [B, vocab] + updated cache."""
    hidden, cache = forward(
        params, cfg, tokens[:, None], positions[:, None], cache
    )
    return unembed(params, cfg, hidden[:, 0]), cache


@partial(jax.jit, static_argnames=("cfg", "sampling", "max_len"))
def generate(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,       # [B, T] right-padded prompts
    seq_lens: jax.Array,     # [B]
    key: jax.Array,
    sampling: SamplingParams,
    max_len: int,
    eos_id: int = -1,        # -1 → never stops early
) -> tuple[jax.Array, jax.Array]:
    """Generate sampling.max_new_tokens per row.

    Returns (generated [B, max_new_tokens] int32, num_generated [B]).
    Rows that hit eos_id keep emitting pad-like eos tokens (shapes are
    static); num_generated counts tokens up to and including eos.
    """
    B, T = tokens.shape
    if T + sampling.max_new_tokens > max_len:
        raise ValueError(
            f"cache too small: prompt window {T} + max_new_tokens "
            f"{sampling.max_new_tokens} exceeds max_len {max_len} "
            "(out-of-range cache writes would be silently dropped)"
        )
    cache = init_cache(cfg, B, max_len, params["embed"].dtype)

    logits, cache = prefill(params, cfg, tokens, seq_lens, cache)
    key, k0 = jax.random.split(key)
    first = sample(logits, k0, sampling)

    def step(carry, _):
        cache, prev_token, pos, done, key = carry
        key, k = jax.random.split(key)
        logits, cache = decode_step(params, cfg, prev_token, pos, cache)
        token = sample(logits, k, sampling)
        token = jnp.where(done, eos_id, token)
        new_done = done | (token == eos_id)
        return (cache, token, pos + 1, new_done, key), (token, done)

    done0 = first == eos_id
    (_, _, _, _, _), (rest, was_done) = jax.lax.scan(
        step,
        (cache, first, seq_lens, done0, key),
        None,
        length=sampling.max_new_tokens - 1,
    )

    generated = jnp.concatenate([first[None, :], rest], axis=0).T  # [B, N]
    # Count tokens emitted before each row finished (+1 for the eos itself).
    alive = jnp.concatenate(
        [jnp.zeros((1, B), dtype=bool), was_done], axis=0
    ).T                                                            # [B, N]
    num_generated = jnp.sum(~alive, axis=1).astype(jnp.int32)
    return generated, num_generated
