"""Llama-3 family façade.

The reference contains no model code (its backend is a mock —
/root/reference/internal/service/mock.go); Llama-3 is the flagship serving
family from BASELINE.json configs 2-3. The architecture (GQA, RoPE
theta=500k, SwiGLU, RMSNorm, untied head for 8B/70B) is implemented by the
config-driven stack in transformer.py; this module binds the family name to
its configs and weight loading.
"""

from __future__ import annotations

import jax.numpy as jnp

from .config import LLAMA3_8B, LLAMA3_70B, LLAMA32_1B, TINY_LLAMA, ModelConfig
from .transformer import KVCache, forward, init_cache, init_params, unembed

__all__ = [
    "LLAMA3_8B",
    "LLAMA3_70B",
    "LLAMA32_1B",
    "TINY_LLAMA",
    "KVCache",
    "ModelConfig",
    "forward",
    "init_cache",
    "init_params",
    "unembed",
    "param_bytes",
]


def param_bytes(cfg: ModelConfig, dtype=jnp.bfloat16) -> int:
    return cfg.num_params() * jnp.dtype(dtype).itemsize
