"""Speculative decoding: draft/verify generation (measurement config 5).

A small draft model proposes `gamma` tokens autoregressively; the target
model scores the whole proposal in ONE forward pass (gamma+1 positions —
prefill-shaped work that uses the MXU efficiently instead of gamma separate
bandwidth-bound decode steps); a prefix is accepted and one extra token is
emitted at the first mismatch (greedy) / rejection (sampled). Guarantees:

- temperature == 0: output is EXACTLY the target model's greedy decode,
  for any draft model (verified in tests/test_speculative.py).
- temperature > 0: standard rejection sampling [Leviathan et al.] — accept
  draft token x with prob min(1, p_t(x)/p_d(x)), else resample from the
  normalized residual max(p_t - p_d, 0); the output distribution equals
  target-only sampling. top_p and top_k are intentionally unsupported here
  (truncation filters break the residual-distribution identity) and are
  rejected at trace time.

TPU-shape design: everything is fixed-shape under one jit. Per-row
divergence (different acceptance counts) is data, not shape: positions,
done flags, and output counts are [B] arrays, and KV caches are slot-per-
position (models/transformer.py), so stale entries written for rejected
draft tokens are simply overwritten when the row's position catches up —
no cache rewind is needed (slot s is only ever attended once position > s,
and by then the accepted token's KV has been rewritten there).

No analog exists in the reference (SURVEY.md §2b lists speculative decoding
as absent); the design follows the north star + PAPERS.md patterns.
"""

from __future__ import annotations

from functools import partial
import jax
import jax.numpy as jnp

from ..engine.sampling import SamplingParams
from .config import ModelConfig
from .transformer import forward, init_cache, unembed


def _token_probs(logits: jax.Array, temperature: float) -> jax.Array:
    """[.., V] fp32 probabilities at the given temperature."""
    return jax.nn.softmax(logits / jnp.maximum(temperature, 1e-6), axis=-1)


def rejection_accept(
    t_probs: jax.Array,       # [B, gamma(+1), V] target probs
    d_dists: jax.Array,       # [B, gamma, V] draft probs (as sampled)
    drafts: jax.Array,        # [B, gamma] draft tokens
    u: jax.Array,             # [B, gamma] uniform(0,1)
) -> jax.Array:
    """Leviathan acceptance test: accept draft x with prob
    min(1, p_t(x)/p_d(x)). Shared by the contiguous path below and the
    paged serving path (engine/spec_decode.py) so a numerical fix lands in
    both."""
    gamma = drafts.shape[1]
    p_t = jnp.take_along_axis(
        t_probs[:, :gamma], drafts[..., None], axis=-1
    )[..., 0]
    p_d = jnp.take_along_axis(d_dists, drafts[..., None], axis=-1)[..., 0]
    return u < jnp.minimum(1.0, p_t / jnp.maximum(p_d, 1e-20))


def residual_extra_dist(
    t_probs: jax.Array,       # [B, gamma+1, V]
    d_dists: jax.Array,       # [B, gamma, V]
    n_acc: jax.Array,         # [B] accepted-prefix lengths
) -> jax.Array:
    """[B, V] distribution for the extra token: the normalized residual
    max(p_t - p_d, 0) at the first rejection, or the target's distribution
    at the bonus position when all gamma drafts were accepted; degenerate
    zero-mass residuals fall back to the target distribution."""
    B, g1, _ = t_probs.shape
    gamma = g1 - 1
    rows = jnp.arange(B, dtype=jnp.int32)
    all_acc = n_acc == gamma
    p_t_x = t_probs[rows, n_acc]
    p_d_x = d_dists[rows, jnp.minimum(n_acc, gamma - 1)]
    residual = jnp.maximum(p_t_x - p_d_x, 0.0)
    res_mass = jnp.sum(residual, axis=-1, keepdims=True)
    residual = jnp.where(
        res_mass > 1e-20, residual / jnp.maximum(res_mass, 1e-20), p_t_x
    )
    return jnp.where(all_acc[:, None], p_t_x, residual)


@partial(
    jax.jit,
    static_argnames=(
        "target_cfg", "draft_cfg", "sampling", "max_len", "gamma",
        "return_stats",
    ),
)
def speculative_generate(
    target_params: dict,
    target_cfg: ModelConfig,
    draft_params: dict,
    draft_cfg: ModelConfig,
    tokens: jax.Array,        # [B, T] right-padded prompts
    seq_lens: jax.Array,      # [B]
    key: jax.Array,
    sampling: SamplingParams,
    max_len: int,
    gamma: int = 4,
    eos_id: int = -1,
    return_stats: bool = False,
) -> tuple[jax.Array, ...]:
    """Draft/verify generation; same contract as models/generate.generate:
    returns (generated [B, max_new_tokens] int32, num_generated [B]).
    With return_stats, appends (accepted_drafts, proposed_drafts) scalars —
    the acceptance rate is the speedup dial and regressions in draft-cache
    bookkeeping are invisible in the (always target-exact) output stream."""
    B, T = tokens.shape
    max_new = sampling.max_new_tokens
    greedy = sampling.temperature == 0.0
    if sampling.top_k > 0 or sampling.top_p < 1.0:
        raise ValueError(
            "speculative_generate supports greedy and plain-temperature "
            "sampling only: top_k/top_p truncation breaks the rejection-"
            "sampling residual identity (output would not match target-only "
            "sampling). Filter-free SamplingParams required."
        )
    # +gamma: the final verify window may draft past the last emitted token;
    # those cache writes must land in real slots (JAX clamps OOB scatters,
    # which would corrupt the last slot).
    if T + max_new + gamma > max_len:
        raise ValueError(
            f"cache too small: prompt window {T} + max_new_tokens {max_new} "
            f"+ gamma {gamma} exceeds max_len {max_len}"
        )

    t_dtype = target_params["embed"].dtype
    d_dtype = draft_params["embed"].dtype
    t_cache = init_cache(target_cfg, B, max_len, t_dtype)
    d_cache = init_cache(draft_cfg, B, max_len, d_dtype)

    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    rows = jnp.arange(B, dtype=jnp.int32)

    # Prefill both models; sample the first token from the TARGET.
    t_hidden, t_cache = forward(
        target_params, target_cfg, tokens, positions, t_cache
    )
    _, d_cache = forward(draft_params, draft_cfg, tokens, positions, d_cache)
    t_logits = unembed(target_params, target_cfg, t_hidden[rows, seq_lens - 1])

    key, k0 = jax.random.split(key)
    if greedy:
        first = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
    else:
        first = jax.random.categorical(
            k0, t_logits / sampling.temperature, axis=-1
        ).astype(jnp.int32)

    out_buf = jnp.full((B, max_new), eos_id, jnp.int32)
    out_buf = out_buf.at[:, 0].set(first)
    counts = jnp.ones((B,), jnp.int32)
    done = first == eos_id
    prev = first                      # last emitted token per row
    pos = seq_lens                    # position of `prev`

    def draft_step(carry, _):
        d_cache, tok, p, key = carry
        key, k = jax.random.split(key)
        hidden, d_cache = forward(
            draft_params, draft_cfg, tok[:, None], p[:, None], d_cache
        )
        logits = unembed(draft_params, draft_cfg, hidden[:, 0])
        if greedy:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            dist = jnp.zeros((B, 0), jnp.float32)     # unused in greedy mode
        else:
            dist = _token_probs(logits, sampling.temperature)  # [B, V]
            nxt = jax.random.categorical(
                k, logits / sampling.temperature, axis=-1
            ).astype(jnp.int32)
        return (d_cache, nxt, p + 1, key), (nxt, dist)

    def cond(state):
        done, it = state[5], state[8]
        return (~done.all()) & (it < max_new)

    def body(state):
        (t_cache, d_cache, out_buf, counts, prev, done, pos, key, it,
         acc_total, prop_total) = state

        # --- Draft gamma tokens (autoregressive, consumes prev → drafts). --
        key, kd = jax.random.split(key)
        (d_cache, _, _, _), (drafts, d_dists) = jax.lax.scan(
            draft_step, (d_cache, prev, pos, kd), None, length=gamma
        )
        drafts = drafts.T                              # [B, gamma]
        d_dists = jnp.swapaxes(d_dists, 0, 1)          # [B, gamma, V] (sampled)

        # --- Verify: ONE target forward over [prev, drafts] (gamma+1). ----
        window = jnp.concatenate([prev[:, None], drafts], axis=1)
        w_pos = pos[:, None] + jnp.arange(gamma + 1, dtype=jnp.int32)[None, :]
        t_hidden, t_cache = forward(
            target_params, target_cfg, window, w_pos, t_cache
        )
        t_logits = unembed(target_params, target_cfg, t_hidden)  # [B,γ+1,V]

        # Sync the draft cache over the same window: the draft scan only
        # wrote slots pos..pos+gamma-1 (each step writes the token it
        # consumes), so on full acceptance slot pos+gamma (the last draft)
        # would stay a permanent zero-KV hole — the next round starts past
        # it, draft predictions diverge, and acceptance silently collapses.
        _, d_cache = forward(draft_params, draft_cfg, window, w_pos, d_cache)

        # --- Acceptance. --------------------------------------------------
        key, ka = jax.random.split(key)
        if greedy:
            t_choice = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
            match = drafts == t_choice[:, :gamma]      # [B, gamma]
            acc = jnp.cumprod(match.astype(jnp.int32), axis=1)
            n_acc = jnp.sum(acc, axis=1)               # [B] accepted drafts
            # Token emitted after the accepted prefix: target's argmax at
            # the first mismatch — or the bonus token when all accepted.
            extra = t_choice[rows, n_acc]
        else:
            t_probs = _token_probs(t_logits, sampling.temperature)  # [B,γ+1,V]
            u = jax.random.uniform(ka, (B, gamma))
            accept = rejection_accept(t_probs, d_dists, drafts, u)
            acc = jnp.cumprod(accept.astype(jnp.int32), axis=1)
            n_acc = jnp.sum(acc, axis=1)
            # First rejection: sample the normalized residual
            # max(p_t - p_d, 0); all accepted: bonus-sample the target's
            # distribution at the extra position [Leviathan et al. 2023].
            dist = residual_extra_dist(t_probs, d_dists, n_acc)
            key, kr = jax.random.split(key)
            extra = jax.random.categorical(
                kr, jnp.log(jnp.maximum(dist, 1e-20)), axis=-1
            ).astype(jnp.int32)

        # --- Emit accepted drafts + the extra token. ----------------------
        emit = jnp.concatenate(
            [drafts, jnp.zeros((B, 1), jnp.int32)], axis=1
        )
        emit = emit.at[rows, n_acc].set(extra)         # [B, gamma+1]
        n_out = n_acc + 1

        new_out, new_counts, new_done = out_buf, counts, done
        eos_seen = jnp.zeros((B,), bool)
        for j in range(gamma + 1):
            tok_j = emit[:, j]
            valid = (j < n_out) & ~new_done & ~eos_seen
            idx = jnp.where(valid, counts + j, max_new)  # OOB → dropped
            new_out = new_out.at[rows, idx].set(tok_j, mode="drop")
            new_counts = new_counts + (valid & (idx < max_new)).astype(jnp.int32)
            eos_seen = eos_seen | (valid & (tok_j == eos_id))
        new_done = new_done | eos_seen | (new_counts >= max_new)

        # Rows continue from their last emitted token.
        last_idx = jnp.clip(new_counts - 1, 0, max_new - 1)
        new_prev = new_out[rows, last_idx]
        emitted = new_counts - counts
        new_pos = pos + jnp.where(done, 0, emitted)

        active = (~done).astype(jnp.int32)
        # Stats count only drafts that had a chance to be emitted (clip by
        # the row's remaining budget before the round — ADVICE r1): budget-
        # truncated tail drafts must neither inflate nor deflate the dial,
        # so a perfect draft still reads exactly 1.0 (the self-draft canary
        # in tests/test_speculative.py). Same convention as the engine
        # (engine._spec_step).
        budget = jnp.maximum(max_new - counts, 0)
        acc_total = acc_total + jnp.sum(active * jnp.minimum(n_acc, budget))
        prop_total = prop_total + jnp.sum(
            active * jnp.minimum(jnp.int32(gamma), budget)
        )

        return (
            t_cache, d_cache, new_out, new_counts, new_prev, new_done,
            new_pos, key, it + 1, acc_total, prop_total,
        )

    state = (t_cache, d_cache, out_buf, counts, prev, done, pos, key,
             jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
             jnp.zeros((), jnp.int32))
    state = jax.lax.while_loop(cond, body, state)
    out_buf, counts, acc_total, prop_total = state[2], state[3], state[9], state[10]
    if return_stats:
        return out_buf, counts, acc_total, prop_total
    return out_buf, counts
