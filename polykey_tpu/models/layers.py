"""Transformer layer primitives shared across model families.

Functional style: parameters are dict pytrees, every function is pure. All
linear weights use the [in_features, out_features] convention so matmuls are
plain `x @ w` and shard naturally under Megatron-style TP partition specs
(parallel/sharding.py). Layers are stacked on a leading axis and driven by
`lax.scan` in the family forward functions — one compiled block regardless of
depth, and a natural unit for pipeline-stage sharding.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..models.config import ModelConfig
from .quant import qdot


def rms_norm(
    x: jax.Array, weight: jax.Array, eps: float, offset: float = 0.0
) -> jax.Array:
    """RMSNorm with fp32 accumulation. Gemma stores weights as (1 + w), which
    callers express via offset=1.0."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * (offset + weight.astype(jnp.float32))).astype(x.dtype)


def rope(
    x: jax.Array,                # [B, T, H, D]
    positions: jax.Array,        # [B, T]
    theta: float,
) -> jax.Array:
    """Rotary position embedding, half-split (rotate-half) convention."""
    half = x.shape[-1] // 2
    freqs = theta ** (
        -jnp.arange(0, half, dtype=jnp.float32) / half
    )                                                    # [half]
    angles = positions[:, :, None].astype(jnp.float32) * freqs  # [B, T, half]
    cos = jnp.cos(angles)[:, :, None, :]                 # [B, T, 1, half]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


def _activate(x: jax.Array, activation: str) -> jax.Array:
    if activation == "silu":
        return jax.nn.silu(x)
    if activation == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    raise ValueError(f"unknown activation {activation!r}")


def mlp(p: dict, x: jax.Array, activation: str) -> jax.Array:
    """Gated MLP (SwiGLU / GeGLU): act(x@gate) * (x@up) @ down."""
    gate = _activate(qdot(x, p["gate"]), activation)
    return qdot(gate * qdot(x, p["up"]), p["down"])


def qkv_project(
    p: dict, x: jax.Array, cfg: ModelConfig
) -> tuple[jax.Array, jax.Array, jax.Array]:
    B, T, _ = x.shape
    q = qdot(x, p["wq"]).reshape(B, T, cfg.num_heads, cfg.head_dim)
    k = qdot(x, p["wk"]).reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    v = qdot(x, p["wv"]).reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    return q, k, v


def init_attention_params(
    key: jax.Array, cfg: ModelConfig, dtype=jnp.bfloat16
) -> dict:
    kq, kk, kv, ko = jax.random.split(key, 4)
    h, d = cfg.hidden_size, cfg.head_dim
    scale = h**-0.5
    return {
        "wq": jax.random.normal(kq, (h, cfg.num_heads * d), dtype) * scale,
        "wk": jax.random.normal(kk, (h, cfg.num_kv_heads * d), dtype) * scale,
        "wv": jax.random.normal(kv, (h, cfg.num_kv_heads * d), dtype) * scale,
        "wo": jax.random.normal(ko, (cfg.num_heads * d, h), dtype)
        * (cfg.num_heads * d) ** -0.5,
    }


def init_mlp_params(
    key: jax.Array, hidden: int, intermediate: int, dtype=jnp.bfloat16
) -> dict:
    kg, ku, kd = jax.random.split(key, 3)
    return {
        "gate": jax.random.normal(kg, (hidden, intermediate), dtype) * hidden**-0.5,
        "up": jax.random.normal(ku, (hidden, intermediate), dtype) * hidden**-0.5,
        "down": jax.random.normal(kd, (intermediate, hidden), dtype)
        * intermediate**-0.5,
    }


def layer_sliding_window(cfg: ModelConfig, layer_idx: jax.Array) -> Optional[jax.Array]:
    """Gemma-2 interleaves sliding-window (even) and global (odd) layers.

    Returns a per-layer window size as a traced scalar (or None when the
    config has no window). Global layers get window = max_seq_len, which is
    equivalent to no window.
    """
    if cfg.sliding_window is None:
        return None
    return jnp.where(layer_idx % 2 == 0, cfg.sliding_window, cfg.max_seq_len)
