"""Config-driven transformer forward pass covering the served families.

One implementation handles Llama-3 (GQA + RoPE + SwiGLU), Gemma-2 (post
norms, logit soft-capping, interleaved sliding-window layers, scaled
embeddings), and — via the MoE hook — Mixtral. Families are selected by
config (models/config.py registry), not by per-family modules.

TPU-first design choices:
- layers stacked on a leading axis, driven by `lax.scan`: one compiled block,
  natural pipeline-stage unit;
- static shapes only: right-padded batches, masks computed from absolute
  positions (never data-dependent shapes);
- KV cache is a plain pytree carried through scan; slot s always holds the
  token at absolute position s, so causal masking doubles as garbage-slot
  masking (see ops/attention.make_attention_mask);
- bf16 weights/activations, fp32 softmax/norm accumulation, fp32 logits.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from ..ops.attention import attention
from .config import ModelConfig
from .quant import embed_lookup, qdot, unembed_logits
from .layers import (
    init_attention_params,
    init_mlp_params,
    mlp,
    qkv_project,
    rms_norm,
    rope,
)


@struct.dataclass
class KVCache:
    """Contiguous per-layer KV cache: [num_layers, B, S, num_kv_heads, head_dim].

    The simple serving path (fixed-geometry batch, fixed max length). The
    continuous-batching engine replaces this with the paged cache
    (engine/kv_cache.py + ops/paged_attention.py).
    """

    k: jax.Array
    v: jax.Array

    @property
    def num_slots(self) -> int:
        return self.k.shape[2]


def init_cache(
    cfg: ModelConfig, batch: int, max_len: int, dtype=jnp.bfloat16
) -> KVCache:
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def init_params(key: jax.Array, cfg: ModelConfig, dtype=jnp.bfloat16) -> dict:
    """Random-init parameter pytree with layers stacked for scan."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    # rms_norm computes gain = offset + w (offset 1.0 for the Gemma storage
    # convention, models with scale_embeddings). Init w so the effective
    # gain is 1 — zero gains would make every hidden state identically
    # zero at init, turning random-init tests vacuous.
    norm_offset = 1.0 if cfg.scale_embeddings else 0.0
    norm_init = jnp.full((cfg.hidden_size,), 1.0 - norm_offset, dtype)

    def one_layer(k: jax.Array) -> dict:
        k_attn, k_mlp = jax.random.split(k)
        layer = {
            "attn": init_attention_params(k_attn, cfg, dtype),
            "ln1": norm_init,
            "ln2": norm_init,
        }
        if cfg.is_moe:
            k_router, k_experts = jax.random.split(k_mlp)
            layer["router"] = (
                jax.random.normal(k_router, (cfg.hidden_size, cfg.num_experts), dtype)
                * cfg.hidden_size**-0.5
            )
            layer["experts"] = jax.vmap(
                lambda kk: init_mlp_params(
                    kk, cfg.hidden_size, cfg.intermediate_size, dtype
                )
            )(jax.random.split(k_experts, cfg.num_experts))
        else:
            layer["mlp"] = init_mlp_params(
                k_mlp, cfg.hidden_size, cfg.intermediate_size, dtype
            )
        if cfg.use_post_norms:
            layer["post_ln1"] = norm_init
            layer["post_ln2"] = norm_init
        return layer

    layers = jax.vmap(one_layer)(jax.random.split(k_layers, cfg.num_layers))

    params = {
        "embed": jax.random.normal(
            k_embed, (cfg.vocab_size, cfg.hidden_size), dtype
        )
        * cfg.hidden_size**-0.5,
        "layers": layers,
        "final_norm": norm_init,
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = (
            jax.random.normal(k_head, (cfg.hidden_size, cfg.vocab_size), dtype)
            * cfg.hidden_size**-0.5
        )
    return params


def _moe_mlp(layer_params: dict, h: jax.Array, cfg: ModelConfig) -> jax.Array:
    from ..ops.moe import moe_mlp, moe_mlp_dispatch  # deferred import

    if cfg.moe_dispatch:
        return moe_mlp_dispatch(layer_params, h, cfg)
    return moe_mlp(layer_params, h, cfg)


def _layer_window(cfg: ModelConfig, layer_idx: jax.Array):
    """Gemma-2 interleaving: even layers sliding-window, odd layers global."""
    if cfg.sliding_window is None:
        return None
    return jnp.where(layer_idx % 2 == 0, cfg.sliding_window, cfg.max_seq_len)


def embed_tokens(params: dict, cfg: ModelConfig, tokens: jax.Array) -> jax.Array:
    """Token embedding lookup (+ Gemma's sqrt(H) scaling)."""
    x = embed_lookup(params["embed"], tokens)
    if cfg.scale_embeddings:
        x = (x.astype(jnp.float32) * cfg.hidden_size**0.5).astype(x.dtype)
    return x


def apply_layer(layer_params, layer_idx, x, positions, cfg: ModelConfig, attend, kc, vc):
    """One transformer block at absolute layer index `layer_idx`.

    Norms, projections, RoPE, residuals, MLP/MoE, and Gemma post-norms live
    here; the KV mechanics are injected via
    `attend(layer_idx, q, k, v, kc, vc) → (ctx, kc, vc)`. Shared by the
    scanned stack (_run_stack) and the pipeline-parallel stage bodies
    (parallel/pipeline.py), so a stage runs the exact computation the
    unsharded stack runs.
    """
    B, T = x.shape[:2]
    norm_offset = 1.0 if cfg.scale_embeddings else 0.0
    eps = cfg.rms_norm_eps

    h = rms_norm(x, layer_params["ln1"], eps, norm_offset)
    q, k, v = qkv_project(layer_params["attn"], h, cfg)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)

    ctx, kc, vc = attend(layer_idx, q, k, v, kc, vc)

    attn_out = ctx.reshape(B, T, cfg.num_heads * cfg.head_dim)
    attn_out = qdot(attn_out, layer_params["attn"]["wo"])
    if cfg.use_post_norms:
        attn_out = rms_norm(attn_out, layer_params["post_ln1"], eps, norm_offset)
    x = x + attn_out

    h = rms_norm(x, layer_params["ln2"], eps, norm_offset)
    if cfg.is_moe:
        mlp_out = _moe_mlp(layer_params, h, cfg)
    else:
        mlp_out = mlp(layer_params["mlp"], h, cfg.activation)
    if cfg.use_post_norms:
        mlp_out = rms_norm(mlp_out, layer_params["post_ln2"], eps, norm_offset)
    x = x + mlp_out

    return x, kc, vc


def _run_stack(params, cfg: ModelConfig, tokens, positions, kv_scanned, attend):
    """Shared transformer stack: embed → scan(layer body) → final norm."""
    norm_offset = 1.0 if cfg.scale_embeddings else 0.0
    eps = cfg.rms_norm_eps

    x = embed_tokens(params, cfg, tokens)

    def body(x, scanned):
        layer_params, layer_idx, kc, vc = scanned
        x, kc, vc = apply_layer(
            layer_params, layer_idx, x, positions, cfg, attend, kc, vc
        )
        return x, (kc, vc)

    layer_ids = jnp.arange(cfg.num_layers, dtype=jnp.int32)
    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], layer_ids) + kv_scanned
    )
    x = rms_norm(x, params["final_norm"], eps, norm_offset)
    return x, new_k, new_v


def make_causal_attend(cfg: ModelConfig, positions: jax.Array):
    """No-cache causal attention closure over `positions` [B, T]: attention
    spans the current tokens only, masked by position (with Gemma's
    per-layer sliding-window interleaving). The training/scoring attend;
    pipeline stages (parallel/pipeline.py) build one per microbatch."""
    q_pos = positions[:, :, None]                       # [B, T, 1]
    kv_pos = positions[:, None, :]                      # [B, 1, S]

    def attend(layer_idx, q, k, v, kc, vc):
        mask = kv_pos <= q_pos
        window = _layer_window(cfg, layer_idx)
        if window is not None:
            mask &= kv_pos > q_pos - window
        ctx = attention(
            q, k, v, mask,
            scale=cfg.q_scale, logit_softcap=cfg.attn_logit_softcap,
        )
        return ctx, kc, vc

    return attend


def forward(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,               # [B, T] int32, right-padded
    positions: jax.Array,            # [B, T] absolute positions
    cache: Optional[KVCache] = None,
    attn_override=None,              # (layer_idx, q, k, v) → ctx; no-cache only
) -> tuple[jax.Array, Optional[KVCache]]:
    """Run the stack; returns (hidden [B, T, H], updated cache).

    With a cache: new K/V are written at their absolute positions and
    attention spans all cache slots — prefill and decode share this path.
    Without a cache (training / one-shot scoring): attention spans the
    current sequence only; `attn_override` swaps the attention computation
    (the sequence-parallel ring path, ops/ring_attention.py, mounts here).
    """
    B = tokens.shape[0]
    use_cache = cache is not None
    if use_cache and attn_override is not None:
        raise ValueError(
            "attn_override applies to the no-cache path only (the cached "
            "path would silently ignore it and run full attention over the "
            "gathered cache, defeating the override's purpose)"
        )
    batch_idx = jnp.arange(B, dtype=jnp.int32)[:, None]

    if use_cache:
        # Inference-only path → flash kernel is safe (no VJP needed); it
        # falls back to the reference attention off-TPU and for tiny shapes.
        from ..ops.flash_attention import flash_attention

        def attend(layer_idx, q, k, v, kc, vc):
            kc = kc.at[batch_idx, positions].set(k)
            vc = vc.at[batch_idx, positions].set(v)
            ctx = flash_attention(
                q, kc, vc, positions,
                scale=cfg.q_scale,
                logit_softcap=cfg.attn_logit_softcap,
                window=_layer_window(cfg, layer_idx),
            )
            return ctx, kc, vc

        kv_scanned = (cache.k, cache.v)
    else:
        causal = make_causal_attend(cfg, positions)

        def attend(layer_idx, q, k, v, kc, vc):
            if attn_override is not None:
                return attn_override(layer_idx, q, k, v), kc, vc
            return causal(layer_idx, q, k, v, kc, vc)

        empty = jnp.zeros((cfg.num_layers, 0), dtype=jnp.float32)
        kv_scanned = (empty, empty)

    x, new_k, new_v = _run_stack(params, cfg, tokens, positions, kv_scanned, attend)
    new_cache = KVCache(k=new_k, v=new_v) if use_cache else None
    return x, new_cache


def forward_paged(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,               # [B, T] int32, right-padded
    positions: jax.Array,            # [B, T] absolute positions
    paged,                           # engine.kv_cache.PagedKV
    page_tables: jax.Array,          # [B, P] int32
    mesh=None,                       # serving mesh → shard_map the kernels
):
    """Forward pass over the paged KV cache (serving path).

    Same computation as `forward`-with-cache, but KV lives in the shared page
    pools and is addressed through per-sequence page tables — the layout the
    continuous-batching engine composes decode batches from. Used both for
    prefill (T = prompt bucket) and batched decode (T = 1).

    `mesh` (static at the engine's jit boundary) lets the Pallas kernels
    run under shard_map when tp/dp/sp extents exceed 1 — GSPMD cannot
    partition an opaque pallas_call; the jnp paths need no help.
    """
    from ..ops.paged_attention import paged_attention, paged_write
    from ..ops.paged_attention_kernel import paged_attention_decode

    decode = tokens.shape[1] == 1

    def attend(layer_idx, q, k, v, kc, vc):
        kc, vc = paged_write(kc, vc, k, v, page_tables, positions, mesh=mesh)
        # Single-token steps take the DMA decode kernel (reads only valid
        # pages); prefill buckets take the gather path (wide T amortizes
        # the window materialization, and flash covers contiguous prefill).
        op = paged_attention_decode if decode else paged_attention
        ctx = op(
            q, kc, vc, page_tables, positions,
            scale=cfg.q_scale,
            logit_softcap=cfg.attn_logit_softcap,
            window=_layer_window(cfg, layer_idx),
            mesh=mesh,
        )
        return ctx, kc, vc

    if paged.quantized:
        # int8 KV: the per-layer cache operand is a (values, scales)
        # pair; the write/read ops dispatch on the pair form and the
        # scale pools ride the same scan/donation plumbing.
        kv_scanned = ((paged.k, paged.ks), (paged.v, paged.vs))
        x, new_k, new_v = _run_stack(
            params, cfg, tokens, positions, kv_scanned, attend
        )
        return x, type(paged)(
            k=new_k[0], v=new_v[0], ks=new_k[1], vs=new_v[1]
        )
    x, new_k, new_v = _run_stack(
        params, cfg, tokens, positions, (paged.k, paged.v), attend
    )
    return x, type(paged)(k=new_k, v=new_v)


def forward_ragged(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,               # [T] int32 flat token stream
    positions: jax.Array,            # [T] int32 absolute positions
    paged,                           # engine.kv_cache.PagedKV
    token_tables: jax.Array,         # [T, P] int32 per-TOKEN table rows
    seq_starts: jax.Array,           # [S] int32 ragged range starts
    seq_lens: jax.Array,             # [S] int32 new-token counts
    kv_lens: jax.Array,              # [S] int32 KV lengths (new incl.)
    page_tables: jax.Array,          # [S, P] int32 per-SEQUENCE tables
    mesh=None,
):
    """Forward pass over a RAGGED flat token stream (ISSUE 12): mixed
    prefill and decode tokens from many sequences in ONE dispatch, each
    attending over its own paged-KV window.

    Position-wise compute (embed, norms, projections, RoPE, MLP) runs on
    the stream as a [1, T] batch — per-row math identical to the
    bucketed paths. KV writes go through paged_write's per-token path
    (one batch row per token: B=T, T=1 — the decode write shape, so the
    TPU write kernel serves it unchanged); attention goes through
    ragged_paged_attention (kernel on TPU, per-token gather fallback
    elsewhere — the bit-identity reference). Padding rows carry
    position 0 and all-garbage table rows: they write to and attend over
    the reserved garbage page, exactly like inactive decode lanes.

    Returns (hidden [T, H], updated paged)."""
    from ..ops.paged_attention import paged_write
    from ..ops.ragged_paged_attention_kernel import (
        ragged_gather_attention,
        ragged_paged_attention,
        use_ragged_kernel,
    )

    T = tokens.shape[0]
    pos_row = positions.reshape(T, 1)

    data_pool = paged.k[0] if paged.quantized else paged.k
    Hk, D = data_pool.shape[2], data_pool.shape[3]
    # The ragged kernel runs un-shard_mapped (GSPMD cannot partition an
    # opaque pallas_call, and no shard_map wrapping exists for the flat
    # stream yet): ANY mesh extent > 1 — tp included — routes to the
    # gather path, whose gathers/scatters GSPMD partitions as-is. A
    # shard_mapped tp ragged kernel is first-hardware-window work.
    kernel_ok = use_ragged_kernel(Hk, D) and (
        mesh is None
        or all(
            mesh.shape.get(ax, 1) == 1 for ax in ("dp", "sp", "pp", "tp")
        )
    )

    def attend(layer_idx, q, k, v, kc, vc):
        # One batch row per token: the decode write shape (T==1 path).
        kc, vc = paged_write(
            kc, vc,
            k.reshape(T, 1, *k.shape[2:]), v.reshape(T, 1, *v.shape[2:]),
            token_tables, pos_row, mesh=mesh,
        )
        window = _layer_window(cfg, layer_idx)
        if kernel_ok:
            ctx = ragged_paged_attention(
                q[0], kc, vc, page_tables, seq_starts, seq_lens, kv_lens,
                scale=cfg.q_scale,
                logit_softcap=cfg.attn_logit_softcap,
                window=window, force_kernel=True,
            )
        else:
            ctx = ragged_gather_attention(
                q[0], kc, vc, token_tables, positions,
                scale=cfg.q_scale,
                logit_softcap=cfg.attn_logit_softcap,
                window=window,
            )
        return ctx[None], kc, vc

    if paged.quantized:
        kv_scanned = ((paged.k, paged.ks), (paged.v, paged.vs))
        x, new_k, new_v = _run_stack(
            params, cfg, tokens[None], positions[None], kv_scanned, attend
        )
        return x[0], type(paged)(
            k=new_k[0], v=new_v[0], ks=new_k[1], vs=new_v[1]
        )
    x, new_k, new_v = _run_stack(
        params, cfg, tokens[None], positions[None], (paged.k, paged.v),
        attend
    )
    return x[0], type(paged)(k=new_k, v=new_v)


def make_sp_override(
    cfg: ModelConfig, mesh, positions: jax.Array, impl: str = "ring"
):
    """Build an attn_override routing attention through a sequence-parallel
    path over the mesh's sp axis: ``impl="ring"`` rotates KV via ppermute
    (ops/ring_attention.py — any head count, sp-1 hops), ``impl="ulysses"``
    re-shards heads via all-to-all (ops/ulysses_attention.py — two
    collectives, needs per-device head counts divisible by sp).

    Lives here so the attention-parameter wiring (q_scale, soft-cap,
    per-layer window interleaving) stays in one module with the dense
    attend closures; callers (train/train.py) just mount the result.
    Returns None when the mesh has no sp extent.
    """
    if mesh is None or mesh.shape.get("sp", 1) <= 1:
        return None
    if impl == "ring":
        from ..ops.ring_attention import ring_attention_spmd as sp_attention
    elif impl == "ulysses":
        from ..ops.ulysses_attention import (
            ulysses_attention_spmd as sp_attention,
        )
    else:
        raise ValueError(f"unknown sp attention impl {impl!r}")

    def override(layer_idx, q, k, v):
        return sp_attention(
            q, k, v, positions, positions, mesh,
            scale=cfg.q_scale,
            logit_softcap=cfg.attn_logit_softcap,
            window=_layer_window(cfg, layer_idx),
        )

    return override


def make_ring_override(cfg: ModelConfig, mesh, positions: jax.Array):
    """Back-compat alias for make_sp_override(impl="ring")."""
    return make_sp_override(cfg, mesh, positions, impl="ring")


def unembed(params: dict, cfg: ModelConfig, hidden: jax.Array) -> jax.Array:
    """Project hidden states to vocab logits (fp32), applying Gemma's final
    soft-cap. Callers gather the positions they need *before* unembedding —
    at 128k-256k vocab the [B, T, V] matmul is the expensive part."""
    if cfg.tie_embeddings:
        logits = unembed_logits(hidden, params["embed"], tied=True)
    else:
        logits = unembed_logits(hidden, params["lm_head"], tied=False)
    if cfg.final_logit_softcap is not None:
        logits = cfg.final_logit_softcap * jnp.tanh(
            logits / cfg.final_logit_softcap
        )
    return logits
