"""Model architecture configs + registry.

Covers the three served families from BASELINE.json's measurement configs
(Llama-3-8B, Mixtral-8x7B, Gemma-2-27B) plus scaled-down variants of each for
CPU tests and single-chip experiments. Hyperparameters follow the public
model cards / HF config.json values.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    max_seq_len: int = 8192
    rope_theta: float = 500_000.0
    rms_norm_eps: float = 1e-5
    tie_embeddings: bool = False
    activation: str = "silu"            # "gelu_tanh" for gemma
    # Gemma-2 specifics
    attn_logit_softcap: Optional[float] = None
    final_logit_softcap: Optional[float] = None
    sliding_window: Optional[int] = None      # even layers use the window
    query_pre_attn_scalar: Optional[float] = None
    use_post_norms: bool = False              # post-attn/post-mlp RMSNorms
    scale_embeddings: bool = False            # multiply embeds by sqrt(hidden)
    # MoE (Mixtral) specifics
    num_experts: int = 0
    num_experts_per_tok: int = 0
    # Capacity-bucketed sparse dispatch (ops/moe.py: moe_mlp_dispatch) instead
    # of the einsum-dense formulation. On for real MoE sizes — dense pays
    # num_experts/top_k x the dispatch FLOPs; off for tiny test configs,
    # where dispatch's token-drop-on-overflow would perturb exactness checks.
    moe_dispatch: bool = False

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def q_scale(self) -> float:
        if self.query_pre_attn_scalar is not None:
            return self.query_pre_attn_scalar**-0.5
        return self.head_dim**-0.5

    def num_params(self) -> int:
        """Approximate parameter count (embeddings + blocks + head)."""
        embed = self.vocab_size * self.hidden_size
        attn = self.hidden_size * self.head_dim * (
            self.num_heads * 2 + self.num_kv_heads * 2
        )
        if self.is_moe:
            mlp = 3 * self.hidden_size * self.intermediate_size * self.num_experts
            mlp += self.hidden_size * self.num_experts  # router
        else:
            mlp = 3 * self.hidden_size * self.intermediate_size
        norms = self.hidden_size * (4 if self.use_post_norms else 2)
        block = attn + mlp + norms
        head = 0 if self.tie_embeddings else embed
        return embed + self.num_layers * block + self.hidden_size + head

    def num_active_params(self) -> int:
        """Parameters touched per token: for MoE, only the router plus the
        top-k routed experts count (roofline math — per-token FLOPs scale
        with active params, not total)."""
        if not self.is_moe:
            return self.num_params()
        return self.num_params() - (
            self.num_layers * 3 * self.hidden_size * self.intermediate_size
            * (self.num_experts - self.num_experts_per_tok))


LLAMA3_8B = ModelConfig(
    name="llama-3-8b",
    vocab_size=128_256,
    hidden_size=4096,
    intermediate_size=14_336,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    head_dim=128,
    max_seq_len=8192,
    rope_theta=500_000.0,
)

LLAMA3_70B = ModelConfig(
    name="llama-3-70b",
    vocab_size=128_256,
    hidden_size=8192,
    intermediate_size=28_672,
    num_layers=80,
    num_heads=64,
    num_kv_heads=8,
    head_dim=128,
    max_seq_len=8192,
    rope_theta=500_000.0,
)

LLAMA32_1B = ModelConfig(
    name="llama-3.2-1b",
    vocab_size=128_256,
    hidden_size=2048,
    intermediate_size=8192,
    num_layers=16,
    num_heads=32,
    num_kv_heads=8,
    head_dim=64,
    max_seq_len=8192,
    rope_theta=500_000.0,
    tie_embeddings=True,
)

MIXTRAL_8X7B = ModelConfig(
    name="mixtral-8x7b",
    vocab_size=32_000,
    hidden_size=4096,
    intermediate_size=14_336,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    head_dim=128,
    max_seq_len=8192,
    rope_theta=1_000_000.0,
    num_experts=8,
    num_experts_per_tok=2,
    moe_dispatch=True,
)

GEMMA2_27B = ModelConfig(
    name="gemma-2-27b",
    vocab_size=256_128,
    hidden_size=4608,
    intermediate_size=36_864,
    num_layers=46,
    num_heads=32,
    num_kv_heads=16,
    head_dim=128,
    max_seq_len=8192,
    rope_theta=10_000.0,
    rms_norm_eps=1e-6,
    tie_embeddings=True,
    activation="gelu_tanh",
    attn_logit_softcap=50.0,
    final_logit_softcap=30.0,
    sliding_window=4096,
    query_pre_attn_scalar=144.0,  # hidden_size / num_heads
    use_post_norms=True,
    scale_embeddings=True,
)

GEMMA2_2B = ModelConfig(
    # The family's small member (HF gemma-2-2b config.json values) — the
    # natural speculative DRAFT for gemma-2-9b/27b (same 256k vocab).
    name="gemma-2-2b",
    vocab_size=256_128,
    hidden_size=2304,
    intermediate_size=9216,
    num_layers=26,
    num_heads=8,
    num_kv_heads=4,
    head_dim=256,
    max_seq_len=8192,
    rope_theta=10_000.0,
    rms_norm_eps=1e-6,
    tie_embeddings=True,
    activation="gelu_tanh",
    attn_logit_softcap=50.0,
    final_logit_softcap=30.0,
    sliding_window=4096,
    query_pre_attn_scalar=256.0,
    use_post_norms=True,
    scale_embeddings=True,
)

GEMMA2_9B = ModelConfig(
    name="gemma-2-9b",
    vocab_size=256_128,
    hidden_size=3584,
    intermediate_size=14_336,
    num_layers=42,
    num_heads=16,
    num_kv_heads=8,
    head_dim=256,
    max_seq_len=8192,
    rope_theta=10_000.0,
    rms_norm_eps=1e-6,
    tie_embeddings=True,
    activation="gelu_tanh",
    attn_logit_softcap=50.0,
    final_logit_softcap=30.0,
    sliding_window=4096,
    query_pre_attn_scalar=256.0,
    use_post_norms=True,
    scale_embeddings=True,
)

# Scaled-down variants: same architectural features, CPU-testable sizes.
TINY_LLAMA = ModelConfig(
    name="tiny-llama",
    vocab_size=512,
    hidden_size=64,
    intermediate_size=128,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    head_dim=16,
    max_seq_len=128,
    rope_theta=10_000.0,
)

TINY_MIXTRAL = replace(
    TINY_LLAMA,
    name="tiny-mixtral",
    num_experts=4,
    num_experts_per_tok=2,
)

TINY_GEMMA = replace(
    TINY_LLAMA,
    name="tiny-gemma",
    tie_embeddings=True,
    activation="gelu_tanh",
    attn_logit_softcap=50.0,
    final_logit_softcap=30.0,
    sliding_window=16,
    query_pre_attn_scalar=16.0,
    use_post_norms=True,
    scale_embeddings=True,
)

# A mid-size llama for single-chip benchmarking without 8B's 16 GiB of bf16
# weights (v5e has 16 GiB HBM; 8B serves in int8 — see engine docs).
LLAMA_1B_BENCH = replace(LLAMA32_1B, name="llama-1b-bench")

# Mixtral ARCHITECTURE (8 experts, top-2, dispatch routing) scaled to
# ~4.7 B params so the int8 tree (~4.7 GiB) + KV fits one v5e chip:
# hardware evidence for measurement config 4's mechanism (MoE routing +
# grouped expert matmuls) without 8x7B's 47 B params, which need tp>=4.
MIXTRAL_BENCH = replace(
    MIXTRAL_8X7B,
    name="mixtral-bench",
    hidden_size=2048,
    intermediate_size=5632,
    num_layers=16,
    num_heads=32,
    num_kv_heads=8,
    head_dim=64,
)

MODEL_REGISTRY = {
    cfg.name: cfg
    for cfg in (
        LLAMA3_8B,
        LLAMA3_70B,
        LLAMA32_1B,
        MIXTRAL_8X7B,
        GEMMA2_27B,
        GEMMA2_9B,
        GEMMA2_2B,
        TINY_LLAMA,
        TINY_MIXTRAL,
        TINY_GEMMA,
        LLAMA_1B_BENCH,
        MIXTRAL_BENCH,
    )
}


def get_config(name: str) -> ModelConfig:
    try:
        return MODEL_REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown model {name!r}; known: {sorted(MODEL_REGISTRY)}"
        ) from None
