"""Int8 / int4 weight-only quantization for serving.

One v5e chip has 16 GiB HBM; Llama-3-8B in bf16 is ~16 GiB of weights alone,
so the single-chip serving story for 8B-class models (BASELINE.md config 2)
is int8 weights: per-output-channel symmetric scales, dequantized on the fly
inside the matmul (`(x @ q) * s` — XLA fuses the int8→bf16 cast into the
MXU feed, so HBM traffic halves, which is the whole game for bandwidth-bound
decode). Activations stay bf16; norms/router stay fp (negligible bytes).

int4 (POLYKEY_QUANTIZE=int4) halves weight traffic again — the lever for
beating, not just meeting, the weight-bandwidth-bound throughput target.
Because 4-bit symmetric ([-7, 7]) is too coarse for a whole contraction
axis, int4 uses GROUP-WISE scales (group_size along the contraction axis,
AWQ/GPTQ granularity): q stores two nibbles per uint8 byte, packed in
PAIRS ALONG THE CONTRACTION AXIS ([..., in/2, out] — NOT jnp.int4, which
the axon remote backend rejects at dispatch and which gains nothing: the
manual unpack (mask/shift/sign-extend) is elementwise and fuses into the
dot's operand load exactly like an s4→bf16 cast would). s is
[..., in/g, out], and dequantization happens on the weight side
(`x @ (q·s)`). The embedding and lm_head stay int8: the embedding is a
sparse gather (bandwidth-irrelevant), and the unembed keeps its exact
narrow-operand fp32-accumulate path.

Representation: a `QuantizedTensor` pytree leaf-pair (int values + fp32
scales) that flows through jit/sharding like any array pair. The matmul
seam is `qdot` — every linear in layers.py/transformer.py routes through it
and dispatches on type, so the same forward serves fp, int8, and int4
trees. Group-wise `s` has the same rank as `q` with the group axis in the
contraction position, so row-parallel (Megatron) sharding of the
contraction axis shards the groups consistently.

The reference has no quantization (25 Go files, no ML — SURVEY.md §2); this
is owed to the north star's single-chip 8B serving target.
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp
from flax import struct

from .config import ModelConfig


@struct.dataclass
class QuantizedTensor:
    """Int8/int4 weights with fp32 scales.

    q: bits=8: int8 [..., in, out] (weight shape); bits=4: uint8
       [..., in/2, out] — nibble pairs packed along the contraction axis
       (row 2i in the low nibble, row 2i+1 in the high nibble).
    s: fp32 scales —
       bits=8: [..., out], per-output-channel over the contraction axis;
       bits=4: [..., in/group, out], group-wise along the contraction
       axis (same rank as q, group axis in the contraction position).
    act_dtype: the pre-quantization weight dtype; dequantization targets it
    so an fp32-configured model is not silently narrowed to bf16 (and
    callers sizing KV caches off params["embed"].dtype see the activation
    dtype, not the fp32 scales).
    """

    q: jax.Array
    s: jax.Array
    act_dtype: jnp.dtype = struct.field(pytree_node=False, default=jnp.bfloat16)
    bits: int = struct.field(pytree_node=False, default=8)

    @property
    def shape(self):
        if self.bits == 4:
            # Logical weight shape — the packed contraction axis unfolds.
            return (*self.q.shape[:-2], self.q.shape[-2] * 2,
                    self.q.shape[-1])
        return self.q.shape

    @property
    def dtype(self):
        return jnp.dtype(self.act_dtype)


def quantize(
    w: jax.Array, bits: int = 8, group_size: int = 128
) -> QuantizedTensor:
    """Symmetric quantization of [..., in, out].

    bits=8: per-output-channel scales. bits=4: group-wise scales along
    the contraction axis (group_size, shrunk to the full axis when it
    doesn't divide — tiny test models)."""
    if bits == 8:
        absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2)  # [..., out]
        scale = jnp.maximum(absmax, 1e-8) / 127.0
        q = jnp.clip(
            jnp.round(w.astype(jnp.float32) / scale[..., None, :]), -127, 127
        ).astype(jnp.int8)
        return QuantizedTensor(q=q, s=scale, act_dtype=jnp.dtype(w.dtype))
    if bits != 4:
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    cin = w.shape[-2]
    if cin % 2:
        raise ValueError(
            f"int4 needs an even contraction axis to nibble-pack, got {cin}"
        )
    g = group_size if cin % group_size == 0 else cin
    wf = w.astype(jnp.float32)
    grouped = wf.reshape(*w.shape[:-2], cin // g, g, w.shape[-1])
    absmax = jnp.max(jnp.abs(grouped), axis=-2)            # [..., G, out]
    scale = jnp.maximum(absmax, 1e-8) / 7.0
    q = jnp.clip(
        jnp.round(grouped / scale[..., None, :]), -7, 7
    ).reshape(w.shape).astype(jnp.int8)
    # Nibble-pack contraction-axis pairs: row 2i → low, row 2i+1 → high
    # (two's-complement nibbles via the uint8 wrap).
    pairs = q.reshape(*w.shape[:-2], cin // 2, 2, w.shape[-1])
    packed = (
        (pairs[..., 0, :].astype(jnp.uint8) & 0xF)
        | ((pairs[..., 1, :].astype(jnp.uint8) & 0xF) << 4)
    )
    return QuantizedTensor(
        q=packed, s=scale, act_dtype=jnp.dtype(w.dtype), bits=4
    )


def dequantize(w: QuantizedTensor, dtype=jnp.bfloat16) -> jax.Array:
    if w.bits == 4:
        # One group-layout implementation only — qdot's fused path and
        # this reference must never drift apart.
        return _deq_weight(w, jnp.float32).astype(dtype)
    return (w.q.astype(jnp.float32) * w.s[..., None, :]).astype(dtype)


WeightLike = Union[jax.Array, QuantizedTensor]


def _deq_weight(w: QuantizedTensor, dtype) -> jax.Array:
    """Weight-side group-wise dequantization in the activation dtype — an
    elementwise producer (unpack + scale) XLA fuses into the consuming
    dot's operand load, so HBM traffic stays packed nibbles + small
    scales."""
    p = w.q                                       # [..., in/2, out] uint8
    low = (p & 0xF).astype(jnp.int8)
    high = (p >> 4).astype(jnp.int8)
    low = jnp.where(low > 7, low - 16, low)       # sign-extend the nibble
    high = jnp.where(high > 7, high - 16, high)
    q = jnp.stack([low, high], axis=-2)           # [..., in/2, 2, out]
    shape = w.shape                               # logical [..., in, out]
    q = q.reshape(shape)
    G = w.s.shape[-2]
    cin, cout = shape[-2], shape[-1]
    grouped = q.reshape(*shape[:-2], G, cin // G, cout).astype(dtype)
    return (grouped * w.s[..., None, :].astype(dtype)).reshape(shape)


def qdot(x: jax.Array, w: WeightLike) -> jax.Array:
    """x @ w with on-the-fly dequantization for QuantizedTensor weights.

    int8 scales fold AFTER the matmul (per-output-channel); int4 scales
    vary along the contraction axis, so dequantization moves to the
    weight side of the dot."""
    if isinstance(w, QuantizedTensor):
        if w.bits == 4:
            return x @ _deq_weight(w, x.dtype)
        y = x @ w.q.astype(x.dtype)
        return y * w.s.astype(x.dtype)
    return x @ w


def qeinsum_expert(
    pattern: str, x: jax.Array, w: WeightLike, e_axis: int, **kwargs
):
    """Expert-stacked einsum: int8 scales are [E, out]; `e_axis` names the
    expert axis in the OUTPUT (out is always last). Covers both MoE
    formulations: 'bth,ehi->beti' (e_axis=1) and the dispatch path
    'ech,ehi->eci' (e_axis=0). int4 dequantizes weight-side (group axis
    inside the expert stack)."""
    if isinstance(w, QuantizedTensor):
        if w.bits == 4:
            return jnp.einsum(pattern, x, _deq_weight(w, x.dtype), **kwargs)
        y = jnp.einsum(pattern, x, w.q.astype(x.dtype), **kwargs)
        shape = [1] * y.ndim
        shape[e_axis] = w.s.shape[0]
        shape[-1] = w.s.shape[-1]
        return y * w.s.reshape(shape).astype(y.dtype)
    return jnp.einsum(pattern, x, w, **kwargs)


def embed_lookup(embed: WeightLike, tokens: jax.Array) -> jax.Array:
    """Embedding row lookup; scales are per hidden channel ([H] — the same
    axis the tied unembed contracts, so one tensor serves both uses)."""
    if isinstance(embed, QuantizedTensor):
        rows = embed.q[tokens]                         # int8 [..., H]
        return rows.astype(embed.dtype) * embed.s.astype(embed.dtype)
    return embed[tokens]


def unembed_logits(hidden: jax.Array, embed_or_head: WeightLike, tied: bool):
    """fp32 vocab logits from either a tied embedding ('...h,vh->...v') or an
    lm_head ('...h,hv->...v'), quantized or not."""
    if isinstance(embed_or_head, QuantizedTensor):
        # int8 values (|q| <= 127) are exact in bf16, so the vocab matmul —
        # the hottest step at 128k-256k vocab — keeps narrow operands and
        # accumulates fp32 via preferred_element_type, like the fp path.
        wdt = embed_or_head.dtype
        if tied:
            # Tied: q is [V, H], scales are [H] (contraction axis) — fold the
            # scale into the activation before the matmul.
            scaled = hidden.astype(jnp.float32) * embed_or_head.s
            return jnp.einsum(
                "...h,vh->...v", scaled.astype(wdt),
                embed_or_head.q.astype(wdt),
                preferred_element_type=jnp.float32,
            )
        y = jnp.einsum(
            "...h,hv->...v", hidden.astype(wdt),
            embed_or_head.q.astype(wdt),
            preferred_element_type=jnp.float32,
        )
        return y * embed_or_head.s
    if tied:
        return jnp.einsum(
            "...h,vh->...v", hidden, embed_or_head,
            preferred_element_type=jnp.float32,
        )
    return jnp.einsum(
        "...h,hv->...v", hidden, embed_or_head,
        preferred_element_type=jnp.float32,
    )


_QUANT_LEAVES = ("wq", "wk", "wv", "wo", "gate", "up", "down", "lm_head")


def quantize_params(params: dict, cfg: ModelConfig, bits: int = 8) -> dict:
    """Quantize every linear weight in the tree; norms, router, and biases
    stay fp. The embedding is quantized per hidden channel so the same
    tensor serves lookup and (tied) unembedding. With bits=4 the BLOCK
    linears go int4 group-wise; embed/lm_head stay int8 (sparse gather +
    the exact narrow-operand unembed path — see module docstring)."""

    def walk(node):
        if isinstance(node, dict):
            out = {}
            for name, child in node.items():
                if name in _QUANT_LEAVES and isinstance(child, jax.Array):
                    leaf_bits = 8 if name == "lm_head" else bits
                    out[name] = quantize(child, bits=leaf_bits)
                else:
                    # Covers the experts subtree too: gate/up/down are in
                    # _QUANT_LEAVES and quantize() handles the leading
                    # [L, E, ...] stack axes (scale reduces axis=-2 only).
                    out[name] = walk(child)
            return out
        return node

    out = walk(params)
    embed = params["embed"]                            # [V, H]
    absmax = jnp.max(jnp.abs(embed.astype(jnp.float32)), axis=0)  # [H]
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(embed.astype(jnp.float32) / scale[None, :]), -127, 127
    ).astype(jnp.int8)
    out["embed"] = QuantizedTensor(q=q, s=scale, act_dtype=jnp.dtype(embed.dtype))
    return out


def params_bytes(params) -> int:
    """Total parameter storage in bytes (quantized trees count q + s).
    int4 leaves are packed uint8 (two nibbles per byte), so plain
    size x itemsize is already the HBM truth."""
    return sum(
        leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(params)
    )
