"""Int8 weight-only quantization for serving.

One v5e chip has 16 GiB HBM; Llama-3-8B in bf16 is ~16 GiB of weights alone,
so the single-chip serving story for 8B-class models (BASELINE.md config 2)
is int8 weights: per-output-channel symmetric scales, dequantized on the fly
inside the matmul (`(x @ q) * s` — XLA fuses the int8→bf16 cast into the
MXU feed, so HBM traffic halves, which is the whole game for bandwidth-bound
decode). Activations stay bf16; norms/router stay fp (negligible bytes).

Representation: a `QuantizedTensor` pytree leaf-pair (int8 values + fp32
scales) that flows through jit/sharding like any array pair. The matmul
seam is `qdot` — every linear in layers.py/transformer.py routes through it
and dispatches on type, so the same forward serves fp and int8 trees.

The reference has no quantization (25 Go files, no ML — SURVEY.md §2); this
is owed to the north star's single-chip 8B serving target.
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp
from flax import struct

from .config import ModelConfig


@struct.dataclass
class QuantizedTensor:
    """Int8 weights with per-output-channel fp32 scales.

    q: int8, original weight shape [..., in, out]
    s: fp32, [..., out] — scale over the contraction (in) axis.
    act_dtype: the pre-quantization weight dtype; dequantization targets it
    so an fp32-configured model is not silently narrowed to bf16 (and
    callers sizing KV caches off params["embed"].dtype see the activation
    dtype, not the fp32 scales).
    """

    q: jax.Array
    s: jax.Array
    act_dtype: jnp.dtype = struct.field(pytree_node=False, default=jnp.bfloat16)

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return jnp.dtype(self.act_dtype)


def quantize(w: jax.Array) -> QuantizedTensor:
    """Symmetric per-output-channel int8 quantization of [..., in, out]."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2)     # [..., out]
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(w.astype(jnp.float32) / scale[..., None, :]), -127, 127
    ).astype(jnp.int8)
    return QuantizedTensor(q=q, s=scale, act_dtype=jnp.dtype(w.dtype))


def dequantize(w: QuantizedTensor, dtype=jnp.bfloat16) -> jax.Array:
    return (w.q.astype(jnp.float32) * w.s[..., None, :]).astype(dtype)


WeightLike = Union[jax.Array, QuantizedTensor]


def qdot(x: jax.Array, w: WeightLike) -> jax.Array:
    """x @ w with on-the-fly dequantization for QuantizedTensor weights."""
    if isinstance(w, QuantizedTensor):
        y = x @ w.q.astype(x.dtype)
        return y * w.s.astype(x.dtype)
    return x @ w


def qeinsum_expert(
    pattern: str, x: jax.Array, w: WeightLike, e_axis: int, **kwargs
):
    """Expert-stacked einsum: scales are [E, out]; `e_axis` names the expert
    axis in the OUTPUT (out is always last). Covers both MoE formulations:
    'bth,ehi->beti' (e_axis=1) and the dispatch path 'ech,ehi->eci'
    (e_axis=0)."""
    if isinstance(w, QuantizedTensor):
        y = jnp.einsum(pattern, x, w.q.astype(x.dtype), **kwargs)
        shape = [1] * y.ndim
        shape[e_axis] = w.s.shape[0]
        shape[-1] = w.s.shape[-1]
        return y * w.s.reshape(shape).astype(y.dtype)
    return jnp.einsum(pattern, x, w, **kwargs)


def embed_lookup(embed: WeightLike, tokens: jax.Array) -> jax.Array:
    """Embedding row lookup; scales are per hidden channel ([H] — the same
    axis the tied unembed contracts, so one tensor serves both uses)."""
    if isinstance(embed, QuantizedTensor):
        rows = embed.q[tokens]                         # int8 [..., H]
        return rows.astype(embed.dtype) * embed.s.astype(embed.dtype)
    return embed[tokens]


def unembed_logits(hidden: jax.Array, embed_or_head: WeightLike, tied: bool):
    """fp32 vocab logits from either a tied embedding ('...h,vh->...v') or an
    lm_head ('...h,hv->...v'), quantized or not."""
    if isinstance(embed_or_head, QuantizedTensor):
        # int8 values (|q| <= 127) are exact in bf16, so the vocab matmul —
        # the hottest step at 128k-256k vocab — keeps narrow operands and
        # accumulates fp32 via preferred_element_type, like the fp path.
        wdt = embed_or_head.dtype
        if tied:
            # Tied: q is [V, H], scales are [H] (contraction axis) — fold the
            # scale into the activation before the matmul.
            scaled = hidden.astype(jnp.float32) * embed_or_head.s
            return jnp.einsum(
                "...h,vh->...v", scaled.astype(wdt),
                embed_or_head.q.astype(wdt),
                preferred_element_type=jnp.float32,
            )
        y = jnp.einsum(
            "...h,hv->...v", hidden.astype(wdt),
            embed_or_head.q.astype(wdt),
            preferred_element_type=jnp.float32,
        )
        return y * embed_or_head.s
    if tied:
        return jnp.einsum(
            "...h,vh->...v", hidden, embed_or_head,
            preferred_element_type=jnp.float32,
        )
    return jnp.einsum(
        "...h,hv->...v", hidden, embed_or_head,
        preferred_element_type=jnp.float32,
    )


_QUANT_LEAVES = ("wq", "wk", "wv", "wo", "gate", "up", "down", "lm_head")


def quantize_params(params: dict, cfg: ModelConfig) -> dict:
    """Quantize every linear weight in the tree; norms, router, and biases
    stay fp. The embedding is quantized per hidden channel so the same
    tensor serves lookup and (tied) unembedding."""

    def walk(node):
        if isinstance(node, dict):
            out = {}
            for name, child in node.items():
                if name in _QUANT_LEAVES and isinstance(child, jax.Array):
                    out[name] = quantize(child)
                else:
                    # Covers the experts subtree too: gate/up/down are in
                    # _QUANT_LEAVES and quantize() handles the leading
                    # [L, E, ...] stack axes (scale reduces axis=-2 only).
                    out[name] = walk(child)
            return out
        return node

    out = walk(params)
    embed = params["embed"]                            # [V, H]
    absmax = jnp.max(jnp.abs(embed.astype(jnp.float32)), axis=0)  # [H]
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(embed.astype(jnp.float32) / scale[None, :]), -127, 127
    ).astype(jnp.int8)
    out["embed"] = QuantizedTensor(q=q, s=scale, act_dtype=jnp.dtype(embed.dtype))
    return out


def params_bytes(params) -> int:
    """Total parameter storage in bytes (quantized trees count q + s)."""
    leaves = jax.tree.leaves(params)
    return sum(leaf.size * leaf.dtype.itemsize for leaf in leaves)
