"""Checkpoint save/load for served model weights.

The reference has no persistence at all (SURVEY.md §5 checkpoint/resume:
"Absent — stateless service"); the engine owes load-only checkpointing for
the served checkpoints. Orbax is the storage layer (the JAX-ecosystem
standard; handles sharded arrays natively, so weights restore directly onto
a device mesh when sharding specs are provided).

Formats:
- orbax directory (save_checkpoint / load_checkpoint) — the native format;
- HF safetensors import (import_safetensors) — maps a HuggingFace Llama-style
  state_dict into this framework's param pytree for serving public weights.
  Requires local files; nothing is fetched.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import ModelConfig


def save_checkpoint(path: str, params: dict) -> None:
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), params)


def load_checkpoint(
    path: str,
    cfg: ModelConfig,
    dtype=jnp.bfloat16,
    shardings: Optional[dict] = None,
) -> dict:
    """Restore a param pytree saved by save_checkpoint.

    When `shardings` (a pytree of jax.sharding.NamedSharding matching the
    params) is given, arrays restore directly into their sharded layout.
    """
    import orbax.checkpoint as ocp

    import glob

    path = os.path.abspath(path)
    is_safetensors = path.endswith(".safetensors") or (
        os.path.isdir(path) and glob.glob(os.path.join(path, "*.safetensors"))
    )
    if is_safetensors:
        return import_safetensors(path, cfg, dtype)

    from .transformer import init_params

    template = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, dtype)
    )
    if shardings is not None:
        template = jax.tree_util.tree_map(
            lambda shape_dtype, sharding: jax.ShapeDtypeStruct(
                shape_dtype.shape, shape_dtype.dtype, sharding=sharding
            ),
            template,
            shardings,
        )
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(path, template)


# HF key mappings: framework param path → (HF tensor name pattern, transpose).
# HF stores linear layers as [out, in]; this framework uses [in, out], so
# every matmul weight transposes on import (norms don't).
_HF_ATTN_MAP = {
    ("attn", "wq"): ("model.layers.{i}.self_attn.q_proj.weight", True),
    ("attn", "wk"): ("model.layers.{i}.self_attn.k_proj.weight", True),
    ("attn", "wv"): ("model.layers.{i}.self_attn.v_proj.weight", True),
    ("attn", "wo"): ("model.layers.{i}.self_attn.o_proj.weight", True),
    ("ln1",): ("model.layers.{i}.input_layernorm.weight", False),
}


def _hf_layer_map(cfg: ModelConfig) -> dict:
    """Per-family HF tensor-name map covering all three served families.

    - Llama-3 (dense): mlp.{gate,up,down}_proj, post_attention_layernorm
      as the pre-MLP norm.
    - Mixtral (MoE): block_sparse_moe.gate is the router ([E, H] in HF →
      transposed to this framework's [H, E]); experts.{e}.w1/w2/w3 map to
      gate/down/up and stack over the expert axis ([E, in, out]).
    - Gemma-2: four norms per layer — HF's post_attention_layernorm is the
      *post*-norm (our post_ln1) and pre/post_feedforward_layernorm are
      ln2/post_ln2. HF Gemma RMSNorm stores w with gain = 1 + w, which is
      exactly this framework's storage convention for scale_embeddings
      models (transformer.init_params norm_offset), so values copy as-is.
    """
    m = dict(_HF_ATTN_MAP)
    if cfg.use_post_norms:
        m[("ln2",)] = (
            "model.layers.{i}.pre_feedforward_layernorm.weight", False)
        m[("post_ln1",)] = (
            "model.layers.{i}.post_attention_layernorm.weight", False)
        m[("post_ln2",)] = (
            "model.layers.{i}.post_feedforward_layernorm.weight", False)
    else:
        m[("ln2",)] = (
            "model.layers.{i}.post_attention_layernorm.weight", False)
    if cfg.is_moe:
        m[("router",)] = (
            "model.layers.{i}.block_sparse_moe.gate.weight", True)
        m[("experts", "gate")] = (
            "model.layers.{i}.block_sparse_moe.experts.{e}.w1.weight", True)
        m[("experts", "down")] = (
            "model.layers.{i}.block_sparse_moe.experts.{e}.w2.weight", True)
        m[("experts", "up")] = (
            "model.layers.{i}.block_sparse_moe.experts.{e}.w3.weight", True)
    else:
        m[("mlp", "gate")] = ("model.layers.{i}.mlp.gate_proj.weight", True)
        m[("mlp", "up")] = ("model.layers.{i}.mlp.up_proj.weight", True)
        m[("mlp", "down")] = ("model.layers.{i}.mlp.down_proj.weight", True)
    return m


def import_safetensors(path: str, cfg: ModelConfig, dtype=jnp.bfloat16) -> dict:
    """Map a local HF safetensors checkpoint into a param pytree.

    Covers the three served families (Llama-3, Mixtral, Gemma-2 — see
    _hf_layer_map); layer tensors are stacked on the leading axis for the
    scan-based forward, expert tensors additionally over the expert axis.
    """
    try:
        from safetensors import safe_open  # optional dep; gate at call time
    except ImportError as e:
        raise RuntimeError(
            "safetensors is not installed in this image; convert the "
            "checkpoint to orbax with scripts/convert_checkpoint.py on a "
            "machine that has it"
        ) from e

    import glob
    import json

    if os.path.isdir(path):
        index = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(index):
            with open(index) as f:
                weight_map = json.load(f)["weight_map"]
            files = {os.path.join(path, fn) for fn in weight_map.values()}
        else:
            files = set(glob.glob(os.path.join(path, "*.safetensors")))
    else:
        files = {path}

    tensors: dict[str, np.ndarray] = {}
    for file in sorted(files):
        with safe_open(file, framework="np") as f:
            for name in f.keys():
                tensors[name] = f.get_tensor(name)

    def get(name: str, transpose: bool) -> jnp.ndarray:
        t = tensors[name]
        arr = jnp.asarray(t, dtype=dtype)
        return arr.T if transpose else arr

    layers: dict = {}
    for key_path, (pattern, transpose) in _hf_layer_map(cfg).items():
        if "{e}" in pattern:
            per_layer = [
                jnp.stack([
                    get(pattern.format(i=i, e=e), transpose)
                    for e in range(cfg.num_experts)
                ])
                for i in range(cfg.num_layers)
            ]  # → [L, E, in, out]
        else:
            per_layer = [
                get(pattern.format(i=i), transpose)
                for i in range(cfg.num_layers)
            ]
        node = layers
        for k in key_path[:-1]:
            node = node.setdefault(k, {})
        node[key_path[-1]] = jnp.stack(per_layer)

    params = {
        "embed": get("model.embed_tokens.weight", transpose=False),
        "layers": layers,
        "final_norm": get("model.norm.weight", transpose=False),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = get("lm_head.weight", transpose=True)
    return params
