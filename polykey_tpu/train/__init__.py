"""Fine-tuning: sharded train step (loss, grads, optimizer) over the mesh.

The reference serves only (no training anywhere); this module exists so the
framework covers the fine-tune half of the model lifecycle and so multi-chip
shardings are exercised end-to-end (grads and optimizer state inherit the
parameter specs; batch shards over dp, sequence over sp).
"""

from .train import TrainState, cross_entropy_loss, make_train_step  # noqa: F401
