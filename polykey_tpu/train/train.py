"""Sharded training step.

Loss is next-token cross-entropy with padding masking; the step is a single
jitted function over mesh-sharded state: parameters/optimizer state carry
the TP/PP/EP specs (parallel/sharding.py), batches shard over dp (and sp for
long sequences), and XLA emits the gradient reduce-scatters over the mesh
axes — data parallelism falls out of the sharding, there is no pmap-style
replica loop. `jax.checkpoint` on the loss forward rematerializes block
activations to trade FLOPs for HBM, the standard long-sequence memory lever.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig
from ..models.transformer import forward, make_sp_override, unembed
from ..parallel.sharding import batch_sharding, param_shardings


@struct.dataclass
class TrainState:
    step: jax.Array
    params: dict
    opt_state: optax.OptState


def cross_entropy_loss(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,       # [B, T] input ids
    targets: jax.Array,      # [B, T] next-token ids (-1 → masked)
    positions: jax.Array,    # [B, T]
    sp_mesh: Optional[Mesh] = None,
    sp_impl: str = "ring",
    pp_mesh: Optional[Mesh] = None,
    pp_microbatches: int = 4,
) -> jax.Array:
    """Next-token cross-entropy. With `sp_mesh`, attention runs
    sequence-parallel over the mesh's sp axis — ring (KV chunks rotate
    over ICI, ops/ring_attention.py) or ulysses (head re-shard via
    all-to-all, ops/ulysses_attention.py) per `sp_impl` — instead of XLA
    all-gathering the full sequence per device. With `pp_mesh`, the stack
    runs the GPipe microbatch schedule over the mesh's pp axis
    (parallel/pipeline.py); sp and pp are mutually exclusive here (ring
    attention inside a pipeline stage would need per-stage sp submeshes)."""
    if pp_mesh is not None and pp_mesh.shape.get("pp", 1) > 1:
        if sp_mesh is not None and sp_mesh.shape.get("sp", 1) > 1:
            raise ValueError("sp>1 and pp>1 are mutually exclusive")
        from ..parallel.pipeline import pipeline_forward

        checkpointed = jax.checkpoint(
            lambda p, t, pos: pipeline_forward(
                p, cfg, t, pos, pp_mesh, pp_microbatches
            )
        )
    else:
        attn_override = make_sp_override(cfg, sp_mesh, positions, sp_impl)
        checkpointed = jax.checkpoint(
            lambda p, t, pos: forward(p, cfg, t, pos, None, attn_override)[0]
        )
    hidden = checkpointed(params, tokens, positions)
    logits = unembed(params, cfg, hidden)          # [B, T, V] fp32
    mask = targets >= 0
    safe_targets = jnp.where(mask, targets, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_targets[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)


def make_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    optimizer: Optional[optax.GradientTransformation] = None,
    sp_impl: str = "ring",
    pp_microbatches: int = 4,
):
    """Returns (init_state, train_step, shard_batch) bound to the mesh.

    init_state places params/opt-state under their specs; train_step is
    jitted with donated state, so the optimizer update is in-place on device;
    shard_batch places (tokens, targets, positions) under the batch specs
    (dp-sharded batch axis, sp-sharded sequence axis).
    """
    if optimizer is None:
        optimizer = optax.adamw(learning_rate=1e-4, weight_decay=0.01)

    p_shardings = param_shardings(cfg, mesh)
    replicated = NamedSharding(mesh, P())

    def init_state(params: dict) -> TrainState:
        params = jax.device_put(params, p_shardings)
        # Optimizer moments mirror parameter shapes; initializing from the
        # sharded params makes them inherit the same layout.
        opt_state = optimizer.init(params)
        return TrainState(
            step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state
        )

    sp_mesh = mesh if mesh.shape.get("sp", 1) > 1 else None
    pp_mesh = mesh if mesh.shape.get("pp", 1) > 1 else None

    @partial(jax.jit, donate_argnames=("state",))
    def train_step(state: TrainState, tokens, targets, positions):
        loss, grads = jax.value_and_grad(cross_entropy_loss)(
            state.params, cfg, tokens, targets, positions, sp_mesh,
            sp_impl, pp_mesh, pp_microbatches,
        )
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(step=state.step + 1, params=params, opt_state=opt_state),
            loss,
        )

    def shard_batch(tokens, targets, positions):
        sharding = batch_sharding(mesh, 2, seq_axis=1 if mesh.shape["sp"] > 1 else None)
        return (
            jax.device_put(tokens, sharding),
            jax.device_put(targets, sharding),
            jax.device_put(positions, sharding),
        )

    return init_state, train_step, shard_batch
