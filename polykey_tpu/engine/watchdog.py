"""Engine watchdog: a hung device step must fail fast and visibly.

The reference delegates liveness entirely to the platform (SURVEY.md §5:
Docker healthcheck + restart policy); a TPU engine adds a failure mode the
platform can't see — the process is alive but the step loop is wedged (device
hang, deadlocked transfer). The watchdog notices missing progress while work
is pending, flips gRPC health to NOT_SERVING (so orchestration stops routing
and restarts per policy), and fails in-flight requests cleanly rather than
letting clients hit their deadlines.

The watchdog is RE-ARMABLE (ISSUE 3): a trip latches `tripped` and goes
quiet, but the thread keeps running, so the supervisor
(engine/supervisor.py) can hand it the restarted engine via `rearm()` —
trip state resets, health resumes SERVING, and the fresh engine is
watched from its first step. Without a supervisor the old one-shot
behavior is unchanged: tripped stays latched and the platform restarts
the NOT_SERVING process.
"""

from __future__ import annotations

import threading
import time


class Watchdog:
    def __init__(self, engine, health=None, logger=None,
                 check_interval_s: float = 5.0,
                 recorder=None, stall_counter=None):
        self.engine = engine
        self.health = health
        self.logger = logger
        self.check_interval_s = check_interval_s
        # Observability hooks (both optional): `recorder` is an
        # obs.trace.FlightRecorder that gets a "watchdog_stall" event with
        # the engine state frozen at trip time — the postmortem record the
        # restarted process would otherwise take to its grave;
        # `stall_counter` is the Prometheus watchdog_stalls_total counter.
        self.recorder = recorder
        self.stall_counter = stall_counter
        self.tripped = False
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="polykey-watchdog", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def rearm(self, engine=None) -> None:
        """Point the watchdog at a (restarted) engine and resume
        watching. Resumes health to SERVING — the supervisor calls this
        as the last step of a successful restart, when the fresh engine
        is ready for traffic."""
        if engine is not None:
            self.engine = engine
        self.tripped = False
        if self.health is not None:
            self.health.resume_serving()

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            # Read the reference once per tick: rearm() swaps it from the
            # supervisor thread.
            engine = self.engine
            if self.tripped or engine.dead is not None:
                continue          # quiet until rearm() hands over a live engine
            if not engine.busy:
                continue
            stalled_for = time.monotonic() - engine.last_progress
            if stalled_for < engine.config.watchdog_timeout_s:
                continue
            self.tripped = True
            message = (
                f"engine made no progress for {stalled_for:.0f}s with work "
                "pending (device hang?)"
            )
            if self.logger is not None:
                self.logger.error("watchdog tripped", error=message)
            if self.stall_counter is not None:
                self.stall_counter.inc()
            if self.recorder is not None:
                # Freeze what the engine looked like at trip time.
                # engine.stats() reads host mirrors and queue sizes only —
                # non-blocking, safe while the device call is wedged.
                try:
                    snap = engine.stats()
                    self.recorder.event(
                        "watchdog_stall",
                        message=message,
                        stalled_for_s=round(stalled_for, 1),
                        slots_busy=snap["slots_busy"],
                        queued=snap["queued"],
                        inflight_blocks=snap["inflight_blocks"],
                    )
                except Exception:
                    pass  # postmortem capture must never mask the trip
            # Only flag and flip health here; slot/allocator state belongs to
            # the engine thread. If that thread ever returns from the wedged
            # device call it sees `dead` and fails in-flight work itself; if
            # it never returns, the supervisor (when armed) fails them and
            # restarts, else clients hit request_timeout_s and the platform
            # restarts the NOT_SERVING process (compose healthcheck).
            engine.dead = message
            engine._wake.set()
            if self.health is not None:
                self.health.shutdown()
