"""Serving metrics: the north-star counters (tok/s, TTFT) plus engine gauges.

The reference's observability is per-RPC duration logging only (SURVEY.md §5
"metrics"); the engine adds what serving needs: request phase timestamps
(enqueue → prefill → first token → finish), throughput counters, and pool
gauges. Snapshots surface through the `engine_stats` tool and per-request
Usage on the streaming RPC.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class RequestTimings:
    enqueued: float = field(default_factory=time.monotonic)
    prefill_start: float = 0.0
    first_token: float = 0.0
    finished: float = 0.0
    prompt_tokens: int = 0
    completion_tokens: int = 0

    @property
    def ttft_ms(self) -> float:
        if self.first_token and self.enqueued:
            return (self.first_token - self.enqueued) * 1e3
        return 0.0

    @property
    def tokens_per_sec(self) -> float:
        if self.finished and self.first_token and self.completion_tokens > 1:
            elapsed = self.finished - self.first_token
            if elapsed > 0:
                return (self.completion_tokens - 1) / elapsed
        return 0.0


class EngineMetrics:
    """Thread-safe counters; cheap enough to update from the step loop."""

    _TTFT_WINDOW = 512   # recent-TTFT ring for percentile gauges

    def __init__(self):
        self._lock = threading.Lock()
        self.requests_admitted = 0
        self.requests_completed = 0
        self.requests_failed = 0
        self.tokens_generated = 0
        self.decode_steps = 0
        self.ttft_ms_sum = 0.0
        self.ttft_ms_count = 0
        self._ttft_ring: list[float] = []
        self._ttft_ring_pos = 0
        self.drafts_accepted = 0
        self.drafts_proposed = 0
        self._window_start = time.monotonic()
        self._window_tokens = 0
        self.tokens_per_sec = 0.0

    def on_admit(self) -> None:
        with self._lock:
            self.requests_admitted += 1

    def on_step(self, num_tokens: int) -> None:
        with self._lock:
            self.decode_steps += 1
            self.tokens_generated += num_tokens
            self._window_tokens += num_tokens
            now = time.monotonic()
            elapsed = now - self._window_start
            if elapsed >= 1.0:
                self.tokens_per_sec = self._window_tokens / elapsed
                self._window_start = now
                self._window_tokens = 0

    def on_spec(self, accepted: int, proposed: int) -> None:
        """Per-round speculative counters; acceptance rate is the speedup
        dial (engine._spec_step counts emitted tokens only — ADVICE r1)."""
        with self._lock:
            self.drafts_accepted += accepted
            self.drafts_proposed += proposed

    def on_finish(self, timings: RequestTimings, failed: bool = False) -> None:
        with self._lock:
            if failed:
                self.requests_failed += 1
            else:
                self.requests_completed += 1
            if timings.ttft_ms > 0:
                self.ttft_ms_sum += timings.ttft_ms
                self.ttft_ms_count += 1
                if len(self._ttft_ring) < self._TTFT_WINDOW:
                    self._ttft_ring.append(timings.ttft_ms)
                else:
                    self._ttft_ring[self._ttft_ring_pos] = timings.ttft_ms
                self._ttft_ring_pos = (
                    self._ttft_ring_pos + 1
                ) % self._TTFT_WINDOW

    def snapshot(self) -> dict:
        with self._lock:
            mean_ttft = (
                self.ttft_ms_sum / self.ttft_ms_count
                if self.ttft_ms_count
                else 0.0
            )
            snap = {
                "requests_admitted": self.requests_admitted,
                "requests_completed": self.requests_completed,
                "requests_failed": self.requests_failed,
                "tokens_generated": self.tokens_generated,
                "decode_steps": self.decode_steps,
                "tokens_per_sec": round(self.tokens_per_sec, 2),
                "mean_ttft_ms": round(mean_ttft, 2),
            }
            if self._ttft_ring:
                # p50/p95 over the recent window — TTFT is half the
                # north-star metric and its tail, not its mean, is what
                # operators chase.
                ordered = sorted(self._ttft_ring)
                n = len(ordered)
                snap["p50_ttft_ms"] = round(ordered[n // 2], 2)
                snap["p95_ttft_ms"] = round(
                    ordered[min(n - 1, (n * 95) // 100)], 2
                )
            if self.drafts_proposed:
                snap["drafts_accepted"] = self.drafts_accepted
                snap["drafts_proposed"] = self.drafts_proposed
                snap["spec_acceptance"] = round(
                    self.drafts_accepted / self.drafts_proposed, 3
                )
            return snap
