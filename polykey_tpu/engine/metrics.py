"""Serving metrics: the north-star counters (tok/s, TTFT) plus engine gauges.

The reference's observability is per-RPC duration logging only (SURVEY.md §5
"metrics"); the engine adds what serving needs: request phase timestamps
(enqueue → prefill → first token → finish), throughput counters, and pool
gauges. Snapshots surface through the `engine_stats` tool and per-request
Usage on the streaming RPC; the same state exports in Prometheus text form
via obs.exposition.engine_collector (ISSUE 1).

TTFT and inter-token latency are histogram-backed (obs.histogram): fixed
log-spaced buckets give O(1)-memory p50/p90/p95/p99 over the FULL history
(the old 512-entry ring only saw recent requests and sorted on every
snapshot) and render directly as Prometheus ``_bucket`` families.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..obs.histogram import Histogram

# Bucket bounds for the live-lanes-per-block histogram: lane counts are
# small integers bounded by max_decode_slots, so a fixed power-of-two-ish
# ladder up to 512 covers every plausible slot configuration with ~16
# buckets (O(1) memory, same Prometheus rendering as the latency
# histograms). Exact occupancy ratios come from the counters, not the
# histogram — this exists for the distribution's SHAPE (is the engine
# bimodal between empty and full, or genuinely holding N lanes?).
LANE_BUCKETS = (1, 2, 4, 8, 12, 16, 24, 32, 48, 64, 96, 128, 192, 256,
                384, 512)


@dataclass
class RequestTimings:
    enqueued: float = field(default_factory=time.monotonic)
    prefill_start: float = 0.0
    first_token: float = 0.0
    finished: float = 0.0
    prompt_tokens: int = 0
    completion_tokens: int = 0
    # Device-time attribution (ISSUE 10): the request's share of every
    # decode block's device-busy window (dispatch gap minus host stall,
    # split equally across the lanes live at dispatch). Accumulated by
    # the engine thread; surfaced as a span attribute, the `device-ms`
    # trailer, and the polykey_request_device_ms histogram.
    device_ms: float = 0.0

    @property
    def ttft_ms(self) -> float:
        if self.first_token and self.enqueued:
            return (self.first_token - self.enqueued) * 1e3
        return 0.0

    @property
    def tokens_per_sec(self) -> float:
        if self.finished and self.first_token and self.completion_tokens > 1:
            elapsed = self.finished - self.first_token
            if elapsed > 0:
                return (self.completion_tokens - 1) / elapsed
        return 0.0

class EngineMetrics:
    """Thread-safe counters; cheap enough to update from the step loop."""

    def __init__(self):
        self._lock = threading.Lock()
        self.requests_admitted = 0
        self.requests_completed = 0
        self.requests_failed = 0
        self.tokens_generated = 0
        self.decode_steps = 0
        self.ttft_ms_sum = 0.0
        self.ttft_ms_count = 0
        # Latency histograms (observe() is internally locked; kept outside
        # self._lock so a scrape rendering them never contends the step
        # loop's counter lock).
        self.ttft_hist = Histogram()
        self.itl_hist = Histogram()
        self.drafts_accepted = 0
        self.drafts_proposed = 0
        # Overload accounting (ISSUE 3): sheds at admission, deadline
        # expiries by phase. Exported as polykey_requests_shed_total and
        # polykey_deadline_expired_total{phase=...}.
        self.requests_shed = 0
        self.deadline_expired = {"queued": 0, "prefill": 0, "decode": 0}
        # EWMA of per-request service time (admission → finish), the
        # input to the estimated-queue-delay admission check: with S
        # slots draining in parallel, one queued request waits roughly
        # qsize × ewma / S before admission. 0.0 until the first finish.
        self._service_ewma_s = 0.0
        self._window_start = time.monotonic()
        self._window_tokens = 0
        self.tokens_per_sec = 0.0
        # Occupancy tracker (ISSUE 4): always-on per-dispatch live-lane
        # accounting, replacing the opt-in POLYKEY_LOOP_TRACE counters as
        # the source of truth for avg_lanes. One locked add per dispatched
        # block (the engine loop runs a handful of dispatches per second
        # at steady state — negligible next to the device call it rides):
        #   blocks_dispatched — decode blocks / spec rounds dispatched
        #   lanes_dispatched  — Σ live lanes at dispatch (block-weighted)
        #   lane_steps        — Σ lanes × steps   (step-weighted; what the
        #                       roofline's bytes/token actually amortizes
        #                       over, since a K-step block reads weights K
        #                       times at that occupancy)
        #   steps_dispatched  — Σ steps
        # avg_lanes in snapshots is the STEP-weighted mean; an EWMA of
        # lanes-per-block gives the "now" gauge for dashboards.
        self.blocks_dispatched = 0
        self.lanes_dispatched = 0
        self.lane_steps = 0
        self.steps_dispatched = 0
        self._lanes_ewma = 0.0
        self.lanes_hist = Histogram(bounds=LANE_BUCKETS)
        # Interleaved-prefill accounting: total prefill tokens dispatched
        # and the worst single-iteration injection observed WHILE decode
        # lanes were live — the bound the stall test pins (engine loop
        # charges per iteration; see EngineConfig.prefill_budget for the
        # overshoot semantics).
        self.prefill_tokens_total = 0
        self.interleave_max_tokens = 0
        # Padding-waste accounting (ISSUE 12): per dispatch, how many
        # token rows the device COMPUTED vs how many were useful work.
        # Decode blocks charge slots×steps dispatched / lanes×steps
        # useful (dead-lane padding); bucketed prefill charges the
        # padded group width (n_pad × bucket, or the chunk width C) vs
        # the real token count; the ragged dispatch charges its static
        # stream width vs the tokens appended. tokens_useful /
        # tokens_dispatched is the occupancy-soak's padding-waste
        # ratio — the number the ragged path exists to raise.
        self.tokens_dispatched_total = 0
        self.tokens_useful_total = 0
        # Lookahead pipeline accounting (ISSUE 6): per processed block,
        # the OBSERVED lookahead (blocks dispatched after it, before its
        # readback — ≥1 means the dispatch frontier ran ahead of the
        # processed frontier; 0 is the synchronous depth-1 shape) and the
        # host stall (ms the processed frontier blocked waiting for the
        # block's D2H copy — ~0 when the pipeline hid the roundtrip).
        # The stall histogram renders as polykey_host_stall_ms_bucket.
        self.blocks_processed = 0
        # Blocks that actually performed a readback — dead blocks (every
        # occupant gone, sync skipped) count in blocks_processed but not
        # here, so stall means divide by the reads that happened.
        self.blocks_synced = 0
        self.lookahead_sum = 0
        self.lookahead_max = 0
        self.host_stall_ms_total = 0.0
        self.host_stall_hist = Histogram()
        # Dispatch cadence: host-side gap between consecutive block
        # dispatches. At depth 1 the gap is bounded below by the block's
        # device time plus the readback (the host sits between blocks);
        # with lookahead it shrinks toward pure host scheduling work —
        # bench's `dispatch_gap_ms` is the windowed mean of this.
        self.dispatch_gap_ms_total = 0.0
        self.dispatch_gaps = 0
        self._last_dispatch_t = 0.0
        # Device-time attribution (ISSUE 10): total device-busy ms
        # charged across blocks (gap − stall, clamped ≥ 0) and the
        # per-request distribution of that charge. busy/gap is the
        # polykey_device_busy_fraction gauge — the "how device-bound is
        # steady state" dial, from the recorded schedule.
        self.device_busy_ms_total = 0.0
        self.device_ms_hist = Histogram()
        # Host-memory KV tier (ISSUE 15): page-fault counters by kind
        # ("prefix" = a sticky short-prompt session resuming off spilled
        # pages, "ctx" = a long-context prompt's middle pages paging
        # back for chunked prefill), spill/restore page counters, and
        # the restore-latency histogram (gather of host contents +
        # upload + scatter dispatch — the cost a faulting lane pays that
        # a resident lane must never share).
        self.kv_page_faults = {"prefix": 0, "ctx": 0}
        self.kv_pages_evicted = 0
        self.kv_pages_restored = 0
        self.kv_restore_hist = Histogram()
        # SLO signal plane (ISSUE 11): attached by the engine when
        # signals are enabled (obs.signals.SignalPlane), None otherwise.
        # It lives HERE — not on the engine — because the supervisor's
        # metrics-adoption path already carries this object to the fresh
        # engine on restart, which is exactly the continuity the
        # windowed ring and the SLO budget accounting need.
        self.signals = None

    def on_process_block(self, lookahead: int,
                         stall_ms: Optional[float],
                         trace_id: Optional[str] = None) -> None:
        """One in-flight block processed with `lookahead` newer blocks
        already dispatched; `stall_ms` is the blocking-readback wall time
        (None for dead blocks whose sync was skipped entirely).
        `trace_id` exemplars the stall bucket with a request that was
        live in the block."""
        with self._lock:
            self.blocks_processed += 1
            self.lookahead_sum += lookahead
            if lookahead > self.lookahead_max:
                self.lookahead_max = lookahead
            if stall_ms is not None:
                self.blocks_synced += 1
                self.host_stall_ms_total += stall_ms
        if stall_ms is not None:
            self.host_stall_hist.observe(stall_ms, trace_id=trace_id)

    def on_spec_host_sync(self, stall_ms: float) -> None:
        """--ab-spec emulation only (EngineConfig.spec_host_sync): a
        blocking packed readback forced at DISPATCH time is host stall
        exactly like the process-side read, so it lands in the same
        accounting — otherwise the A/B's host_stall_ms_mean would show
        the emulated host-loop leg as stall-free (its process-side read
        finds the data already copied)."""
        with self._lock:
            self.blocks_synced += 1
            self.host_stall_ms_total += stall_ms
        self.host_stall_hist.observe(stall_ms)

    def on_device_busy(self, busy_ms: float) -> None:
        """Device-busy ms attributed to one processed block."""
        with self._lock:
            self.device_busy_ms_total += busy_ms

    def on_dispatch_idle(self) -> None:
        """The engine went idle (no live lanes, nothing in flight): reset
        the dispatch-gap clock so the FIRST block of the next request is
        not charged the idle wait as device-busy time. Without this, a
        low-QPS engine (one request every few seconds) reports seconds
        of device_ms for sub-second requests — the gap-tiles-the-device
        assumption only holds while dispatches are back to back."""
        with self._lock:
            self._last_dispatch_t = 0.0

    def on_prefill_interleave(self, tokens: int, decode_live: bool) -> None:
        """Prefill tokens dispatched in one engine-loop iteration;
        `decode_live` marks iterations where decode lanes were active at
        admission time (only those can stall a running stream)."""
        if tokens <= 0:
            return
        with self._lock:
            self.prefill_tokens_total += tokens
            if decode_live and tokens > self.interleave_max_tokens:
                self.interleave_max_tokens = tokens

    def on_padding_tokens(self, dispatched: int, useful: int) -> None:
        """Token rows computed vs useful for one prefill dispatch
        (bucketed group / chunk / ragged stream) — the padding-waste
        counters the occupancy soak diffs."""
        with self._lock:
            self.tokens_dispatched_total += dispatched
            self.tokens_useful_total += useful

    def on_dispatch(self, lanes: int, steps: int,
                    slots: int = 0) -> float:
        """One decode block (or spec round) dispatched with `lanes` live
        decode lanes for `steps` device steps. Returns the counted
        dispatch gap in ms (0.0 for the first dispatch or an idle-capped
        gap) — the attribution window the engine charges to the block.
        `slots` (the static batch width) feeds the padding-waste
        counters: the device computes slots×steps token rows of which
        lanes×steps are useful."""
        now = time.monotonic()
        counted_gap = 0.0
        with self._lock:
            if slots > 0:
                self.tokens_dispatched_total += slots * steps
                self.tokens_useful_total += lanes * steps
            if self._last_dispatch_t:
                gap_ms = (now - self._last_dispatch_t) * 1e3
                # Idle gaps (no active lanes → no dispatch) are load
                # shape, not scheduling cost; cap what one gap can
                # contribute so the windowed mean reads cadence.
                if gap_ms < 10_000.0:
                    self.dispatch_gap_ms_total += gap_ms
                    self.dispatch_gaps += 1
                    counted_gap = gap_ms
            self._last_dispatch_t = now
            self.blocks_dispatched += 1
            self.lanes_dispatched += lanes
            self.lane_steps += lanes * steps
            self.steps_dispatched += steps
            self._lanes_ewma = (
                float(lanes) if self.blocks_dispatched == 1
                else 0.9 * self._lanes_ewma + 0.1 * lanes
            )
        self.lanes_hist.observe(float(lanes))
        return counted_gap

    def counter_sample(self) -> dict:
        """Every monotone counter in ONE locked read — the signal
        plane's ring entry (obs.signals). Raw values only: rates,
        availability, and delta-quantiles are computed read-side from
        two samples, so this stays cheap enough for a 5 s cadence (and
        a 50 ms test cadence) on the engine thread."""
        with self._lock:
            return {
                "requests_admitted": self.requests_admitted,
                "requests_completed": self.requests_completed,
                "requests_failed": self.requests_failed,
                "requests_shed": self.requests_shed,
                "deadline_expired_queued": self.deadline_expired["queued"],
                "deadline_expired_prefill": self.deadline_expired["prefill"],
                "deadline_expired_decode": self.deadline_expired["decode"],
                "tokens_generated": self.tokens_generated,
                "decode_steps": self.decode_steps,
                "blocks_dispatched": self.blocks_dispatched,
                "lanes_dispatched": self.lanes_dispatched,
                "lane_steps": self.lane_steps,
                "steps_dispatched": self.steps_dispatched,
                "prefill_tokens_total": self.prefill_tokens_total,
                "tokens_dispatched_total": self.tokens_dispatched_total,
                "tokens_useful_total": self.tokens_useful_total,
                "blocks_processed": self.blocks_processed,
                "blocks_synced": self.blocks_synced,
                "lookahead_sum": self.lookahead_sum,
                "host_stall_ms_total": self.host_stall_ms_total,
                "dispatch_gap_ms_total": self.dispatch_gap_ms_total,
                "dispatch_gaps": self.dispatch_gaps,
                "device_busy_ms_total": self.device_busy_ms_total,
                "drafts_accepted": self.drafts_accepted,
                "drafts_proposed": self.drafts_proposed,
                "kv_page_faults_prefix": self.kv_page_faults["prefix"],
                "kv_page_faults_ctx": self.kv_page_faults["ctx"],
                "kv_pages_evicted": self.kv_pages_evicted,
                "kv_pages_restored": self.kv_pages_restored,
            }

    def lanes_snapshot(self) -> dict:
        """Occupancy counters alone — cheap enough for harnesses to poll
        around a measurement window and diff (occupancy_soak, bench)."""
        with self._lock:
            return {
                "blocks_dispatched": self.blocks_dispatched,
                "lanes_dispatched": self.lanes_dispatched,
                "lane_steps": self.lane_steps,
                "steps_dispatched": self.steps_dispatched,
                "avg_lanes": (
                    round(self.lane_steps / self.steps_dispatched, 2)
                    if self.steps_dispatched else None
                ),
                "lanes_ewma": round(self._lanes_ewma, 2),
                # Pipeline counters for windowed diffs (bench step_costs,
                # occupancy soak): host stall + dispatch cadence.
                "blocks_processed": self.blocks_processed,
                "blocks_synced": self.blocks_synced,
                "lookahead_sum": self.lookahead_sum,
                "host_stall_ms_total": self.host_stall_ms_total,
                "dispatch_gap_ms_total": self.dispatch_gap_ms_total,
                "dispatch_gaps": self.dispatch_gaps,
                "device_busy_ms_total": self.device_busy_ms_total,
                # Padding-waste counters (ISSUE 12): harnesses diff these
                # over a window; useful/dispatched is the waste ratio.
                "tokens_dispatched_total": self.tokens_dispatched_total,
                "tokens_useful_total": self.tokens_useful_total,
            }

    def on_kv_fault(self, kind: str, pages: int) -> None:
        """`pages` host-resident pages faulted for one admission
        (restored before its suffix may prefill)."""
        with self._lock:
            self.kv_page_faults[kind] += pages

    def on_kv_evict(self, pages: int) -> None:
        with self._lock:
            self.kv_pages_evicted += pages

    def on_kv_restore(self, pages: int, ms: float,
                      trace_id: Optional[str] = None) -> None:
        with self._lock:
            self.kv_pages_restored += pages
        self.kv_restore_hist.observe(ms, trace_id=trace_id)

    def on_admit(self) -> None:
        with self._lock:
            self.requests_admitted += 1

    def on_shed(self) -> None:
        with self._lock:
            self.requests_shed += 1

    def on_deadline_expired(self, phase: str) -> None:
        with self._lock:
            self.deadline_expired[phase] += 1

    def service_time_ewma_s(self) -> float:
        with self._lock:
            return self._service_ewma_s

    def on_step(self, num_tokens: int) -> None:
        with self._lock:
            self.decode_steps += 1
            self.tokens_generated += num_tokens
            self._window_tokens += num_tokens
            now = time.monotonic()
            elapsed = now - self._window_start
            if elapsed >= 1.0:
                self.tokens_per_sec = self._window_tokens / elapsed
                self._window_start = now
                self._window_tokens = 0

    def on_itl(self, gap_ms: float, count: int = 1,
               trace_id: Optional[str] = None) -> None:
        """Record `count` tokens delivered with a per-token gap of
        `gap_ms` (one decode block's inter-emit window amortized over its
        tokens). Per-BLOCK measurement, not per-request mean: a 2 s stall
        between blocks lands in the histogram as 2 s-scale gaps for that
        block's tokens instead of vanishing into a request average."""
        if gap_ms > 0:
            self.itl_hist.observe(gap_ms, count, trace_id=trace_id)

    def on_spec(self, accepted: int, proposed: int) -> None:
        """Per-round speculative counters; acceptance rate is the speedup
        dial (engine._spec_step counts emitted tokens only — ADVICE r1)."""
        with self._lock:
            self.drafts_accepted += accepted
            self.drafts_proposed += proposed

    def on_finish(self, timings: RequestTimings, failed: bool = False,
                  trace_id: Optional[str] = None) -> None:
        ttft = timings.ttft_ms
        with self._lock:
            if failed:
                self.requests_failed += 1
            else:
                self.requests_completed += 1
                if timings.finished and timings.prefill_start:
                    dur = timings.finished - timings.prefill_start
                    if dur > 0:
                        self._service_ewma_s = (
                            dur if self._service_ewma_s == 0.0
                            else 0.8 * self._service_ewma_s + 0.2 * dur
                        )
            if ttft > 0:
                self.ttft_ms_sum += ttft
                self.ttft_ms_count += 1
        if ttft > 0:
            self.ttft_hist.observe(ttft, trace_id=trace_id)
        if timings.device_ms > 0:
            self.device_ms_hist.observe(timings.device_ms,
                                        trace_id=trace_id)

    def snapshot(self) -> dict:
        with self._lock:
            mean_ttft = (
                self.ttft_ms_sum / self.ttft_ms_count
                if self.ttft_ms_count
                else 0.0
            )
            # The throughput window only advances inside on_step, so on an
            # idle engine the last busy window's rate would be reported
            # forever (now also scraped as polykey_tokens_per_sec —
            # phantom throughput on dashboards). Under traffic on_step
            # flushes the window at ~1s intervals; a window start more
            # than 5s old means the step loop has gone idle — decay the
            # gauge (any unflushed remainder tokens are equally stale).
            if (
                self.tokens_per_sec > 0.0
                and time.monotonic() - self._window_start > 5.0
            ):
                self.tokens_per_sec = 0.0
                # Restart the window clean or the first flush after idle
                # would average the new burst over the whole idle gap and
                # report ~0 while decoding at full speed.
                self._window_start = time.monotonic()
                self._window_tokens = 0
            snap = {
                # Host-KV tier (ISSUE 15): always present (0 with the
                # tier off) so collectors index them unconditionally.
                "kv_page_faults_prefix": self.kv_page_faults["prefix"],
                "kv_page_faults_ctx": self.kv_page_faults["ctx"],
                "kv_pages_evicted": self.kv_pages_evicted,
                "kv_pages_restored": self.kv_pages_restored,
                "requests_admitted": self.requests_admitted,
                "requests_completed": self.requests_completed,
                "requests_failed": self.requests_failed,
                "requests_shed": self.requests_shed,
                "deadline_expired_queued": self.deadline_expired["queued"],
                "deadline_expired_prefill": self.deadline_expired["prefill"],
                "deadline_expired_decode": self.deadline_expired["decode"],
                "tokens_generated": self.tokens_generated,
                "decode_steps": self.decode_steps,
                "tokens_per_sec": round(self.tokens_per_sec, 2),
                "mean_ttft_ms": round(mean_ttft, 2),
                "blocks_dispatched": self.blocks_dispatched,
                "lane_steps": self.lane_steps,
                "steps_dispatched": self.steps_dispatched,
                "lanes_ewma": round(self._lanes_ewma, 2),
                "prefill_tokens_total": self.prefill_tokens_total,
                "interleave_max_tokens": self.interleave_max_tokens,
                "tokens_dispatched": self.tokens_dispatched_total,
                "tokens_useful": self.tokens_useful_total,
                # Fraction of computed token rows that were useful work
                # (1 − padding waste) — the dial the ragged path raises.
                "tokens_useful_fraction": (
                    round(self.tokens_useful_total
                          / self.tokens_dispatched_total, 4)
                    if self.tokens_dispatched_total else None
                ),
                "blocks_processed": self.blocks_processed,
                "lookahead_observed_max": self.lookahead_max,
                "lookahead_observed_mean": (
                    round(self.lookahead_sum / self.blocks_processed, 2)
                    if self.blocks_processed else 0.0
                ),
                "host_stall_ms_total": round(self.host_stall_ms_total, 2),
                "device_busy_ms_total": round(self.device_busy_ms_total, 2),
                # Cumulative device-busy fraction of inter-dispatch wall
                # time — the attribution-side mirror of bench's windowed
                # overlap_ratio, always in [0, 1] (busy = gap − stall).
                "device_busy_fraction": (
                    round(self.device_busy_ms_total
                          / self.dispatch_gap_ms_total, 4)
                    if self.dispatch_gap_ms_total else 0.0
                ),
            }
            if self.steps_dispatched:
                # Step-weighted measured occupancy — the number roofline
                # grading consumes (avg_lanes_source: "measured").
                snap["avg_lanes"] = round(
                    self.lane_steps / self.steps_dispatched, 2
                )
            drafts_proposed = self.drafts_proposed
            drafts_accepted = self.drafts_accepted
        if self.ttft_hist.count:
            # TTFT tail percentiles — TTFT is half the north-star metric
            # and its tail, not its mean, is what operators chase. These
            # are SINCE-START percentiles (the old p50_ttft_ms/p95_ttft_ms
            # keys over a recent-512 ring are gone — recency belongs to
            # the scraper via rate() over the exported buckets, not to a
            # second windowing scheme in-process).
            p50, p95, p99 = self.ttft_hist.percentiles(50, 95, 99)
            snap["ttft_ms_p50"] = round(p50, 2)
            snap["ttft_ms_p95"] = round(p95, 2)
            snap["ttft_ms_p99"] = round(p99, 2)
        if self.itl_hist.count:
            p50, p95, p99 = self.itl_hist.percentiles(50, 95, 99)
            snap["itl_ms_p50"] = round(p50, 2)
            snap["itl_ms_p95"] = round(p95, 2)
            snap["itl_ms_p99"] = round(p99, 2)
        if self.host_stall_hist.count:
            # Host-stall tail: the "is decode host-bound?" dial — a p50
            # near roundtrip_ms means the lookahead pipeline is not
            # hiding the host (see DEPLOY.md runbook).
            p50, p95 = self.host_stall_hist.percentiles(50, 95)
            snap["host_stall_ms_p50"] = round(p50, 2)
            snap["host_stall_ms_p95"] = round(p95, 2)
        if self.device_ms_hist.count:
            p50, p95 = self.device_ms_hist.percentiles(50, 95)
            snap["request_device_ms_p50"] = round(p50, 2)
            snap["request_device_ms_p95"] = round(p95, 2)
        if self.kv_restore_hist.count:
            p50, p95 = self.kv_restore_hist.percentiles(50, 95)
            snap["kv_restore_ms_p50"] = round(p50, 2)
            snap["kv_restore_ms_p95"] = round(p95, 2)
        if drafts_proposed:
            snap["drafts_accepted"] = drafts_accepted
            snap["drafts_proposed"] = drafts_proposed
            snap["spec_acceptance"] = round(
                drafts_accepted / drafts_proposed, 3
            )
        return snap
