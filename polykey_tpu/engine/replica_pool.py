"""Replica tier: N supervised engines behind a health/load-aware router.

Everything before ISSUE 9 was one engine process: a watchdog trip or a
supervisor give-up took down the whole service, and every in-flight
stream died with it. This module generalizes the PR 3 resilience layer
from "restart the engine" to "drain and re-route a replica":

- Each replica is an independently supervised `InferenceEngine` — its
  own watchdog, its own `EngineSupervisor` restart budget, its own
  metrics namespace (exported with a ``replica`` label), its own fault-
  injection scope (``POLYKEY_FAULTS="step-stall=1.0@1:replica=2"``).
- A router scores SERVING replicas per request:
  ``prefix_weight × warmth − delay_weight × est_delay``, where warmth is
  the replica's cached-prefix fraction for the prompt (NetKV-style
  "route to where the state lives", via ``engine.prefix_warmth``) and
  est_delay is the PR 3 queue-delay EWMA estimate. Candidates whose
  estimated delay would blow the request deadline are filtered first
  (headroom). Ties break on the lowest replica index — routing is
  deterministic given equal state.
- On a replica fault (watchdog trip, loop crash, injected fault) the
  pool marks it DRAINING, stops admissions to it, and re-routes its
  work: every request the dying engine fails with an engine-lifecycle
  error is resubmitted to a healthy replica. Queued requests (zero
  tokens emitted) move losslessly; in-flight streams RESUME — the
  replacement attempt re-executes from the prompt with the same seed and
  the pool suppresses the first `emitted` tokens, so a greedy stream's
  resumed suffix is bit-identical to an uninterrupted run (and a sampled
  stream on a plain engine too, since draws key on fold_in(seed,
  position)); resumed streams are flagged ``restarted`` for the gateway
  trailer because a speculative engine only guarantees distributional
  reproducibility.
- Health is aggregated: the real `HealthService` reports SERVING while
  ≥1 replica serves; a per-replica give-up marks that replica DEAD and
  leaves the rest serving — the single-engine "give up ⇒ NOT_SERVING
  for platform recycle" contract now applies per replica, and only an
  all-replicas give-up surfaces process-level NOT_SERVING.

Replica state machine (COMPONENTS.md §12)::

    NEW ──start──▶ SERVING ──fault──▶ DRAINING ──factory──▶ RESTARTING
                      ▲                   │                     │
                      └──────rearm────────┴──────give-up──▶   DEAD

A pool of 1 degenerates to the single-engine supervisor semantics: a
fault finds no other SERVING replica, so requests fail UNAVAILABLE
(retryable) exactly as today, and recovery is the supervisor restart.

The pool quacks like an engine where the gateway needs it to
(`config`, `tokenizer`, `submit`, `stats`, `dead`, `shutdown`), so
`TpuService` routes through it without a parallel code path.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import numpy as np

from .config import EngineConfig
from .engine import EngineDeadError, EngineOverloadedError, GenRequest, InferenceEngine
from .supervisor import EngineSupervisor
from .watchdog import Watchdog

# Replica lifecycle states (stats()["per_replica"][i]["state"] and the
# polykey_replica_state{replica,state} gauge enumerate exactly these).
NEW = "NEW"
SERVING = "SERVING"
DRAINING = "DRAINING"
RESTARTING = "RESTARTING"
DEAD = "DEAD"
STATES = (NEW, SERVING, DRAINING, RESTARTING, DEAD)

# Stats keys summed across replicas in ReplicaPool.stats() — everything
# here is a monotonic count or an instantaneous quantity where the pool
# total is the meaningful serving-tier number. Percentiles/EWMAs stay
# per-replica (in "per_replica") because they do not add.
_ADDITIVE_KEYS = frozenset({
    "requests_admitted", "requests_completed", "requests_failed",
    "requests_shed",
    "deadline_expired_queued", "deadline_expired_prefill",
    "deadline_expired_decode",
    "tokens_generated", "decode_steps", "tokens_per_sec",
    "slots_busy", "slots_total", "pages_free", "pages_total", "queued",
    "inflight_blocks",
    "blocks_dispatched", "lane_steps", "steps_dispatched",
    "prefill_tokens_total", "blocks_processed", "host_stall_ms_total",
    "device_busy_ms_total",
    "prefix_cache_pages", "prefix_hit_tokens", "prefix_lookup_tokens",
    "prefix_host_pages", "prefix_host_hit_tokens",
    "kv_page_faults_prefix", "kv_page_faults_ctx",
    "kv_pages_evicted", "kv_pages_restored",
    "kv_host_pages", "kv_host_capacity", "kv_device_pages",
    "kv_reloaded_pages",
    "drafts_accepted", "drafts_proposed",
})

_ROUTE_REASONS = ("prefix-hit", "least-delay", "headroom")


class _ReplicaHealth:
    """Per-replica stand-in for the gateway HealthService: the replica's
    watchdog, supervisor, and engine crash path all call the usual
    health methods on it, and the pool folds those per-replica signals
    into the REAL health service's aggregate (SERVING while ≥1 replica
    lives) instead of letting one replica flip the whole process."""

    def __init__(self, pool: "ReplicaPool", index: int):
        self._pool = pool
        self._index = index

    def shutdown(self) -> None:
        self._pool._on_replica_down(self._index)

    def resume_serving(self) -> None:
        self._pool._on_replica_up(self._index)

    def resume(self) -> None:
        pass  # per-replica un-latch is implied by resume_serving

    def set_serving_status(self, service, status) -> None:
        pass  # service-name granularity stays with the real HealthService


@dataclass
class _Replica:
    index: int
    engine: InferenceEngine
    watchdog: Optional[Watchdog]
    supervisor: Optional[EngineSupervisor]
    state: str = NEW


@dataclass
class _FlightRecord:
    """Pool-side tracking for ONE client request across engine attempts.

    `request` is the gateway's GenRequest — its `out` queue is what the
    handler thread drains, and the pool is the only writer to it. Each
    engine attempt is a shadow GenRequest whose `out` is an
    `_AttemptQueue` feeding back here; `suppress` tokens of the current
    attempt are dropped (already delivered by a previous attempt) before
    forwarding resumes."""

    request: GenRequest
    attempt: Optional[GenRequest] = None
    replica: int = -1
    emitted: int = 0            # tokens forwarded to the client, total
    seen: int = 0               # tokens produced by the CURRENT attempt
    suppress: int = 0           # leading tokens of this attempt to drop
    reroutes: int = 0
    terminal: bool = False      # current attempt delivered done/error
    lock: threading.Lock = field(default_factory=threading.Lock)


class _AttemptQueue:
    """The shadow request's `out`: engine events flow through the pool
    (suppression, reroute-on-failure, timing merge) instead of straight
    to the client. Only `put` matters — it is the engine's entire
    surface on a request's out queue."""

    def __init__(self, pool: "ReplicaPool", record: _FlightRecord):
        self._pool = pool
        self._record = record

    def put(self, item, block: bool = True, timeout=None) -> None:
        self._pool._on_attempt_event(self._record, self, item)


class ReplicaPool:
    """Engine-shaped facade over N supervised replicas + the router."""

    def __init__(self, config: EngineConfig, health=None, logger=None,
                 recorder=None):
        config.validate()
        self.config = config
        self.health = health
        self.logger = logger
        self.recorder = recorder
        self.replicas: list[_Replica] = []
        self.tokenizer = None           # first replica's (all identical)
        self._lock = threading.Lock()
        self._closing = False
        self._serving_advertised = True
        self.requests_rerouted = 0
        self.streams_resumed = 0
        self.router_decisions = {reason: 0 for reason in _ROUTE_REASONS}
        # Recovery-hint inputs (ISSUE 13 satellite): how often the
        # supervisors poll (create() overwrites with its real interval)
        # — the no-healthy-replica UNAVAILABLE carries an estimated
        # retry-after derived from it, so clients back off on the
        # SERVER's recovery clock instead of hammering a restarting tier.
        self._supervisor_interval_s = 0.5
        # Pool-assigned seeds for seedless sampled requests: a resumed
        # attempt must replay the SAME stream, so the root is fixed
        # before the first attempt instead of drawn inside one engine.
        self._seed_rng = np.random.default_rng()
        # Live router weights (autopilot actuation surface): _route
        # reads THESE per call, not the frozen config, so a mid-run
        # set_route_weights lands on the very next routing decision.
        self._route_prefix_weight = config.route_prefix_weight
        self._route_delay_weight = config.route_delay_weight

    # -- live-knob actuation (autopilot; any thread) -------------------------

    def set_route_weights(self, prefix: Optional[float] = None,
                          delay: Optional[float] = None) -> tuple:
        """Update the router score weights in place (floats, GIL-atomic
        against concurrent _route calls). None leaves a weight alone;
        both clamp non-negative. Returns the applied pair."""
        if prefix is not None:
            self._route_prefix_weight = max(0.0, float(prefix))
        if delay is not None:
            self._route_delay_weight = max(0.0, float(delay))
        return (self._route_prefix_weight, self._route_delay_weight)

    def knob_setpoints(self) -> dict:
        """Pool-level live knobs plus replica 0's engine knobs (all
        replicas receive identical actuations — the autopilot
        broadcasts through apply_engine_knobs)."""
        out = {
            "route_prefix_weight": round(self._route_prefix_weight, 4),
            "route_delay_weight": round(self._route_delay_weight, 4),
        }
        if self.replicas:
            out.update(self.replicas[0].engine.knob_setpoints())
        return out

    def apply_engine_knobs(self, knobs: dict) -> dict:
        """Broadcast engine-level knob setpoints to EVERY replica (a
        restarted replica's fresh engine is re-covered by the
        autopilot's restart listener). Returns the values applied by
        the last replica — identical engines apply identically."""
        from .autopilot import apply_engine_knobs

        applied: dict = {}
        for rep in self.replicas:
            applied = apply_engine_knobs(rep.engine, knobs)
        return applied

    # -- construction --------------------------------------------------------

    @classmethod
    def create(
        cls, config: EngineConfig, replicas: Optional[int] = None,
        health=None, logger=None, obs=None, seed: int = 0,
        params: Optional[dict] = None, draft_params: Optional[dict] = None,
        watchdog_interval_s: float = 5.0,
        supervisor_interval_s: float = 0.5,
        join_timeout_s: float = 5.0,
    ) -> "ReplicaPool":
        """Build and start a fully wired pool: engines, per-replica
        watchdogs and (when `config.supervise`) supervisors, shared
        stall/restart counters from `obs`. Interval knobs exist so chaos
        tests can scale the detection latency the way test_chaos scales
        the watchdog window."""
        n = replicas or config.replicas
        recorder = obs.recorder if obs is not None else None
        stall_counter = restart_counter = None
        if obs is not None:
            from ..obs import Counter

            # Same names TpuService registers — get_or_create keeps the
            # two construction orders (pool-first in from_env, service-
            # first in tests) from colliding.
            stall_counter, _ = obs.registry.get_or_create(
                Counter,
                "polykey_watchdog_stalls_total",
                "Watchdog trips on a wedged engine step loop.",
            )
            restart_counter, _ = obs.registry.get_or_create(
                Counter,
                "polykey_engine_restarts_total",
                "Supervised in-process engine restarts.",
            )
        pool = cls(config, health=health, logger=logger, recorder=recorder)
        pool._supervisor_interval_s = supervisor_interval_s
        # Phase 1 — construct everything with replicas registered (state
        # NEW) before any watchdog/supervisor thread starts, so a shim
        # callback can never index a replica that isn't there yet.
        for i in range(n):
            # Per-replica durable-KV state dir (ISSUE 15): a shared dir
            # would let each replica's store gc() — capped at ONE
            # engine's host capacity — delete the other replicas'
            # batches (the same scoping the cross-process worker
            # harness applies).
            kv_dir = config.kv_state_dir
            if kv_dir:
                kv_dir = os.path.join(kv_dir, f"kv-replica-{i}")
            rep_cfg = dataclasses.replace(
                config, replica=i, kv_state_dir=kv_dir,
            )
            shim = _ReplicaHealth(pool, i)
            engine = InferenceEngine(
                rep_cfg, params=params, health=shim, logger=logger,
                seed=seed, draft_params=draft_params,
            )
            watchdog = Watchdog(
                engine, health=shim, logger=logger, recorder=recorder,
                stall_counter=stall_counter,
                check_interval_s=watchdog_interval_s,
            )
            supervisor = None
            if config.supervise:
                ctor = engine._ctor_args
                factory = partial(
                    pool._build_replacement, i, rep_cfg, ctor, shim
                )
                supervisor = EngineSupervisor(
                    engine, factory,
                    watchdog=watchdog, health=shim, logger=logger,
                    recorder=recorder, restart_counter=restart_counter,
                    max_restarts=config.max_engine_restarts,
                    restart_window_s=config.restart_window_s,
                    check_interval_s=supervisor_interval_s,
                    join_timeout_s=join_timeout_s,
                )
                supervisor.add_restart_listener(
                    partial(pool._on_replica_restarted, i)
                )
                supervisor.add_giveup_listener(
                    partial(pool._on_replica_giveup, i)
                )
            pool.replicas.append(_Replica(
                index=i, engine=engine, watchdog=watchdog,
                supervisor=supervisor,
            ))
        pool.tokenizer = pool.replicas[0].engine.tokenizer
        # Phase 2 — go live.
        for rep in pool.replicas:
            rep.state = SERVING
            rep.watchdog.start()
            if rep.supervisor is not None:
                rep.supervisor.start()
        if recorder is not None:
            recorder.event("replica_pool_started", replicas=n)
        if logger is not None:
            logger.info(
                "replica pool started", replicas=n,
                model=config.model, slots_per_replica=config.max_decode_slots,
            )
        return pool

    def _build_replacement(self, index, rep_cfg, ctor, shim):
        """Supervisor restart factory: flag the replica RESTARTING for
        the state gauge, then rebuild from the captured constructor
        inputs (same weights/seed — supervisor.py contract)."""
        self._transition(index, RESTARTING, only_from=(DRAINING,))
        return InferenceEngine(
            rep_cfg, params=ctor["params"], health=shim,
            logger=self.logger, seed=ctor["seed"],
            draft_params=ctor["draft_params"],
        )

    # -- replica state machine ----------------------------------------------

    def _transition(self, index: int, state: str,
                    only_from: Optional[tuple] = None) -> None:
        """Move one replica's state and re-aggregate health. DEAD is
        terminal (a gave-up supervisor never comes back)."""
        flip_down = flip_up = False
        with self._lock:
            if index >= len(self.replicas):
                return  # construction-time callback before registration
            rep = self.replicas[index]
            if rep.state == state or rep.state == DEAD:
                return
            if only_from is not None and rep.state not in only_from:
                return
            previous = rep.state
            rep.state = state
            serving = sum(1 for r in self.replicas if r.state == SERVING)
            if self._serving_advertised and serving == 0:
                self._serving_advertised = False
                flip_down = True
            elif not self._serving_advertised and serving > 0:
                self._serving_advertised = True
                flip_up = True
        if self.recorder is not None:
            self.recorder.event(
                "replica_state", replica=index, state=state,
                previous=previous,
            )
        if self.logger is not None:
            self.logger.info(
                "replica state change", replica=index, state=state,
                previous=previous,
            )
        if self.health is not None and not self._closing:
            # Aggregate health: the real service flips only on the
            # 0 ↔ ≥1 live-replica boundary — one replica's failure is
            # the pool's problem, not the load balancer's.
            if flip_down:
                self.health.shutdown()
            elif flip_up:
                self.health.resume_serving()

    def _on_replica_down(self, index: int) -> None:
        self._transition(index, DRAINING, only_from=(NEW, SERVING))

    def _on_replica_up(self, index: int) -> None:
        self._transition(index, SERVING,
                         only_from=(NEW, DRAINING, RESTARTING))

    def _on_replica_restarted(self, index: int, fresh) -> None:
        with self._lock:
            if index < len(self.replicas):
                self.replicas[index].engine = fresh
        self._transition(index, SERVING, only_from=(DRAINING, RESTARTING))

    def _on_replica_giveup(self, index: int, reason: str) -> None:
        self._transition(index, DEAD)

    # -- engine-shaped surface ----------------------------------------------

    @property
    def dead(self) -> Optional[str]:
        if self._closing:
            return "engine is shut down"
        with self._lock:
            if self.replicas and all(r.state == DEAD for r in self.replicas):
                return "all replicas dead (restart budgets exhausted)"
        return None

    @property
    def busy(self) -> bool:
        return any(rep.engine.busy for rep in self.replicas)

    def submit(self, request: GenRequest) -> None:
        """Route and submit. Raises EngineOverloadedError when the
        chosen replica sheds (retry-after contract unchanged) and
        EngineDeadError when no replica can take work."""
        if self._closing:
            raise EngineDeadError("engine is shut down")
        if request.seed is None and request.temperature > 0.0:
            # Fix the sampling root NOW: a mid-stream resume re-executes
            # with the same seed, which is what makes the suppressed
            # prefix match the delivered one on a plain engine.
            request.seed = int(self._seed_rng.integers(0, 1 << 63))
        record = _FlightRecord(request)
        exclude: set[int] = set()
        for _ in range(len(self.replicas)):
            replica, reason = self._route(request, exclude)
            if replica is None:
                break
            with record.lock:
                attempt = self._make_attempt(record)
                record.attempt = attempt
                record.replica = replica.index
            try:
                replica.engine.submit(attempt)
            except EngineDeadError:
                # Raced a fault the shim hasn't reported yet: mark and
                # try the next replica.
                self._on_replica_down(replica.index)
                exclude.add(replica.index)
                continue
            request.replica = replica.index
            self._count_decision(reason)
            return
        # No-healthy-replica fall-through: UNAVAILABLE with an
        # estimated-recovery hint (ISSUE 13 satellite). Previously only
        # the shed path attached retry-after-ms, so clients re-hit a
        # recovering tier at full rate exactly when it could least
        # afford it.
        raise EngineDeadError(
            self.dead or "no serving replica available",
            retry_after_ms=self._recovery_hint_ms(),
        )

    def _recovery_hint_ms(self) -> Optional[int]:
        """Estimated time until a replica could serve again: while any
        replica is DRAINING/RESTARTING a supervised restart is in
        flight — a couple of supervisor poll intervals is the earliest
        it can complete. All-DEAD means platform recycle: hint a
        conservative second so retries don't spin. None only when the
        pool is empty (nothing to estimate)."""
        with self._lock:
            if not self.replicas:
                return None
            recovering = any(
                r.state in (DRAINING, RESTARTING, NEW) for r in self.replicas
            )
        if recovering:
            return max(100, int(2000 * self._supervisor_interval_s))
        return 1000

    def stats(self) -> dict:
        per = []
        agg: dict = {}
        restarts = 0
        supervised = False
        gave_up_all = True
        for rep in list(self.replicas):
            snap = rep.engine.stats()
            snap["state"] = rep.state
            if rep.supervisor is not None:
                supervised = True
                snap["engine_restarts"] = rep.supervisor.restarts
                restarts += rep.supervisor.restarts
                gave_up_all = gave_up_all and rep.supervisor.gave_up
            per.append(snap)
            for key, value in snap.items():
                if key in _ADDITIVE_KEYS and isinstance(value, (int, float)):
                    agg[key] = agg.get(key, 0) + value
        agg["model"] = per[0].get("model") if per else self.config.model
        if agg.get("steps_dispatched"):
            agg["avg_lanes"] = round(
                agg.get("lane_steps", 0) / agg["steps_dispatched"], 2
            )
            # avg_lanes is per-DISPATCH (bounded by one replica's slot
            # count), so the occupancy denominator is per-replica slots
            # — dividing by the pool-summed slots_total would understate
            # a saturated pool by 1/N.
            agg["occupancy"] = round(
                agg["avg_lanes"] / max(1, self.config.max_decode_slots), 4
            )
        with self._lock:
            agg["replicas_total"] = len(self.replicas)
            agg["replicas_serving"] = sum(
                r.state == SERVING for r in self.replicas
            )
            agg["replica_states"] = {
                str(r.index): r.state for r in self.replicas
            }
            agg["requests_rerouted"] = self.requests_rerouted
            agg["streams_resumed"] = self.streams_resumed
            agg["router_decisions"] = dict(self.router_decisions)
        agg["engine_restarts"] = restarts
        agg["supervisor_gave_up"] = supervised and gave_up_all
        agg["per_replica"] = per
        return agg

    def shutdown(self, timeout: float = 10.0) -> None:
        self._closing = True
        for rep in self.replicas:
            if rep.supervisor is not None:
                rep.supervisor.stop()
        for rep in self.replicas:
            if rep.watchdog is not None:
                rep.watchdog.stop()
        for rep in self.replicas:
            rep.engine.shutdown(timeout)

    # -- router --------------------------------------------------------------

    def _route(self, request: GenRequest,
               exclude: set) -> tuple[Optional[_Replica], str]:
        """Pick the best SERVING replica for `request`. Deterministic:
        the score orders candidates and ties break on the lowest index.
        Returns (replica, reason) — reason ∈ {prefix-hit, least-delay,
        headroom} for the router-decision counter."""
        now = time.monotonic()
        with self._lock:
            candidates = [
                r for r in self.replicas
                if r.state == SERVING and r.index not in exclude
            ]
        if not candidates:
            return None, ""
        ids: list = []
        if self.config.prefix_cache and request.prompt:
            # Tokenized once per REQUEST, not per route call: reroutes
            # (and the per-candidate warmth probes) reuse the stash
            # instead of re-encoding the whole prompt.
            ids = getattr(request, "_route_ids", None)
            if ids is None:
                ids = self.tokenizer.encode(request.prompt)
                request._route_ids = ids
        scored = []
        for rep in candidates:
            warmth = rep.engine.prefix_warmth(ids) if ids else 0.0
            delay = rep.engine.queue_delay_estimate_s()
            feasible = (
                request.deadline is None or now + delay < request.deadline
            )
            # The load term is epsilon-weighted: it only decides when
            # warmth and the delay estimate tie (cold engines report 0
            # delay until their first completion — without it, every
            # cold-burst request would land on replica 0).
            score = (
                self._route_prefix_weight * warmth
                - self._route_delay_weight * delay
                - 1e-3 * rep.engine.load_fraction()
            )
            scored.append((rep, warmth, delay, feasible, score))
        feasible_only = [entry for entry in scored if entry[3]]
        filtered = bool(feasible_only) and len(feasible_only) < len(scored)
        if feasible_only:
            scored = feasible_only
        scored.sort(key=lambda entry: (-entry[4], entry[0].index))
        best = scored[0]
        if filtered:
            reason = "headroom"
        elif best[1] > 0.0:
            reason = "prefix-hit"
        else:
            reason = "least-delay"
        return best[0], reason

    def _count_decision(self, reason: str) -> None:
        with self._lock:
            if reason in self.router_decisions:
                self.router_decisions[reason] += 1

    # -- attempt plumbing ----------------------------------------------------

    def _make_attempt(self, record: _FlightRecord) -> GenRequest:
        """A shadow GenRequest for one engine attempt: same generation
        inputs (prompt/sampling/seed/deadline), SHARED cancellation
        event and trace, its own out queue feeding the pool. The
        original enqueue time carries over so TTFT spans queue + any
        reroute, not just the last attempt."""
        orig = record.request
        shadow = GenRequest(
            prompt=orig.prompt,
            max_new_tokens=orig.max_new_tokens,
            temperature=orig.temperature,
            top_p=orig.top_p,
            top_k=orig.top_k,
            seed=orig.seed,
            deadline=orig.deadline,
            out=_AttemptQueue(self, record),
            cancelled=orig.cancelled,
            trace=orig.trace,
        )
        shadow.timings.enqueued = orig.timings.enqueued
        return shadow

    def _on_attempt_event(self, record: _FlightRecord, source, item) -> None:
        """Engine event for one attempt (engine/supervisor thread).
        Decisions happen under the record lock; queue puts and resubmits
        happen outside it."""
        kind, value = item
        forward = None
        reroute_cause = None
        with record.lock:
            if record.attempt is None or source is not record.attempt.out:
                return  # late event from a superseded attempt
            if kind == "token":
                record.seen += 1
                if record.seen <= record.suppress:
                    return  # already delivered by a previous attempt
                record.emitted += 1
                timings = record.request.timings
                attempt_t = record.attempt.timings
                if timings.prefill_start == 0.0:
                    timings.prefill_start = attempt_t.prefill_start
                if timings.first_token == 0.0:
                    timings.first_token = (
                        attempt_t.first_token or time.monotonic()
                    )
                if attempt_t.prompt_tokens:
                    timings.prompt_tokens = attempt_t.prompt_tokens
                forward = item
            elif record.terminal:
                return  # duplicate terminal (wedged-restart double fail)
            elif kind == "done":
                record.terminal = True
                timings = record.request.timings
                attempt_t = record.attempt.timings
                timings.finished = attempt_t.finished or time.monotonic()
                if attempt_t.prompt_tokens:
                    timings.prompt_tokens = attempt_t.prompt_tokens
                timings.completion_tokens = record.emitted
                if timings.first_token == 0.0:
                    timings.first_token = attempt_t.first_token
                # Device-time attribution accumulates ACROSS attempts: a
                # resumed stream's device cost includes the replay work
                # on the new replica (that honesty is the point).
                timings.device_ms += attempt_t.device_ms
                forward = ("done", timings)
            else:  # error
                record.terminal = True
                if record.attempt is not None:
                    record.request.timings.device_ms += (
                        record.attempt.timings.device_ms
                    )
                if self._recoverable(record, value):
                    reroute_cause = value
                else:
                    forward = item
        if forward is not None:
            record.request.out.put(forward)
        elif reroute_cause is not None:
            self._reroute(record, reroute_cause)

    def _recoverable(self, record: _FlightRecord, message: str) -> bool:
        """Engine-lifecycle failures (the gateway's UNAVAILABLE prefix
        contract: message starts with "engine") are re-routable; request
        outcomes (deadline, cancellation, admission errors) are not."""
        return (
            message.startswith("engine")
            and not self._closing
            and not record.request.cancelled.is_set()
            and record.reroutes < self.config.max_reroutes
        )

    def _reroute(self, record: _FlightRecord, cause: str) -> None:
        """Move a failed request to a healthy replica: queued requests
        (emitted == 0) transfer losslessly; mid-stream requests resume
        with the already-delivered tokens suppressed."""
        failed_replica = record.replica
        self._on_replica_down(failed_replica)
        exclude = {failed_replica}
        while True:
            replica, reason = self._route(record.request, exclude)
            if replica is None:
                # No healthy replica: surface the original failure — the
                # gateway maps it to UNAVAILABLE and, for streams,
                # attaches the resume-supported trailer so the CLIENT
                # can resume once a replica returns.
                record.request.out.put(("error", cause))
                return
            with record.lock:
                record.reroutes += 1
                record.suppress = record.emitted
                record.seen = 0
                record.terminal = False
                resumed = record.suppress > 0
                attempt = self._make_attempt(record)
                record.attempt = attempt
                record.replica = replica.index
            try:
                replica.engine.submit(attempt)
            except (EngineDeadError, EngineOverloadedError) as e:
                if self.logger is not None:
                    self.logger.warn(
                        "reroute target rejected request; trying next",
                        replica=replica.index, error=str(e),
                    )
                if isinstance(e, EngineDeadError):
                    self._on_replica_down(replica.index)
                exclude.add(replica.index)
                continue
            record.request.replica = replica.index
            if resumed:
                record.request.restarted = True
            with self._lock:
                self.requests_rerouted += 1
                if resumed:
                    self.streams_resumed += 1
            self._count_decision(reason)
            # Trace continuity (ISSUE 10): the stream keeps its original
            # root span (attempts share it), and the failover becomes an
            # explicit `resume` child — the span tree then SHOWS the
            # replica move a postmortem reader would otherwise have to
            # reconstruct from counters. Instant span (start == end):
            # the resumed work itself lands as further decode children.
            trace = record.request.trace
            if trace is not None:
                now = time.monotonic()
                trace.child(
                    "resume", start=now, end=now,
                    from_replica=failed_replica, to_replica=replica.index,
                    suppressed_tokens=record.suppress, cause=cause,
                )
            # And the TARGET replica's flight-deck timeline marks the
            # arrival, so its Perfetto export explains the admission
            # burst a failover causes.
            timeline = getattr(replica.engine, "timeline", None)
            if timeline is not None:
                timeline.note(
                    "reroute_in", from_replica=failed_replica,
                    resumed=resumed, suppressed_tokens=record.suppress,
                )
            if self.recorder is not None:
                self.recorder.event(
                    "request_rerouted", to_replica=replica.index,
                    cause=cause, resumed=resumed,
                    suppressed_tokens=record.suppress,
                )
            if self.logger is not None:
                self.logger.info(
                    "request rerouted", to_replica=replica.index,
                    resumed=resumed, suppressed_tokens=record.suppress,
                )
            return
