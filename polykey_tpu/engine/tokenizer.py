"""Tokenizers for the serving engine.

Two implementations behind one protocol:

- ByteTokenizer — self-contained UTF-8 byte-level tokenizer (PAD/BOS/EOS +
  256 byte ids). The engine's default: needs no external vocab files, so the
  whole stack runs hermetically (the same zero-external-dependency discipline
  as the reference's mock backend, SURVEY.md §4).
- HFTokenizer — adapter over a local `transformers` tokenizer directory for
  serving real checkpoints (Llama-3 / Mixtral / Gemma vocab files). Loaded
  lazily; never fetches from the network.
"""

from __future__ import annotations

from typing import Protocol, Sequence


class Tokenizer(Protocol):
    bos_id: int
    eos_id: int
    pad_id: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes with 3 specials. Vocab: 0=PAD, 1=BOS, 2=EOS, 3+b=byte b.

    ``eos_id`` is -1 — the "no EOS" sentinel (models/generate.py
    convention): the engine's stop condition ``token == eos_id`` then
    never fires. Id 2 stays RESERVED in the vocab layout (a trained
    byte-level checkpoint that wants an EOS can claim it and serve
    through HFTokenizer-style config), but this hermetic tokenizer only
    ever fronts random-init or synthetic-corpus models, which emit any
    low id with ~uniform probability — nothing ever TRAINS id 2 to mean
    "stop", so honoring it made every exact-budget test and every bench
    stream length a per-prompt coin flip (root cause of the seed-carried
    test_int8_kv_engine_serves failure: the fp32 engine and the
    non-paged golden forward produce the IDENTICAL 8-token stream ending
    in id 2 — the early stop was faithful decoding of a meaningless
    "EOS", not an int8-KV defect)."""

    pad_id = 0
    bos_id = 1
    eos_id = -1          # no EOS: id 2 is reserved but never honored
    vocab_size = 259

    def encode(self, text: str) -> list[int]:
        return [self.bos_id] + [3 + b for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int]) -> str:
        # Ids outside the byte range (specials below, or a model vocab larger
        # than 259 sampling unmapped ids) are skipped rather than crashing.
        data = bytes(i - 3 for i in ids if 3 <= i < 259)
        return data.decode("utf-8", errors="replace")

    def decode_incremental(self, ids: Sequence[int], state: bytes = b"") -> tuple[str, bytes]:
        """Streaming decode: returns (complete text, undecoded byte tail).

        UTF-8 sequences can split across token boundaries; the tail carries
        incomplete sequences into the next call so streamed chunks never
        contain replacement characters mid-character.
        """
        data = state + bytes(i - 3 for i in ids if 3 <= i < 259)
        # Find the longest decodable prefix (max 3 trailing continuation bytes).
        for cut in range(len(data), max(len(data) - 4, -1), -1):
            try:
                return data[:cut].decode("utf-8"), data[cut:]
            except UnicodeDecodeError:
                continue
        return "", data


class HFTokenizer:
    """Local HuggingFace tokenizer adapter (no network access)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer  # lazy; heavy import

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.bos_id = self._tok.bos_token_id or 0
        self.eos_id = self._tok.eos_token_id or 0
        self.pad_id = self._tok.pad_token_id or self.eos_id
        self.vocab_size = len(self._tok)

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


class IncrementalDetokenizer:
    """Bounded-window incremental detokenization for context-dependent
    tokenizers (BPE / sentencepiece, where decode(prefix + t) is not
    decode(prefix) + decode(t)).

    The naive streaming approach re-decodes the full prefix per token —
    O(n²) host work over a stream. This keeps the standard two-offset
    window (the vLLM detokenizer recurrence): `prefix_offset` marks ids
    whose text is committed, `read_offset` marks ids represented in
    emitted text; each push decodes only ids[prefix_offset:], a handful
    of tokens in steady state. A delta is emitted only when the window's
    text GROWS and doesn't end in U+FFFD (an incomplete byte-fallback
    sequence must finish before its text is released, so streamed chunks
    never contain replacement characters mid-character).

    ''.join of pushes equals decode(all ids) up to any trailing
    incomplete sequence, which `flush()` reports."""

    def __init__(self, tok: Tokenizer):
        self._tok = tok
        self._ids: list[int] = []
        self._prefix_off = 0
        self._read_off = 0

    _WINDOW_CAP = 64   # force-commit bound on uncommitted ids

    def push(self, token_id: int) -> str:
        self._ids.append(int(token_id))
        prefix = self._tok.decode(self._ids[self._prefix_off:self._read_off])
        full = self._tok.decode(self._ids[self._prefix_off:])
        if len(full) > len(prefix) and not full.endswith("�"):
            self._prefix_off = self._read_off
            self._read_off = len(self._ids)
            return full[len(prefix):]
        if len(self._ids) - self._prefix_off > self._WINDOW_CAP:
            # Degenerate run (e.g. skipped specials or invalid byte
            # fallback) whose text never grows: force-commit so the
            # window — and the per-push re-decode — stays bounded, even
            # at the cost of releasing a trailing U+FFFD.
            delta = full[len(prefix):] if len(full) > len(prefix) else ""
            self._prefix_off = self._read_off = len(self._ids)
            return delta
        return ""

    def flush(self) -> str:
        """Text still held back (e.g. a trailing incomplete sequence)."""
        prefix = self._tok.decode(self._ids[self._prefix_off:self._read_off])
        full = self._tok.decode(self._ids[self._prefix_off:])
        self._prefix_off = self._read_off = len(self._ids)
        return full[len(prefix):] if len(full) > len(prefix) else ""


def load_tokenizer(spec: str) -> Tokenizer:
    """'byte' → ByteTokenizer; anything else is a local HF tokenizer path."""
    if spec == "byte":
        return ByteTokenizer()
    return HFTokenizer(spec)
