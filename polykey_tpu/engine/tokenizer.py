"""Tokenizers for the serving engine.

Two implementations behind one protocol:

- ByteTokenizer — self-contained UTF-8 byte-level tokenizer (PAD/BOS/EOS +
  256 byte ids). The engine's default: needs no external vocab files, so the
  whole stack runs hermetically (the same zero-external-dependency discipline
  as the reference's mock backend, SURVEY.md §4).
- HFTokenizer — adapter over a local `transformers` tokenizer directory for
  serving real checkpoints (Llama-3 / Mixtral / Gemma vocab files). Loaded
  lazily; never fetches from the network.
"""

from __future__ import annotations

from typing import Protocol, Sequence


class Tokenizer(Protocol):
    bos_id: int
    eos_id: int
    pad_id: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes with 3 specials. Vocab: 0=PAD, 1=BOS, 2=EOS, 3+b=byte b."""

    pad_id = 0
    bos_id = 1
    eos_id = 2
    vocab_size = 259

    def encode(self, text: str) -> list[int]:
        return [self.bos_id] + [3 + b for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int]) -> str:
        # Ids outside the byte range (specials below, or a model vocab larger
        # than 259 sampling unmapped ids) are skipped rather than crashing.
        data = bytes(i - 3 for i in ids if 3 <= i < 259)
        return data.decode("utf-8", errors="replace")

    def decode_incremental(self, ids: Sequence[int], state: bytes = b"") -> tuple[str, bytes]:
        """Streaming decode: returns (complete text, undecoded byte tail).

        UTF-8 sequences can split across token boundaries; the tail carries
        incomplete sequences into the next call so streamed chunks never
        contain replacement characters mid-character.
        """
        data = state + bytes(i - 3 for i in ids if 3 <= i < 259)
        # Find the longest decodable prefix (max 3 trailing continuation bytes).
        for cut in range(len(data), max(len(data) - 4, -1), -1):
            try:
                return data[:cut].decode("utf-8"), data[cut:]
            except UnicodeDecodeError:
                continue
        return "", data


class HFTokenizer:
    """Local HuggingFace tokenizer adapter (no network access)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer  # lazy; heavy import

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.bos_id = self._tok.bos_token_id or 0
        self.eos_id = self._tok.eos_token_id or 0
        self.pad_id = self._tok.pad_token_id or self.eos_id
        self.vocab_size = len(self._tok)

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


def load_tokenizer(spec: str) -> Tokenizer:
    """'byte' → ByteTokenizer; anything else is a local HF tokenizer path."""
    if spec == "byte":
        return ByteTokenizer()
    return HFTokenizer(spec)
