"""Autopilot: the closed control loop over the signal plane (ISSUE 18).

PR 9 shipped ``signals_snapshot()`` as "the autopilot read API"; this
module is the consumer. A supervised thread reads the snapshot each
tick and drives bounded, hysteretic, cooldown-rate-limited actuations:

- **knobs** — dispatch lookahead from host-stall/device-busy evidence,
  prefill budget from interactive-arrival presence, KV restore slots
  and the host-KV resident floor from the PR 15 fault/restore signals,
  the speculative gamma cap from windowed draft-acceptance evidence,
  router delay weight from per-replica TTFT skew;
- **capacity** — disagg prefill and decode tiers scale independently
  from per-tier queue-delay evidence (scale-down drains before
  killing; DisaggPool.scale_down owns the drain).

Design split: `evaluate()` and the `decide_*` functions are PURE —
(snapshot, state, config, now) in, decisions out, no I/O — so the
controller core unit-tests on canned snapshots (hysteresis bands,
cooldowns, bounds, no-flap). The `Autopilot` thread owns only the
impure edge: reading the snapshot, applying decisions through the
target's live-knob setters, and recording evidence (timeline
``autopilot_decision`` notes, the decision ring `/debug/slo` serves,
the Prometheus families exposition renders).

Discipline inherited from the signal plane: **no evidence, no
verdict**. A reading of None (young window, empty tier, no host-KV)
holds the knob; the controller never synthesizes a zero. Every
actuation is clamped to explicit bounds, never fires inside its
per-action cooldown, and only moves when the reading crosses the far
side of a hysteresis band — an oscillating signal inside the band
produces no decisions at all.

Supervisor contract: a watchdog trip pauses the loop (a restarting
engine's signals are garbage and its knobs are about to be rebuilt
from config); the restart listener re-applies the current setpoints to
the FRESH engine — actuations live on engine attributes, so adoption
alone would silently revert them — then re-arms the loop.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field, fields, replace
from typing import Optional

from ..obs.signals import signals_available, signals_snapshot

__all__ = [
    "Autopilot",
    "AutopilotConfig",
    "AutopilotUnavailableError",
    "ControllerState",
    "Decision",
    "apply_engine_knobs",
    "evaluate",
]


class AutopilotUnavailableError(RuntimeError):
    """The autopilot cannot run against this target — typed so the
    boot path fails loudly (POLYKEY_AUTOPILOT=1 with the signal plane
    disabled is a misconfiguration, not a silent no-op)."""


# Actions. Knob actions actuate through engine/pool setters; scale
# actions through DisaggPool's tier-resize API.
LOOKAHEAD = "lookahead"
PREFILL_BUDGET = "prefill_budget"
RESTORE_SLOTS = "restore_slots"
RESIDENT_FLOOR = "resident_floor"
SPEC_GAMMA = "spec_gamma"
ROUTE_DELAY_WEIGHT = "route_delay_weight"
SCALE_PREFILL = "scale_prefill"
SCALE_DECODE = "scale_decode"

UP = "up"
DOWN = "down"

_ENGINE_KNOB_SETTERS = {
    LOOKAHEAD: "set_lookahead",
    PREFILL_BUDGET: "set_prefill_budget",
    RESTORE_SLOTS: "set_kv_restore_slots",
    RESIDENT_FLOOR: "set_resident_floor",
    SPEC_GAMMA: "set_spec_gamma",
}


def apply_engine_knobs(engine, knobs: dict) -> dict:
    """Apply a knob→value dict through an engine's live setters.
    Unknown names and absent setters are skipped (a worker running an
    older engine build must not crash on a newer coordinator's knob).
    Returns name → value actually applied (post-clamp)."""
    applied: dict = {}
    for name, value in knobs.items():
        attr = _ENGINE_KNOB_SETTERS.get(name)
        setter = getattr(engine, attr, None) if attr else None
        if setter is None:
            continue
        try:
            applied[name] = setter(value)
        except (TypeError, ValueError):
            continue  # a malformed value must never kill the caller
    return applied


@dataclass(frozen=True)
class AutopilotConfig:
    """Controller policy. The env-read knobs (from_env) are the
    operator surface documented in DEPLOY.md; the remaining thresholds
    are hysteresis-band tuning with safe defaults, overridable
    programmatically (tests, soaks)."""

    interval_s: float = 2.0          # tick cadence
    cooldown_s: float = 20.0         # per-action minimum gap
    target_busy: float = 0.75        # device-busy fraction target
    lookahead_max: int = 6
    tier_min: int = 1
    tier_max: int = 3
    queue_high_s: float = 0.3        # tier queue delay: scale-up edge
    queue_low_s: float = 0.03        # tier queue delay: scale-down edge
    decisions_keep: int = 64         # decision-ring size
    min_evidence_s: float = 10.0     # youngest window worth acting on
    # Hysteresis bands (act only OUTSIDE the band; inside = hold).
    stall_high_ms: float = 1.0       # host-stall p95: deepen lookahead
    stall_low_ms: float = 0.0        # host-stall p95: relax lookahead
    arrival_high_per_s: float = 0.5  # interactive presence: narrow budget
    arrival_low_per_s: float = 0.05  # batch-quiet: widen budget
    fault_high_per_min: float = 30.0  # kv fault pressure: more slots/floor
    fault_low_per_min: float = 0.0    # kv quiet: decay toward baseline
    ttft_skew_high_ms: float = 500.0  # per-replica p95 spread: weight delay
    ttft_skew_low_ms: float = 100.0   # spread healed: decay weight
    # Speculative acceptance band (ISSUE 19). Mirrors the device-side
    # per-lane dial constants (GAMMA_ACCEPT_FLOOR/CEIL in spec_decode):
    # the host controller moves the engine-wide cap, the device dial
    # moves each lane inside it.
    spec_accept_low: float = 0.35    # acceptance collapsed: cap the rung
    spec_accept_high: float = 0.55   # acceptance healthy: restore boot cap

    @staticmethod
    def enabled_from_env() -> bool:
        """The master switch. Default OFF: with POLYKEY_AUTOPILOT unset
        nothing constructs, nothing attaches, and every existing
        suite/soak is byte-identical."""
        return os.environ.get("POLYKEY_AUTOPILOT", "").lower() in (
            "1", "true"
        )

    @classmethod
    def from_env(cls) -> "AutopilotConfig":
        """Single parse site for every POLYKEY_AUTOPILOT* knob (the
        ML004 discipline, owned here rather than EngineConfig because
        the controller runs beside the engine, not inside it)."""

        def _f(name: str, default: float) -> float:
            raw = os.environ.get(name, "")
            try:
                return float(raw) if raw.strip() else default
            except ValueError:
                return default

        def _i(name: str, default: int) -> int:
            raw = os.environ.get(name, "")
            try:
                return int(raw) if raw.strip() else default
            except ValueError:
                return default

        return cls(
            interval_s=max(0.05, _f("POLYKEY_AUTOPILOT_INTERVAL", 2.0)),
            cooldown_s=max(0.0, _f("POLYKEY_AUTOPILOT_COOLDOWN", 20.0)),
            target_busy=min(1.0, max(
                0.0, _f("POLYKEY_AUTOPILOT_TARGET_BUSY", 0.75))),
            lookahead_max=max(
                1, _i("POLYKEY_AUTOPILOT_LOOKAHEAD_MAX", 6)),
            tier_min=max(1, _i("POLYKEY_AUTOPILOT_TIER_MIN", 1)),
            tier_max=max(1, _i("POLYKEY_AUTOPILOT_TIER_MAX", 3)),
            queue_high_s=_f("POLYKEY_AUTOPILOT_QUEUE_HIGH", 0.3),
            queue_low_s=_f("POLYKEY_AUTOPILOT_QUEUE_LOW", 0.03),
            decisions_keep=max(
                1, _i("POLYKEY_AUTOPILOT_DECISIONS", 64)),
            min_evidence_s=max(
                0.0, _f("POLYKEY_AUTOPILOT_MIN_EVIDENCE", 10.0)),
        )


@dataclass
class Decision:
    """One typed actuation verdict — exactly what the timeline event,
    the decision ring, and the Prometheus counter record."""

    action: str
    direction: str           # "up" | "down"
    reason: str              # human-readable evidence sentence
    reading: Optional[float]  # the measurement that crossed the band
    old: float
    new: float

    def as_dict(self) -> dict:
        return {
            "action": self.action, "direction": self.direction,
            "reason": self.reason, "reading": self.reading,
            "old": self.old, "new": self.new,
        }


@dataclass
class ControllerState:
    """Everything `evaluate` needs beyond the snapshot, kept explicit
    so tests drive the pure core without an Autopilot instance.

    setpoints — current value per action (the gauge family);
    baselines — boot values: decay targets and the operator's floor
    (the autopilot relaxes TOWARD config, never below it);
    bounds — (lo, hi) hard clamp per action;
    steps — increment per decision (ints step, floats scale);
    last_fired — action → monotonic timestamp of its last decision.
    """

    setpoints: dict = field(default_factory=dict)
    baselines: dict = field(default_factory=dict)
    bounds: dict = field(default_factory=dict)
    steps: dict = field(default_factory=dict)
    last_fired: dict = field(default_factory=dict)


def _label_seconds(label: str) -> float:
    """Inverse of obs.signals.window_label ("1m" → 60)."""
    try:
        if label.endswith("h"):
            return float(label[:-1]) * 3600.0
        if label.endswith("m"):
            return float(label[:-1]) * 60.0
        if label.endswith("s"):
            return float(label[:-1])
        return float(label)
    except ValueError:
        return float("inf")


def _freshest(windowed: Optional[dict]) -> Optional[dict]:
    """The shortest window's summary — breach detection acts on the
    freshest evidence (the longest window is the budget's, not the
    controller's). None when every window is still empty."""
    if not windowed:
        return None
    for label in sorted(windowed, key=_label_seconds):
        summary = windowed[label]
        if summary:
            return summary
    return None


def _ready(state: ControllerState, action: str, cfg: AutopilotConfig,
           now: float) -> bool:
    return now - state.last_fired.get(action, -1e18) >= cfg.cooldown_s


def _bounded(state: ControllerState, action: str, value: float) -> float:
    lo, hi = state.bounds.get(action, (float("-inf"), float("inf")))
    return min(hi, max(lo, value))


# ---------------------------------------------------------------------------
# Pure decision functions — one per actuated knob/capacity axis.
# Each returns a Decision or None ("hold"); None ALWAYS means either
# no evidence (null verdict) or the reading sits inside the hysteresis
# band or the action is cooling down / at its bound.
# ---------------------------------------------------------------------------


def decide_lookahead(summary: Optional[dict], state: ControllerState,
                     cfg: AutopilotConfig, now: float) -> Optional[Decision]:
    """Deepen the dispatch pipeline while the host is the bottleneck:
    nonzero host-stall p95 with the device under the busy target means
    readback latency is not hidden. Relax one step back toward the
    boot depth once stalls vanish AND the device runs at target — both
    edges, so a reading between them holds (hysteresis)."""
    if summary is None or not _ready(state, LOOKAHEAD, cfg, now):
        return None
    stall_p95 = summary.get("host_stall_ms_p95")
    busy = summary.get("device_busy_fraction")
    if stall_p95 is None or busy is None:
        return None  # null verdict: young window or idle engine
    old = state.setpoints.get(LOOKAHEAD)
    if old is None:
        return None
    if stall_p95 > cfg.stall_high_ms and busy < cfg.target_busy:
        new = _bounded(state, LOOKAHEAD, old + 1)
        if new != old:
            return Decision(
                LOOKAHEAD, UP,
                f"host_stall p95 {stall_p95:.1f}ms with device_busy "
                f"{busy:.2f} < target {cfg.target_busy:.2f}",
                stall_p95, old, new,
            )
    elif (stall_p95 <= cfg.stall_low_ms and busy >= cfg.target_busy
            and old > state.baselines.get(LOOKAHEAD, old)):
        new = max(state.baselines[LOOKAHEAD], old - 1)
        return Decision(
            LOOKAHEAD, DOWN,
            f"host_stall p95 {stall_p95:.1f}ms at device_busy "
            f"{busy:.2f}; relaxing toward boot depth",
            stall_p95, old, new,
        )
    return None


def decide_prefill_budget(summary: Optional[dict],
                          pool_windows: Optional[dict],
                          state: ControllerState, cfg: AutopilotConfig,
                          now: float) -> Optional[Decision]:
    """Interactive-arrival presence: live arrivals mean in-flight
    decode ITL needs protecting — narrow the interleave budget by one
    chunk. A quiet pool (batch work, no interactive tail to protect)
    widens it back to move prompts faster. Arrival evidence comes from
    the aggregate window (in-process engines) or, for a disagg target
    with no in-process planes, from the pool's windowed handoff rate."""
    if not _ready(state, PREFILL_BUDGET, cfg, now):
        return None
    rate = None
    if summary is not None:
        rate = summary.get("arrival_rate_per_s")
    if rate is None and pool_windows:
        pool = _freshest(pool_windows)
        if pool and pool.get("covered_s", 0) > 0:
            handoffs = pool.get("handoffs") or {}
            rate = round(
                sum(handoffs.values()) / pool["covered_s"], 3
            )
    if rate is None:
        return None  # no arrival evidence anywhere: hold
    old = state.setpoints.get(PREFILL_BUDGET)
    chunk = state.steps.get(PREFILL_BUDGET, 0)
    if old is None or chunk <= 0:
        return None
    if rate >= cfg.arrival_high_per_s:
        new = _bounded(state, PREFILL_BUDGET, old - chunk)
        if new != old:
            return Decision(
                PREFILL_BUDGET, DOWN,
                f"interactive arrivals {rate:.2f}/s >= "
                f"{cfg.arrival_high_per_s:.2f}/s; narrowing interleave "
                "to protect ITL",
                rate, old, new,
            )
    elif rate <= cfg.arrival_low_per_s:
        new = _bounded(state, PREFILL_BUDGET, old + chunk)
        if new != old:
            return Decision(
                PREFILL_BUDGET, UP,
                f"arrivals {rate:.2f}/s <= {cfg.arrival_low_per_s:.2f}/s;"
                " widening interleave for prompt throughput",
                rate, old, new,
            )
    return None


def decide_restore_slots(summary: Optional[dict], state: ControllerState,
                         cfg: AutopilotConfig,
                         now: float) -> Optional[Decision]:
    """KV fault pressure (PR 15 histograms): a sustained page-fault
    rate with restore p95 well above p50 means faulting lanes queue
    behind the per-iteration restore budget — raise it. Zero faults
    decay it back toward the boot value."""
    if summary is None or not _ready(state, RESTORE_SLOTS, cfg, now):
        return None
    old = state.setpoints.get(RESTORE_SLOTS)
    if old is None:
        return None  # no host-KV tier on this target
    rate = summary.get("kv_fault_rate_per_min")
    if rate is None:
        return None
    if rate > cfg.fault_high_per_min:
        new = _bounded(state, RESTORE_SLOTS, old + 1)
        if new != old:
            p50 = summary.get("kv_restore_ms_p50")
            p95 = summary.get("kv_restore_ms_p95")
            tail = (f"; restore p95/p50 {p95:.0f}/{p50:.0f}ms"
                    if p50 and p95 else "")
            return Decision(
                RESTORE_SLOTS, UP,
                f"kv fault rate {rate:.1f}/min > "
                f"{cfg.fault_high_per_min:.1f}/min{tail}",
                rate, old, new,
            )
    elif (rate <= cfg.fault_low_per_min
            and old > state.baselines.get(RESTORE_SLOTS, old)):
        new = max(state.baselines[RESTORE_SLOTS], old - 1)
        return Decision(
            RESTORE_SLOTS, DOWN,
            f"kv fault rate {rate:.1f}/min; relaxing toward boot budget",
            rate, old, new,
        )
    return None


def decide_resident_floor(summary: Optional[dict], state: ControllerState,
                          cfg: AutopilotConfig,
                          now: float) -> Optional[Decision]:
    """Resize the host-KV resident floor under fault pressure
    (PersistentKV shape): sustained faults mean the working set
    thrashes the floor — spill earlier so hot pages stay resident.
    Quiet decay returns the device pool to serving capacity."""
    if summary is None or not _ready(state, RESIDENT_FLOOR, cfg, now):
        return None
    old = state.setpoints.get(RESIDENT_FLOOR)
    step = state.steps.get(RESIDENT_FLOOR, 0)
    if old is None or step <= 0:
        return None
    rate = summary.get("kv_fault_rate_per_min")
    if rate is None:
        return None
    if rate > cfg.fault_high_per_min:
        new = _bounded(state, RESIDENT_FLOOR, old + step)
        if new != old:
            return Decision(
                RESIDENT_FLOOR, UP,
                f"kv fault rate {rate:.1f}/min > "
                f"{cfg.fault_high_per_min:.1f}/min; raising spill floor",
                rate, old, new,
            )
    elif (rate <= cfg.fault_low_per_min
            and old > state.baselines.get(RESIDENT_FLOOR, old)):
        new = max(state.baselines[RESIDENT_FLOOR], old - step)
        return Decision(
            RESIDENT_FLOOR, DOWN,
            f"kv fault rate {rate:.1f}/min; relaxing spill floor",
            rate, old, new,
        )
    return None


def decide_gamma(summary: Optional[dict], state: ControllerState,
                 cfg: AutopilotConfig, now: float) -> Optional[Decision]:
    """Speculation width from windowed acceptance evidence (ISSUE 19):
    when the fleet-wide accept rate collapses below the band, every
    verify position past the first is wasted target compute — snap the
    engine's gamma cap down to the low ladder rung (set_spec_gamma
    rung-snaps, so any value below gamma_max lands on gamma_low).
    Healthy acceptance above the band restores the boot cap. The
    per-lane device dial handles per-sequence variation INSIDE the cap;
    this knob is the coarse host override for workload-wide collapse.
    The setpoint only exists when the engine booted with a draft model
    (knob_setpoints gates it), so the action is armed iff spec is on."""
    if summary is None or not _ready(state, SPEC_GAMMA, cfg, now):
        return None
    old = state.setpoints.get(SPEC_GAMMA)
    if old is None:
        return None  # no draft model on this target: never arms
    rate = summary.get("spec_accept_rate")
    if rate is None:
        return None  # no drafts proposed in the window: null verdict
    lo, _hi = state.bounds.get(SPEC_GAMMA, (1, old))
    if rate < cfg.spec_accept_low and old > lo:
        return Decision(
            SPEC_GAMMA, DOWN,
            f"spec accept rate {rate:.2f} < {cfg.spec_accept_low:.2f}; "
            "capping speculation at the low rung",
            rate, old, lo,
        )
    if (rate > cfg.spec_accept_high
            and old < state.baselines.get(SPEC_GAMMA, old)):
        new = state.baselines[SPEC_GAMMA]
        return Decision(
            SPEC_GAMMA, UP,
            f"spec accept rate {rate:.2f} > {cfg.spec_accept_high:.2f}; "
            "restoring boot gamma cap",
            rate, old, new,
        )
    return None


def decide_route_weights(replicas: Optional[dict], state: ControllerState,
                         cfg: AutopilotConfig,
                         now: float) -> Optional[Decision]:
    """Per-replica TTFT skew (PR 7/13 routing): when one replica's
    windowed p95 runs far ahead of another's, the router is not
    spreading delay — double the delay weight so queue-delay dominates
    warmth. Healed skew decays the weight back toward the configured
    baseline."""
    if not replicas or not _ready(state, ROUTE_DELAY_WEIGHT, cfg, now):
        return None
    old = state.setpoints.get(ROUTE_DELAY_WEIGHT)
    if old is None:
        return None
    p95s = []
    for entry in replicas.values():
        summary = _freshest(entry.get("windows"))
        if summary and summary.get("ttft_ms_p95") is not None:
            p95s.append(summary["ttft_ms_p95"])
    if len(p95s) < 2:
        return None  # skew needs at least two measured replicas
    skew = max(p95s) - min(p95s)
    if skew > cfg.ttft_skew_high_ms:
        new = _bounded(state, ROUTE_DELAY_WEIGHT, old * 2.0)
        if new != old:
            return Decision(
                ROUTE_DELAY_WEIGHT, UP,
                f"replica ttft p95 skew {skew:.0f}ms > "
                f"{cfg.ttft_skew_high_ms:.0f}ms",
                skew, old, new,
            )
    elif (skew < cfg.ttft_skew_low_ms
            and old > state.baselines.get(ROUTE_DELAY_WEIGHT, old)):
        new = max(state.baselines[ROUTE_DELAY_WEIGHT], old / 2.0)
        return Decision(
            ROUTE_DELAY_WEIGHT, DOWN,
            f"replica ttft p95 skew {skew:.0f}ms healed",
            skew, old, new,
        )
    return None


def decide_scale(tier: str, tiers: Optional[dict],
                 state: ControllerState, cfg: AutopilotConfig,
                 now: float) -> Optional[Decision]:
    """Elastic tier sizing from per-tier queue-delay evidence: the
    heartbeat-fed mean queue delay across a tier's serving workers
    (outage waiters' ages join the mean when the pings go dark).
    Above the high edge, grow — but only with NO boot already in
    flight (serving == total): a worker boot pays a jax import +
    compile storm, and stacking a second one starves the capacity
    the first was supposed to deliver; measure the tier with its
    in-flight capacity landed, then reassess. Below the low edge
    with headroom, shrink (DisaggPool drains before killing). None
    queue delay — empty tier or no ping yet — holds."""
    action = SCALE_PREFILL if tier == "prefill" else SCALE_DECODE
    if not tiers or not _ready(state, action, cfg, now):
        return None
    entry = tiers.get(tier)
    if not entry:
        return None
    delay = entry.get("queue_delay_s")
    serving = entry.get("serving", 0)
    total = entry.get("total", 0)
    if delay is None:
        return None  # no heartbeat evidence: hold
    if (delay > cfg.queue_high_s and total < cfg.tier_max
            and serving == total):
        return Decision(
            action, UP,
            f"{tier} queue delay {delay:.3f}s > {cfg.queue_high_s:.3f}s",
            delay, total, total + 1,
        )
    if (delay < cfg.queue_low_s and serving > cfg.tier_min
            and total > cfg.tier_min):
        return Decision(
            action, DOWN,
            f"{tier} queue delay {delay:.3f}s < {cfg.queue_low_s:.3f}s "
            "with headroom; draining one worker",
            delay, total, total - 1,
        )
    return None


def evaluate(snapshot: dict, state: ControllerState, cfg: AutopilotConfig,
             now: float) -> list[Decision]:
    """The pure controller core: one tick's verdicts over one
    signals_snapshot. Enforces the evidence gate (a youngest window
    covering less than min_evidence_s holds every aggregate-driven
    knob), then runs each decision function. Capacity decisions run
    only when the snapshot carries tier evidence (disagg targets)."""
    decisions: list[Decision] = []
    summary = _freshest(snapshot.get("aggregate"))
    if summary is not None and summary.get(
            "covered_s", 0.0) < cfg.min_evidence_s:
        summary = None  # young engine: explicit hold, not tiny-window noise
    pool_windows = snapshot.get("pool")
    for decision in (
        decide_lookahead(summary, state, cfg, now),
        decide_prefill_budget(summary, pool_windows, state, cfg, now),
        decide_restore_slots(summary, state, cfg, now),
        decide_resident_floor(summary, state, cfg, now),
        decide_gamma(summary, state, cfg, now),
        decide_route_weights(snapshot.get("replicas"), state, cfg, now),
        decide_scale("prefill", snapshot.get("tiers"), state, cfg, now),
        decide_scale("decode", snapshot.get("tiers"), state, cfg, now),
    ):
        if decision is not None:
            decisions.append(decision)
    return decisions


# ---------------------------------------------------------------------------
# The impure edge: the control thread.
# ---------------------------------------------------------------------------


class Autopilot:
    """The control thread over one target (InferenceEngine, ReplicaPool
    or DisaggPool). start() refuses (typed) when the signal plane is
    off; stop() detaches. While running, `target.autopilot is self`, so
    /debug/slo, /metrics and flightwatch all see the same state."""

    def __init__(self, target, config: Optional[AutopilotConfig] = None,
                 supervisor=None, obs=None, logger=None):
        self.target = target
        self.cfg = config or AutopilotConfig.from_env()
        self.obs = obs
        self.logger = logger
        self._explicit_supervisor = supervisor
        self.state: Optional[ControllerState] = None
        self.decisions: deque = deque(maxlen=self.cfg.decisions_keep)
        self.decisions_total: dict[tuple[str, str], int] = {}
        self._lock = threading.Lock()
        self._paused_reasons: set[str] = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_tier_restores = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Autopilot":
        if not signals_available(self.target):
            raise AutopilotUnavailableError(
                "autopilot needs the signal plane: "
                "POLYKEY_SIGNALS_INTERVAL=0 disables it, so there is "
                "nothing to read — unset it (or set POLYKEY_AUTOPILOT=0)"
            )
        self.state = self._build_state()
        self._attach_supervisors()
        self.target.autopilot = self
        self._thread = threading.Thread(
            target=self._run, name="polykey-autopilot", daemon=True
        )
        self._thread.start()
        if self.logger is not None:
            self.logger.info(
                "autopilot armed",
                interval_s=self.cfg.interval_s,
                cooldown_s=self.cfg.cooldown_s,
                setpoints=dict(self.state.setpoints),
            )
        self._note("autopilot_armed", setpoints=dict(self.state.setpoints))
        return self

    def stop(self, join_timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout_s)
        if getattr(self.target, "autopilot", None) is self:
            self.target.autopilot = None

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.interval_s):
            try:
                self.tick()
            except Exception as e:  # the loop must never die silently
                if self.logger is not None:
                    self.logger.error("autopilot tick failed",
                                      error=str(e))

    # -- target shape --------------------------------------------------------

    def _engines(self) -> list:
        if hasattr(self.target, "workers"):
            return []  # disagg: engines live in worker processes
        replicas = getattr(self.target, "replicas", None)
        if replicas is not None:
            return [rep.engine for rep in replicas]
        return [self.target]

    def _build_state(self) -> ControllerState:
        """Baselines/bounds from the target's boot configuration — the
        autopilot widens from the operator's settings and decays back
        to them, never below."""
        config = self.target.config
        state = ControllerState()
        chunk = config.prefill_chunk or max(config.prefill_buckets)
        engines = self._engines()
        if engines:
            knobs = engines[0].knob_setpoints()
        else:
            # Disagg: the coordinator holds no engine; boot setpoints
            # mirror the config every worker was spawned with.
            knobs = {
                "lookahead": max(1, config.lookahead_blocks),
                "prefill_budget": max(
                    config.prefill_budget or 2 * chunk, chunk
                ),
            }
            if config.host_kv_bytes > 0:
                knobs["restore_slots"] = config.host_kv_restore_slots
                knobs["resident_floor"] = (
                    config.host_kv_resident_pages or config.num_pages // 8
                )
            if config.draft_model:
                knobs["spec_gamma"] = config.spec_gamma
        state.setpoints[LOOKAHEAD] = knobs["lookahead"]
        state.baselines[LOOKAHEAD] = knobs["lookahead"]
        state.bounds[LOOKAHEAD] = (
            knobs["lookahead"], max(knobs["lookahead"], self.cfg.lookahead_max)
        )
        budget = knobs["prefill_budget"]
        state.setpoints[PREFILL_BUDGET] = budget
        state.baselines[PREFILL_BUDGET] = budget
        state.steps[PREFILL_BUDGET] = chunk
        state.bounds[PREFILL_BUDGET] = (chunk, max(budget * 2, 4 * chunk))
        if "restore_slots" in knobs:
            slots = knobs["restore_slots"]
            state.setpoints[RESTORE_SLOTS] = slots
            state.baselines[RESTORE_SLOTS] = slots
            state.bounds[RESTORE_SLOTS] = (
                slots, max(slots, config.max_decode_slots)
            )
            floor = knobs["resident_floor"]
            step = max(1, config.num_pages // 16)
            state.setpoints[RESIDENT_FLOOR] = floor
            state.baselines[RESIDENT_FLOOR] = floor
            state.steps[RESIDENT_FLOOR] = step
            state.bounds[RESIDENT_FLOOR] = (
                floor, max(floor, config.num_pages // 2)
            )
        if "spec_gamma" in knobs:
            cap = knobs["spec_gamma"]
            state.setpoints[SPEC_GAMMA] = cap
            state.baselines[SPEC_GAMMA] = cap
            if engines:
                low = engines[0]._gamma_low
            else:
                low = (max(1, config.spec_gamma // 2)
                       if config.adaptive_gamma else config.spec_gamma)
            state.bounds[SPEC_GAMMA] = (low, cap)
        if hasattr(self.target, "set_route_weights"):
            weight = config.route_delay_weight
            state.setpoints[ROUTE_DELAY_WEIGHT] = weight
            state.baselines[ROUTE_DELAY_WEIGHT] = weight
            state.bounds[ROUTE_DELAY_WEIGHT] = (weight, weight * 8.0)
        return state

    def _attach_supervisors(self) -> None:
        supervisors = []
        if self._explicit_supervisor is not None:
            supervisors.append(self._explicit_supervisor)
        for rep in getattr(self.target, "replicas", None) or ():
            if getattr(rep, "supervisor", None) is not None:
                supervisors.append(rep.supervisor)
        for supervisor in supervisors:
            supervisor.add_trip_listener(self._on_trip)
            supervisor.add_restart_listener(self._on_restart)

    # -- supervisor pause / re-arm -------------------------------------------

    def pause(self, reason: str) -> None:
        with self._lock:
            fresh = reason not in self._paused_reasons
            self._paused_reasons.add(reason)
        if fresh:
            self._note("autopilot_paused", reason=reason)
            if self.logger is not None:
                self.logger.info("autopilot paused", reason=reason)

    def resume(self, reason: str) -> None:
        with self._lock:
            was = reason in self._paused_reasons
            self._paused_reasons.discard(reason)
            clear = not self._paused_reasons
        if was and clear:
            self._note("autopilot_rearmed", reason=reason)
            if self.logger is not None:
                self.logger.info("autopilot re-armed", reason=reason)

    @property
    def paused(self) -> bool:
        with self._lock:
            return bool(self._paused_reasons)

    def _on_trip(self, *_args) -> None:
        self.pause("supervisor-restart")

    def _on_restart(self, fresh) -> None:
        """A fresh engine boots with config-default knobs; the current
        setpoints must outlive the restart (adoption carries metrics,
        not engine attributes), so re-apply them BEFORE re-arming."""
        if self.state is not None:
            apply_engine_knobs(fresh, self._knob_setpoints())
        self.resume("supervisor-restart")

    def _knob_setpoints(self) -> dict:
        assert self.state is not None
        return {
            name: self.state.setpoints[name]
            for name in _ENGINE_KNOB_SETTERS
            if name in self.state.setpoints
        }

    # -- the tick ------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> list[Decision]:
        """One control iteration; public so tests and soaks can drive
        it synchronously. Returns the decisions applied."""
        if self.state is None:
            return []
        if self.paused:
            return []
        if now is None:
            now = time.monotonic()
        self._reapply_after_worker_restarts()
        snapshot = signals_snapshot(self.target)
        decisions = evaluate(snapshot, self.state, self.cfg, now)
        for decision in decisions:
            self._apply(decision, now)
        return decisions

    def _reapply_after_worker_restarts(self) -> None:
        """Disagg: a respawned worker process boots from _config_env,
        losing every actuated knob — when the pool's restore counter
        moves, re-broadcast the current setpoints (the cross-process
        analogue of the supervisor restart listener)."""
        restores = getattr(self.target, "tier_restores", None)
        if not isinstance(restores, dict):
            return
        total = sum(restores.values())
        with self._lock:
            moved = total > self._last_tier_restores
            if moved:
                self._last_tier_restores = total
        if moved:
            knobs = self._knob_setpoints()
            apply = getattr(self.target, "apply_knobs", None)
            if knobs and callable(apply):
                apply(knobs)

    def _apply(self, decision: Decision, now: float) -> None:
        applied = self._actuate(decision)
        if applied is None:
            return  # actuator refused (e.g. tier resize raced a close)
        decision.new = applied
        self.state.last_fired[decision.action] = now
        if decision.action not in (SCALE_PREFILL, SCALE_DECODE):
            self.state.setpoints[decision.action] = applied
        key = (decision.action, decision.direction)
        with self._lock:
            # polylint: disable=ML002(keyed by (action, direction): 8 static action names x 2 directions, not per-request data)
            self.decisions_total[key] = self.decisions_total.get(key, 0) + 1
            self.decisions.append(
                {"t": round(now, 3), **decision.as_dict()}
            )
        self._note("autopilot_decision", **decision.as_dict())
        if self.obs is not None and self.obs.recorder is not None:
            self.obs.recorder.event(
                "autopilot_decision", **decision.as_dict()
            )
        if self.logger is not None:
            self.logger.info(
                "autopilot decision", action=decision.action,
                direction=decision.direction, reason=decision.reason,
                old=decision.old, new=decision.new,
            )

    def _actuate(self, decision: Decision):
        """Route one decision to the target's actuation surface.
        Returns the applied value, or None when the actuator refused."""
        target = self.target
        if decision.action == SCALE_PREFILL:
            return self._scale("prefill", decision)
        if decision.action == SCALE_DECODE:
            return self._scale("decode", decision)
        if decision.action == ROUTE_DELAY_WEIGHT:
            setter = getattr(target, "set_route_weights", None)
            if setter is None:
                return None
            _prefix, delay = setter(delay=decision.new)
            return delay
        knobs = {decision.action: decision.new}
        if hasattr(target, "workers"):           # disagg: control plane
            applied = target.apply_knobs(knobs)
        elif hasattr(target, "apply_engine_knobs"):  # replica pool
            applied = target.apply_engine_knobs(knobs)
        else:                                    # bare engine
            applied = apply_engine_knobs(target, knobs)
        return applied.get(decision.action)

    def _scale(self, tier: str, decision: Decision):
        if decision.direction == UP:
            scale = getattr(self.target, "scale_up", None)
        else:
            scale = getattr(self.target, "scale_down", None)
        if scale is None:
            return None
        name = scale(tier)
        return decision.new if name is not None else None

    # -- observability -------------------------------------------------------

    def _note(self, kind: str, **attrs) -> None:
        timeline = getattr(self.target, "timeline", None)
        if timeline is None:
            replicas = getattr(self.target, "replicas", None)
            if replicas:
                timeline = getattr(replicas[0].engine, "timeline", None)
        if timeline is not None:
            timeline.note(kind, **attrs)

    def snapshot(self) -> dict:
        """JSON-able controller state for /debug/slo ("autopilot" key),
        the Prometheus families, and flightwatch."""
        with self._lock:
            totals = {
                f"{action}:{direction}": count
                for (action, direction), count
                in sorted(self.decisions_total.items())
            }
            recent = list(self.decisions)
        return {
            "enabled": True,
            "paused": self.paused,
            "interval_s": self.cfg.interval_s,
            "cooldown_s": self.cfg.cooldown_s,
            "setpoints": dict(self.state.setpoints) if self.state else {},
            "decisions_total": totals,
            "decisions": recent,
        }


def maybe_start(target, supervisor=None, obs=None, logger=None):
    """Gateway boot hook: construct+start an Autopilot iff
    POLYKEY_AUTOPILOT=1. Returns the running instance or None. A
    start-time refusal (signal plane off) propagates — the typed error
    is the contract, not a log line."""
    if not AutopilotConfig.enabled_from_env():
        return None
    return Autopilot(
        target, config=AutopilotConfig.from_env(),
        supervisor=supervisor, obs=obs, logger=logger,
    ).start()


# Unused-import guards for the dataclass helpers referenced only in
# type positions on some Python versions.
_ = (fields, replace)
