"""Roofline accounting: grade measured serving numbers against physics.

VERDICT r4 missing #4: every bench phase must carry model-bandwidth-
utilization (decode is weight+KV *read*-bound) and model-FLOP-utilization
(prefill is MXU-bound) so any chip/model measurement is comparable to the
hardware ceiling at a glance — not only to the 8B north-star target.

The reference publishes no performance numbers at all (SURVEY.md §6), so
both the targets (BASELINE.md) and this physics grading are north-star
scope. All byte/FLOP counts derive from the architecture geometry in
models/config.py (ModelConfig.num_params / num_active_params); they are
intentionally first-order (no norm/activation traffic, no padding):
good to a few percent for dense models, which is enough to tell
"at 6% of roofline" from "at 60%".

Decode, per engine step with B live lanes at average context C:
  step_bytes = dense_weights + experts_hit * expert_bytes
               + B * C * kv_bytes_per_token
  (weights amortize over lanes — THE reason batched decode wins; for
  MoE, the experts HIT per step is min(num_experts, B * top_k): at
  serving batch widths effectively every expert streams every step,
  so MoE weight traffic does NOT amortize the way dense does.)
  flops  = B * (2 * active_params + 4 * L * C * H * Dh)
  MBU    = achieved bytes/s / (n_chips * chip HBM bytes/s)
  MFU    = achieved flops/s / (n_chips * chip peak flops)
Speculative decoding adds the draft model's step weight read (the draft
streams its weights every decode block too); its extra FLOPs are second-
order for byte-bound decode and are not modeled.
Prefill FLOPs for a P-token prompt ≈ P * (2 * active_params) +
  2 * L * P^2 * H * Dh (causal attention ≈ half the dense 4x term).
`prefill_mfu_at_ttft` divides by the measured light-load TTFT, so it is
a LOWER bound on kernel MFU (TTFT includes host tokenize/queue/dispatch).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from polykey_tpu.models.config import ModelConfig, get_config


@dataclass(frozen=True)
class ChipSpec:
    name: str
    # No int8 OPS peak here: our int8 paths keep bf16 activations, so the
    # MXU's 2x int8 mode never engages and bf16 peak stays the honest MFU
    # denominator (grade() comment below) — an int8 field would invite
    # grading against a ceiling this stack cannot reach.
    peak_bf16_flops: float     # FLOP/s
    hbm_bytes_per_s: float
    hbm_bytes: float           # per-chip capacity (drives hbm_weight_fraction)


# Public spec-sheet numbers.
CHIP_SPECS = {
    # Cloud TPU v5e ("TPU v5 lite"): 197 bf16 TFLOP/s, 819 GB/s HBM BW,
    # 16 GiB HBM per chip.
    "tpu-v5e": ChipSpec("tpu-v5e", 197e12, 819e9, 16 * 2**30),
    # v5p for completeness (multi-host design target).
    "tpu-v5p": ChipSpec("tpu-v5p", 459e12, 2765e9, 95 * 2**30),
}


def detect_chip() -> Optional[ChipSpec]:
    """Map jax.devices()[0].device_kind to a ChipSpec; None off-TPU (a
    CPU run has no meaningful roofline — mbu/mfu stay null there, but the
    per-token byte/FLOP geometry is still emitted). Only kinds this table
    actually knows map to a spec: an unknown v5 variant (or any future
    chip) returns None rather than silently grading against v5p's
    2765 GB/s roofline (ADVICE r5)."""
    try:
        import jax

        d = jax.devices()[0]
        if d.platform != "tpu":
            return None
        kind = d.device_kind.lower()
        if "v5 lite" in kind or "v5e" in kind:
            return CHIP_SPECS["tpu-v5e"]
        if "v5p" in kind:
            return CHIP_SPECS["tpu-v5p"]
    except Exception:
        # No devices / unqueryable backend: roofline annotation is
        # optional context, None disables it without failing the bench.
        return None
    return None


def _bytes_per_el(dtype: str) -> float:
    return {"float32": 4.0, "bfloat16": 2.0, "int8": 1.0}.get(dtype, 2.0)


def _weight_bytes_split(cfg: ModelConfig, dtype: str,
                        quantize: bool, bits: int) -> tuple[float, float]:
    """(dense_bytes, per_expert_bytes) a decode step can stream from HBM.

    dense_bytes: everything read unconditionally each step — attention +
    norms + router (+ the dense MLP for non-MoE) + the LM head (full
    vocab x hidden matmul per step). The embedding table contributes only
    a row gather (negligible). per_expert_bytes: ONE expert's MLP; the
    caller decides how many experts a step hits. int4 keeps embed/lm_head
    at int8 (models/quant.py) — modeled as such."""
    embed = cfg.vocab_size * cfg.hidden_size
    head_params = embed  # lm head is read every step, tied or not
    total = cfg.num_params()
    table_params = embed + (0 if cfg.tie_embeddings else embed)
    block_params = total - table_params  # blocks + final norm
    expert_params = 0.0
    if cfg.is_moe:
        expert_params = 3.0 * cfg.hidden_size * cfg.intermediate_size
        block_params -= cfg.num_layers * cfg.num_experts * expert_params
    if not quantize:
        b = _bytes_per_el(dtype)
        return (block_params + head_params) * b, \
            cfg.num_layers * expert_params * b
    block_b = bits / 8.0
    # Quant scales: one fp32 per channel-group; second-order, ignored.
    # embed/lm_head stay int8 in the int4 scheme.
    return block_params * block_b + head_params * 1.0, \
        cfg.num_layers * expert_params * block_b


def weight_read_bytes(cfg: ModelConfig, dtype: str, quantize: bool,
                      bits: int, lanes: float = 1.0) -> float:
    """Weight bytes one decode step streams from HBM at `lanes` live
    lanes. Dense models: lane-independent. MoE: experts hit per step =
    min(num_experts, lanes * top_k) — the expected coverage; exact
    routing multinomials are second-order."""
    dense, per_expert = _weight_bytes_split(cfg, dtype, quantize, bits)
    if not cfg.is_moe:
        return dense
    hit = min(float(cfg.num_experts),
              max(lanes, 1.0) * cfg.num_experts_per_tok)
    return dense + hit * per_expert


def weight_resident_bytes(cfg: ModelConfig, dtype: str, quantize: bool,
                          bits: int) -> float:
    """HBM the model's weights OCCUPY (capacity, not per-step traffic):
    every expert is resident even though a step streams only the hit
    ones, and an untied embedding table sits in HBM even though decode
    only row-gathers it. Feeds grade()'s hbm_weight_fraction — the
    headroom number that decides how many KV pages (decode slots) a chip
    has left."""
    dense, per_expert = _weight_bytes_split(cfg, dtype, quantize, bits)
    resident = dense
    if cfg.is_moe:
        resident += cfg.num_experts * per_expert
    if not cfg.tie_embeddings:
        # The input table; the LM head copy is already in dense. Stays
        # int8 under quantization (models/quant.py).
        table = cfg.vocab_size * cfg.hidden_size
        resident += table * (1.0 if quantize else _bytes_per_el(dtype))
    return resident


def kv_bytes_per_token(cfg: ModelConfig, kv_dtype: str) -> float:
    """KV bytes one cached token occupies across all layers (K + V)."""
    return (2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim
            * _bytes_per_el(kv_dtype))


def kv_pool_bytes_split(cfg: ModelConfig, num_pages: int, page_size: int,
                        kv_dtype: str) -> tuple[float, float]:
    """(value_bytes, scale_bytes) the preallocated paged KV pool occupies
    in HBM. Mirrors kv_cache.init_paged_kv's allocation exactly (a test
    pins the two byte-for-byte): int8 pools carry a bf16 scale per
    (k|v, head, token slot) alongside the int8 values; wider dtypes have
    no scale plane. Pure model/geometry arithmetic — memlint's capacity
    ledger calls this without importing jax."""
    slots = 2.0 * cfg.num_layers * num_pages * page_size  # k + v planes
    if kv_dtype == "int8":
        return (slots * cfg.num_kv_heads * cfg.head_dim * 1.0,
                slots * cfg.num_kv_heads * 2.0)
    return (slots * cfg.num_kv_heads * cfg.head_dim
            * _bytes_per_el(kv_dtype), 0.0)


def kv_pool_bytes_spec(cfg: ModelConfig, num_pages: int, page_size: int,
                       kv_dtype: str) -> float:
    """Total paged-pool bytes (values + int8 scale planes)."""
    values, scales = kv_pool_bytes_split(cfg, num_pages, page_size, kv_dtype)
    return values + scales


def decode_flops_per_token(cfg: ModelConfig, ctx: float) -> float:
    """MatMul FLOPs to decode one token at context length ctx."""
    attn_scores = 4.0 * cfg.num_layers * ctx * cfg.num_heads * cfg.head_dim
    return 2.0 * cfg.num_active_params() + attn_scores


def prefill_flops(cfg: ModelConfig, prompt_len: int) -> float:
    """MatMul FLOPs to prefill a prompt (causal attention ~ P^2/2)."""
    attn = 2.0 * cfg.num_layers * prompt_len**2 * cfg.num_heads * cfg.head_dim
    return prompt_len * 2.0 * cfg.num_active_params() + attn


def grade(model: str, dtype: str, quantize: bool, quantize_bits: int,
          kv_dtype: str, tok_s: float, avg_lanes: Optional[float],
          avg_ctx: float, p50_ttft_ms: Optional[float] = None,
          prompt_len: Optional[int] = None,
          chip: Optional[ChipSpec] = None,
          draft_model: Optional[str] = None,
          n_chips: int = 1, assumed_lanes: float = 1.0,
          kv_pool_bytes: Optional[float] = None) -> dict:
    """Physics scorecard for one measured phase.

    Always emits the per-token geometry (bytes_per_token, flops_per_token
    at the measured occupancy/context); emits mbu/mfu/prefill_mfu_at_ttft
    only when a chip roofline applies (None on CPU). avg_lanes is the
    measured mean live decode lanes per dispatched block (loop trace);
    pass None when unmeasured — the scorecard then assumes full occupancy
    of `assumed_lanes` and SAYS so (avg_lanes_source), rather than
    silently grading against an occupancy never observed. draft_model
    adds the speculative draft's weight stream. n_chips scales the
    roofline denominator for tp/ep/dp phases."""
    cfg = get_config(model)
    kv_dt = kv_dtype or dtype
    measured = avg_lanes is not None
    lanes = max(avg_lanes, 1.0) if measured else max(assumed_lanes, 1.0)

    w_bytes = weight_read_bytes(cfg, dtype, quantize, quantize_bits, lanes)
    if draft_model:
        dcfg = get_config(draft_model)
        w_bytes += weight_read_bytes(
            dcfg, dtype, quantize, quantize_bits, lanes)
    kv_tok = kv_bytes_per_token(cfg, kv_dt)
    bytes_per_token = w_bytes / lanes + avg_ctx * kv_tok
    flops_per_token = decode_flops_per_token(cfg, avg_ctx)

    out = {
        "bytes_per_token": round(bytes_per_token),
        "flops_per_token": round(flops_per_token),
        "weight_read_bytes": round(w_bytes),
        "kv_bytes_per_cached_token": round(kv_tok),
        "avg_lanes": round(lanes, 2),
        "avg_lanes_source": "measured" if measured else "assumed_full",
        "avg_ctx": round(avg_ctx, 1),
        "chip": chip.name if chip else None,
        "n_chips": n_chips,
        "mbu": None,
        "mfu": None,
    }
    if draft_model:
        out["draft_model"] = draft_model
    if chip is not None:
        # Capacity headroom: what fraction of this chip set's HBM the
        # resident weights (draft included) consume — the complement is
        # the KV-page budget that caps decode slots.
        resident = weight_resident_bytes(cfg, dtype, quantize, quantize_bits)
        if draft_model:
            resident += weight_resident_bytes(
                get_config(draft_model), dtype, quantize, quantize_bits)
        out["hbm_weight_fraction"] = round(
            resident / (n_chips * chip.hbm_bytes), 4)
        if kv_pool_bytes is not None:
            # Full capacity statement (memlint's ML001 ledger): weights
            # PLUS the preallocated paged KV pool and its int8 scale
            # planes. hbm_weight_fraction keeps its weights-only meaning
            # so committed artifacts and BENCH replay parsing stay valid;
            # the extended accounting lands as new sibling keys.
            out["hbm_kv_pool_bytes"] = round(kv_pool_bytes)
            out["hbm_resident_fraction"] = round(
                (resident + kv_pool_bytes) / (n_chips * chip.hbm_bytes), 4)
    if chip is not None and tok_s > 0:
        hbm_bw = n_chips * chip.hbm_bytes_per_s
        peak = n_chips * chip.peak_bf16_flops
        achieved_bw = tok_s * bytes_per_token
        out["mbu"] = round(achieved_bw / hbm_bw, 4)
        # MFU against the precision actually multiplying: int8 weights
        # use the 2x int8 MXU path only when activations are int8 too —
        # ours stay bf16, so bf16 peak is the honest denominator.
        out["mfu"] = round(tok_s * flops_per_token / peak, 4)
        # Decode-side roofline ceiling: tokens/s if HBM were saturated.
        out["roofline_tok_s"] = round(hbm_bw / bytes_per_token, 1)
        if p50_ttft_ms and prompt_len:
            pf = prefill_flops(cfg, prompt_len)
            out["prefill_mfu_at_ttft"] = round(
                pf / (p50_ttft_ms / 1e3) / peak, 4)
    return out
