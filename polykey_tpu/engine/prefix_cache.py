"""Automatic prefix caching: KV pages shared across requests.

Requests that share a prompt prefix (system prompts, few-shot headers,
multi-turn histories) recompute identical KV today. This cache maps
page-aligned prompt prefixes to resident pages in the pool, so a new
request reuses the cached pages and prefills only its unmatched suffix —
TTFT for an N-token prompt with an M-token cached prefix drops to the
cost of N-M tokens.

Correctness rests on three facts:
- KV at a position depends only on the token prefix up to it (causal
  attention, absolute RoPE), so equal page-aligned prefixes ⇒ equal page
  contents; the rolling hash keys on the full prefix, not the page alone.
- Shared pages are read-only for every consumer: a slot's own writes
  start at its first unmatched position, which is strictly beyond the
  matched pages (lookup never matches the full prompt — at least one
  token always prefills), and the engine's garbage-lane writes land on
  the reserved page 0 or at a slot's own frontier.
- Lifetime is refcounts (engine/kv_cache.BlockAllocator, the C++
  native/block_allocator.cc): the cache holds one reference per cached
  page, each using slot holds its own; eviction (LRU) drops the cache's
  reference and the page frees when the last slot releases it.

The reference has no analog (stateless mock — SURVEY.md §2); this is the
standard production-serving feature (vLLM-style automatic prefix
caching) built on this framework's own page/refcount machinery.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict

import numpy as np

from .kv_cache import BlockAllocator


def _page_keys(ids: np.ndarray, page_size: int, n_pages: int) -> list[bytes]:
    """Rolling page-granular prefix keys: key_i commits to ALL tokens in
    pages 0..i, so a page is only ever shared between prompts whose entire
    prefix up to it matches."""
    keys = []
    key = b""
    for i in range(n_pages):
        chunk = ids[i * page_size:(i + 1) * page_size].tobytes()
        key = hashlib.blake2b(key + chunk, digest_size=16).digest()
        keys.append(key)
    return keys


class PrefixCache:
    """LRU map of page-aligned prompt-prefix hashes → pool page ids."""

    def __init__(
        self, allocator: BlockAllocator, page_size: int, capacity_pages: int
    ):
        self._alloc = allocator
        self._page_size = page_size
        self._capacity = max(0, capacity_pages)
        self._map: OrderedDict[bytes, int] = OrderedDict()
        self.hit_tokens = 0
        self.lookup_tokens = 0

    def __len__(self) -> int:
        return len(self._map)

    def lookup(self, ids: np.ndarray) -> list[int]:
        """Longest cached page-aligned proper prefix of `ids`; RETAINS each
        matched page on behalf of the caller (the caller owns releasing
        them like any other slot page). Never matches the whole prompt —
        at least one token must prefill to produce the sampling hidden."""
        n_full = max(0, (len(ids) - 1) // self._page_size)
        pages: list[int] = []
        for key in _page_keys(ids, self._page_size, n_full):
            page = self._map.get(key)
            if page is None:
                break
            self._map.move_to_end(key)
            self._alloc.retain(page)
            pages.append(page)
        self.lookup_tokens += len(ids)
        self.hit_tokens += len(pages) * self._page_size
        return pages

    def probe(self, ids: np.ndarray) -> int:
        """How many leading tokens of `ids` are covered by cached pages —
        a read-only warmth signal for replica routing. Unlike lookup()
        this retains nothing, refreshes no LRU position, and charges no
        hit/lookup accounting: a router probing every replica must not
        perturb the caches it is comparing."""
        n_full = max(0, (len(ids) - 1) // self._page_size)
        matched = 0
        for key in _page_keys(ids, self._page_size, n_full):
            if key not in self._map:
                break
            matched += 1
        return matched * self._page_size

    def insert(self, ids: np.ndarray, table_pages: list[int]) -> None:
        """Register a fully-prefilled prompt's page-aligned pages
        (table_pages[i] holds positions [i·ps, (i+1)·ps)). The cache
        retains each newly-inserted page; known keys just refresh LRU."""
        n_full = min(
            max(0, (len(ids) - 1) // self._page_size), len(table_pages)
        )
        for i, key in enumerate(_page_keys(ids, self._page_size, n_full)):
            if key in self._map:
                self._map.move_to_end(key)
                continue
            if self._capacity and len(self._map) >= self._capacity:
                self._evict_one()
            self._alloc.retain(table_pages[i])
            self._map[key] = table_pages[i]

    def _evict_one(self) -> bool:
        if not self._map:
            return False
        _, page = self._map.popitem(last=False)      # LRU
        self._alloc.release(page)
        return True

    def evict_for(self, pages_needed: int) -> int:
        """Allocation-pressure eviction: drop LRU entries until the free
        list could satisfy `pages_needed` (or the cache is empty). A
        released page only frees if no slot still references it, so this
        loops rather than computing a count."""
        evicted = 0
        while self._alloc.num_free < pages_needed and self._evict_one():
            evicted += 1
        return evicted

    def clear(self) -> None:
        while self._evict_one():
            pass

    def stats(self) -> dict:
        return {
            "prefix_cache_pages": len(self._map),
            "prefix_hit_tokens": self.hit_tokens,
            "prefix_lookup_tokens": self.lookup_tokens,
        }
