"""Automatic prefix caching: KV pages shared across requests, tiered
across device HBM and host RAM (ISSUE 15).

Requests that share a prompt prefix (system prompts, few-shot headers,
multi-turn histories) recompute identical KV today. This cache maps
page-aligned prompt prefixes to resident pages, so a new request reuses
the cached pages and prefills only its unmatched suffix — TTFT for an
N-token prompt with an M-token cached prefix drops to the cost of N-M
tokens.

Since ISSUE 15 an entry lives in one of two tiers:

- ``TIER_DEVICE`` — a refcounted page in the device pool
  (engine/kv_cache.BlockAllocator), exactly the pre-tier behavior;
- ``TIER_HOST`` — a page in the host RAM pool (kv_cache.HostKVPool),
  where cold entries land when the engine spills them under device
  pressure. A lookup that reaches a host entry reports it as a PAGE
  FAULT the engine resolves by allocating a device page, scattering the
  host contents back (``_jit_kv_restore``), and promoting the entry.

Without a host pool no entry is ever host-tier and every method
degenerates to the single-tier behavior byte-for-byte.

Correctness rests on three facts:
- KV at a position depends only on the token prefix up to it (causal
  attention, absolute RoPE), so equal page-aligned prefixes ⇒ equal page
  contents; the rolling hash keys on the full prefix, not the page alone.
- Shared pages are read-only for every consumer: a slot's own writes
  start at its first unmatched position, which is strictly beyond the
  matched pages (lookup never matches the full prompt — at least one
  token always prefills), and the engine's garbage-lane writes land on
  the reserved page 0 or at a slot's own frontier. Read-only content is
  also what makes the host copy coherent: a spilled page's bytes can
  never be stale.
- Lifetime is refcounts for device pages (the cache holds one reference
  per cached page, each using slot holds its own) and single ownership
  for host pages (only the cache points at them).

`PrefixStateStore` below makes the host tier RESTART-DURABLE: spill
batches are also serialized to a state directory in the PR 13 KV wire
format (kv_cache.serialize_kv_state — CRC-framed raw array bytes) plus
a JSON sidecar of page keys, and a fresh engine reloads matching files
into its host tier at construction — the supervisor-restart warm-TTFT
story (ROADMAP item 3).
"""

from __future__ import annotations

import hashlib
import json
import os
from collections import OrderedDict
from typing import Optional

import numpy as np

from .kv_cache import (
    BlockAllocator,
    HostKVPool,
    KVHandoffState,
    KVWireError,
    deserialize_kv_state,
    serialize_kv_state,
)

TIER_DEVICE = "device"
TIER_HOST = "host"


def _page_keys(ids: np.ndarray, page_size: int, n_pages: int) -> list[bytes]:
    """Rolling page-granular prefix keys: key_i commits to ALL tokens in
    pages 0..i, so a page is only ever shared between prompts whose entire
    prefix up to it matches."""
    keys = []
    key = b""
    for i in range(n_pages):
        chunk = ids[i * page_size:(i + 1) * page_size].tobytes()
        key = hashlib.blake2b(key + chunk, digest_size=16).digest()
        keys.append(key)
    return keys


class PrefixCache:
    """LRU map of page-aligned prompt-prefix hashes → (tier, page id)."""

    def __init__(
        self, allocator: BlockAllocator, page_size: int, capacity_pages: int,
        host_pool: Optional[HostKVPool] = None,
    ):
        self._alloc = allocator
        self._page_size = page_size
        self._capacity = max(0, capacity_pages)
        # value = [page_id, tier] — mutated in place on spill/promote so
        # the entry keeps its LRU position across tier moves.
        self._map: OrderedDict[bytes, list] = OrderedDict()
        self._host = host_pool
        self.hit_tokens = 0
        self.lookup_tokens = 0
        self.host_hit_tokens = 0

    def __len__(self) -> int:
        return len(self._map)

    def device_entries(self) -> int:
        return sum(e[1] == TIER_DEVICE for e in self._map.values())

    def host_entries(self) -> int:
        return sum(e[1] == TIER_HOST for e in self._map.values())

    def lookup(self, ids: np.ndarray) -> list[int]:
        """Longest cached DEVICE-resident page-aligned proper prefix of
        `ids`; RETAINS each matched page on behalf of the caller (the
        caller owns releasing them like any other slot page). Never
        matches the whole prompt — at least one token must prefill to
        produce the sampling hidden. Stops at the first host-tier entry
        (host-aware callers use lookup_chain and restore)."""
        matched, _ = self.lookup_chain(ids, include_host=False)
        return [page for _, _, page in matched]

    def lookup_chain(
        self, ids: np.ndarray, include_host: bool = True,
    ) -> tuple[list, list]:
        """The tier-aware lookup: walk the rolling key chain and return
        ``(matched, faults)`` where `matched` is the ordered chain
        ``[(key, tier, page)]`` (device pages RETAINED for the caller;
        host pages still cache-owned) and `faults` lists the indices
        into `matched` that are host-tier — the page faults the engine
        must restore (and then `promote`) before the suffix prefills.
        With ``include_host=False`` the walk stops at the first host
        entry instead (classic single-tier semantics)."""
        n_full = max(0, (len(ids) - 1) // self._page_size)
        matched: list = []
        faults: list[int] = []
        for key in _page_keys(ids, self._page_size, n_full):
            entry = self._map.get(key)
            if entry is None:
                break
            if entry[1] == TIER_HOST and not include_host:
                break
            self._map.move_to_end(key)
            if entry[1] == TIER_DEVICE:
                self._alloc.retain(entry[0])
            else:
                faults.append(len(matched))
            matched.append((key, entry[1], entry[0]))
        self.lookup_tokens += len(ids)
        self.hit_tokens += (len(matched) - len(faults)) * self._page_size
        self.host_hit_tokens += len(faults) * self._page_size
        return matched, faults

    def release_chain(self, matched: list) -> None:
        """Undo lookup_chain's device retains (restore-alloc failure
        path): the caller could not use the match after all."""
        for _, tier, page in matched:
            if tier == TIER_DEVICE:
                self._alloc.release(page)

    def probe_tiered(self, ids: np.ndarray) -> tuple[int, int]:
        """(device_tokens, host_tokens) of `ids` covered by cached
        pages — the tier-aware warmth signal for routing. Read-only:
        retains nothing, refreshes no LRU position, charges no hit
        accounting — a router probing every replica must not perturb
        the caches it is comparing. Host-resident tokens are warm (no
        recompute) but not free (a restore scatter stands between them
        and a dispatch), which is why routers weight them below
        device-resident ones (engine.prefix_warmth)."""
        n_full = max(0, (len(ids) - 1) // self._page_size)
        dev = host = 0
        for key in _page_keys(ids, self._page_size, n_full):
            entry = self._map.get(key)
            if entry is None:
                break
            if entry[1] == TIER_DEVICE:
                dev += self._page_size
            else:
                host += self._page_size
        return dev, host

    def probe(self, ids: np.ndarray) -> int:
        """Total covered tokens regardless of tier (legacy signal)."""
        dev, host = self.probe_tiered(ids)
        return dev + host

    def insert(self, ids: np.ndarray, table_pages: list[int]) -> None:
        """Register a fully-prefilled prompt's page-aligned pages
        (table_pages[i] holds positions [i·ps, (i+1)·ps)). The cache
        retains each newly-inserted page; known keys just refresh LRU.
        Re-inserting over a HOST entry promotes it back to device for
        free — the prompt just recomputed (or restored) those pages, so
        the host copy is redundant."""
        n_full = min(
            max(0, (len(ids) - 1) // self._page_size), len(table_pages)
        )
        for i, key in enumerate(_page_keys(ids, self._page_size, n_full)):
            entry = self._map.get(key)
            if entry is not None:
                if entry[1] == TIER_HOST:
                    self._free_host(entry[0])
                    self._alloc.retain(table_pages[i])
                    entry[0], entry[1] = table_pages[i], TIER_DEVICE
                self._map.move_to_end(key)
                continue
            if self._capacity and len(self._map) >= self._capacity:
                self._evict_one()
            self._alloc.retain(table_pages[i])
            self._map[key] = [table_pages[i], TIER_DEVICE]

    # -- tier moves (engine-driven) ------------------------------------------

    def spill_candidates(self, max_n: int) -> list[tuple[bytes, int]]:
        """Up to `max_n` LRU device-tier entries as (key, device_page)
        — what the engine gathers to host. Read-only; the engine calls
        mark_host/drop per entry once the copy (or the decision not to)
        is done."""
        out = []
        for key, entry in self._map.items():
            if entry[1] == TIER_DEVICE:
                out.append((key, entry[0]))
                if len(out) >= max_n:
                    break
        return out

    def mark_host(self, key: bytes, host_page: int) -> None:
        """Entry's contents now live in the host pool: release the
        cache's device reference and point the entry at the host page.
        LRU position is preserved — spilling is a tier move, not a use."""
        entry = self._map[key]
        assert entry[1] == TIER_DEVICE
        self._alloc.release(entry[0])
        entry[0], entry[1] = host_page, TIER_HOST

    def detach_host(self, key: bytes) -> int:
        """Transfer a HOST entry's page to the caller: the entry leaves
        the map and the caller now owns (and must eventually release or
        re-adopt) the host page. The engine detaches at admission so a
        faulting slot's pending restore can never read a page the
        cache's own LRU pressure freed or reused underneath it."""
        entry = self._map.pop(key)
        assert entry[1] == TIER_HOST
        return entry[0]

    def reinsert_device(self, key: bytes, device_page: int) -> bool:
        """Re-register a restored prefix under its (slot-owned) device
        page — the promote half of detach_host, called after the
        restore scatter issued. The cache takes its own reference; a
        key re-inserted meanwhile (another request recomputed the same
        prefix) wins and this returns False."""
        if key in self._map:
            return False
        if self._capacity and len(self._map) >= self._capacity:
            self._evict_one()
        self._alloc.retain(device_page)
        self._map[key] = [device_page, TIER_DEVICE]
        return True

    def drop(self, key: bytes) -> None:
        """Remove one entry outright (host pool full, durability off —
        the cold page is simply forgotten)."""
        entry = self._map.pop(key)
        if entry[1] == TIER_DEVICE:
            self._alloc.release(entry[0])
        else:
            self._free_host(entry[0])

    def pop_lru_host(self) -> Optional[tuple[bytes, int]]:
        """Drop the least-recently-used HOST entry and return (key,
        host_page) with the page already freed — the host tier's own
        LRU pressure valve."""
        for key, entry in self._map.items():
            if entry[1] == TIER_HOST:
                del self._map[key]
                self._free_host(entry[0])
                return key, entry[0]
        return None

    def adopt_host(self, key: bytes, host_page: int,
                   coldest: bool = False) -> bool:
        """Register a caller-owned host page as a host-tier entry.
        Returns False (caller keeps the page) when the key is already
        cached. `coldest=True` parks it at the LRU end — right for
        construction-time durable reloads (nothing has asked for them
        yet); the engine's re-adopt paths (requeued or dead faulting
        slots) keep the default WARM position, since their session is
        about to retry and LRU pressure must not sacrifice exactly the
        pages that retry needs."""
        if key in self._map:
            return False
        if self._capacity and len(self._map) >= self._capacity:
            self._evict_one()
        self._map[key] = [host_page, TIER_HOST]
        if coldest:
            self._map.move_to_end(key, last=False)
        return True

    def _free_host(self, page: int) -> None:
        if self._host is not None:
            self._host.release(page)

    def _evict_one(self) -> bool:
        if not self._map:
            return False
        key = next(iter(self._map))
        self.drop(key)                               # LRU
        return True

    def evict_for(self, pages_needed: int) -> int:
        """Allocation-pressure eviction: drop LRU DEVICE-tier entries
        until the free list could satisfy `pages_needed` (or none
        remain). A released page only frees if no slot still references
        it, so this loops rather than computing a count. Host-tier
        entries are never touched — dropping one frees no device page,
        so an unsatisfiable demand would otherwise wipe the whole warm
        host tier for nothing. (Without a host pool no host entries
        exist and this is the classic pre-tier behavior.)"""
        evicted = 0
        while self._alloc.num_free < pages_needed:
            key = next(
                (k for k, e in self._map.items() if e[1] == TIER_DEVICE),
                None,
            )
            if key is None:
                break
            self.drop(key)
            evicted += 1
        return evicted

    def clear(self) -> None:
        while self._evict_one():
            pass

    def stats(self) -> dict:
        host = self.host_entries()
        return {
            "prefix_cache_pages": len(self._map) - host,
            "prefix_host_pages": host,
            "prefix_hit_tokens": self.hit_tokens,
            "prefix_host_hit_tokens": self.host_hit_tokens,
            "prefix_lookup_tokens": self.lookup_tokens,
        }


# -- restart-durable spill store (ISSUE 15) -----------------------------------


class PrefixStateStore:
    """Write-through persistence for spilled prefix pages.

    Every spill batch becomes two files in the state dir:

    - ``prefix-<seq>-<pid>.pkkv`` — the page contents as ONE PR 13 wire
      blob (kv_cache.serialize_kv_state): k/v (+ks/vs) restricted to
      the batch's pages, CRC-framed, raw bytes — the same format (and
      the same corruption guarantees) the disagg handoff ships;
    - ``prefix-<seq>-<pid>.keys.json`` — the rolling prefix keys (hex)
      for each page, plus a ``params_key`` fingerprint of everything
      that determines KV content (model, weights source, dtypes). A
      reload under different weights must not resurrect another
      model's KV as warm prefix state.

    Reload (`load_into`) scans the dir oldest-first, CRC-validates each
    blob (`deserialize_kv_state` raises KVWireError on truncation or a
    flipped bit — the file is skipped and deleted, warmth lost, never
    liveness), geometry-checks it against the live pool, and adopts the
    pages into the HOST tier. Files are garbage-collected down to the
    host tier's page capacity so the dir cannot grow without bound."""

    def __init__(self, state_dir: str, model: str, page_size: int,
                 params_key: str, quantized: bool, logger=None):
        import uuid

        self.dir = state_dir
        self.model = model
        self.page_size = page_size
        self.params_key = params_key
        self.quantized = quantized
        self.logger = logger
        self._seq = 0
        # Per-incarnation stem suffix: supervisor restarts build a new
        # store in the SAME process with _seq back at 0 — pid+seq alone
        # would clobber the previous incarnation's batches, destroying
        # exactly the durable state a second crash needs.
        self._run_id = uuid.uuid4().hex[:8]
        os.makedirs(state_dir, exist_ok=True)

    def _warn(self, msg: str, **fields) -> None:
        if self.logger is not None:
            self.logger.warn(msg, **fields)

    def save_batch(self, keys: list[bytes], k: np.ndarray, v: np.ndarray,
                   ks: Optional[np.ndarray], vs: Optional[np.ndarray]) -> None:
        """Persist one spill batch (arrays are [L, n, ps, Hk, D] slices
        of the eviction gather, page-parallel with `keys`). Best-effort:
        a full disk costs durability, never serving."""
        state = KVHandoffState(
            model=self.model, page_size=self.page_size,
            prompt_len=len(keys) * self.page_size, first_token=0, seed=0,
            prompt_ids=np.zeros((0,), np.int32),
            k=k, v=v, ks=ks, vs=vs,
        )
        self._seq += 1
        stem = os.path.join(
            self.dir,
            f"prefix-{self._seq:06d}-{os.getpid()}-{self._run_id}",
        )
        try:
            blob = serialize_kv_state(state)
            with open(stem + ".pkkv.tmp", "wb") as f:
                f.write(blob)
            with open(stem + ".keys.json.tmp", "w") as f:
                json.dump({
                    "keys": [key.hex() for key in keys],
                    "params_key": self.params_key,
                    "quantized": self.quantized,
                }, f)
            # Keys last and atomically: a blob without its sidecar is
            # invisible to reload; a sidecar without its blob is skipped.
            os.replace(stem + ".pkkv.tmp", stem + ".pkkv")
            os.replace(stem + ".keys.json.tmp", stem + ".keys.json")
        except OSError as e:
            self._warn("prefix state write failed", error=str(e))

    def _batches(self) -> list[str]:
        """Sidecar stems, oldest first (mtime)."""
        try:
            names = [n for n in os.listdir(self.dir)
                     if n.endswith(".keys.json")]
        except OSError:
            return []
        stems = [os.path.join(self.dir, n[:-len(".keys.json")])
                 for n in names]
        return sorted(
            stems, key=lambda s: os.path.getmtime(s + ".keys.json")
            if os.path.exists(s + ".keys.json") else 0.0
        )

    def _discard(self, stem: str) -> None:
        for suffix in (".pkkv", ".keys.json"):
            try:
                os.remove(stem + suffix)
            except OSError:
                pass

    def gc(self, max_pages: int) -> None:
        """Drop oldest batches beyond ~max_pages persisted pages (the
        host tier could never hold more anyway)."""
        total = 0
        for stem in reversed(self._batches()):        # newest first
            try:
                with open(stem + ".keys.json") as f:
                    n = len(json.load(f).get("keys", []))
            except (OSError, ValueError):
                self._discard(stem)
                continue
            if total + n > max_pages:
                self._discard(stem)
                continue
            total += n

    def load_into(self, cache: PrefixCache, host: HostKVPool,
                  expect_shape: tuple) -> int:
        """Adopt persisted pages into the host tier (newest batches
        first — they carry the most recently warm sessions). Returns
        pages adopted. Every rejection path is a clean skip: wrong
        params_key, CRC/truncation (KVWireError), geometry mismatch,
        or a full host pool."""
        adopted = 0
        for stem in reversed(self._batches()):
            try:
                with open(stem + ".keys.json") as f:
                    side = json.load(f)
            except (OSError, ValueError) as e:
                self._warn("prefix state sidecar unreadable; discarding",
                           file=stem, error=str(e))
                self._discard(stem)
                continue
            if side.get("params_key") != self.params_key or \
                    bool(side.get("quantized")) != self.quantized:
                # Different weights/dtype produced this KV: not ours.
                continue
            try:
                with open(stem + ".pkkv", "rb") as f:
                    state = deserialize_kv_state(f.read())
            except (OSError, KVWireError) as e:
                self._warn("prefix state blob rejected; discarding",
                           file=stem, error=str(e))
                self._discard(stem)
                continue
            keys = [bytes.fromhex(k) for k in side.get("keys", [])]
            if (state.model != self.model
                    or state.page_size != self.page_size
                    or state.k.shape[0] != expect_shape[0]
                    or tuple(state.k.shape[2:]) != tuple(expect_shape[2:])
                    or state.num_pages != len(keys)):
                self._warn("prefix state geometry mismatch; discarding",
                           file=stem)
                self._discard(stem)
                continue
            for i, key in enumerate(keys):
                try:
                    page = host.alloc()
                except Exception:
                    return adopted                    # host tier full
                host.write(
                    page, state.k[:, i], state.v[:, i],
                    state.ks[:, i] if state.ks is not None else None,
                    state.vs[:, i] if state.vs is not None else None,
                )
                if cache.adopt_host(key, page, coldest=True):
                    adopted += 1
                else:
                    host.release(page)                # already cached
        return adopted
