"""Serving engine: scheduler, paged KV, sampling, streaming, speculation.

The TPU-native replacement for the reference's mock backend — the hot loop
that SURVEY.md §3.2 says mounts at the Service seam: requests enqueue into a
continuous-batching scheduler, and the decode step loop runs on-device.
"""
