"""Paged KV cache: device-side page pools + host-side block allocator.

The north star's core memory structure (no reference analog — the reference
is stateless; SURVEY.md §2b "Paged KV cache"): KV for all sequences lives in
fixed-size pages inside one preallocated pool per layer, so sequences grow
without reallocation or fragmentation, and the decode batch is composed by
page-table indirection rather than copying.

Layout (per K and V):  [num_layers, num_pages, page_size, num_kv_heads,
head_dim]. The trailing (page_size·num_kv_heads, head_dim) footprint of one
page is contiguous in HBM — what the Pallas decode kernel DMAs per grid step.

The allocator is host-side bookkeeping: the C++ implementation
(native/block_allocator.cc, loaded via ctypes) with a pure-Python fallback of
identical semantics. Page 0 is reserved as the garbage page — inactive decode
slots point at it so masked lanes always have a safe write target.
"""

from __future__ import annotations

import ctypes
import json
import os
import struct as _struct
import zlib
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..models.config import ModelConfig

_NATIVE_PATHS = (
    os.path.join(os.path.dirname(__file__), "..", "..", "build",
                 "libblock_allocator.so"),
    "build/libblock_allocator.so",
)


def _load_native() -> Optional[ctypes.CDLL]:
    for path in _NATIVE_PATHS:
        if os.path.exists(path):
            lib = ctypes.CDLL(os.path.abspath(path))
            lib.pk_allocator_new.restype = ctypes.c_void_p
            lib.pk_allocator_new.argtypes = [ctypes.c_int32]
            lib.pk_allocator_free.argtypes = [ctypes.c_void_p]
            lib.pk_num_free.restype = ctypes.c_int32
            lib.pk_num_free.argtypes = [ctypes.c_void_p]
            lib.pk_alloc.restype = ctypes.c_int32
            lib.pk_alloc.argtypes = [
                ctypes.c_void_p, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
            ]
            lib.pk_retain.restype = ctypes.c_int32
            lib.pk_retain.argtypes = [ctypes.c_void_p, ctypes.c_int32]
            lib.pk_release.restype = ctypes.c_int32
            lib.pk_release.argtypes = [ctypes.c_void_p, ctypes.c_int32]
            return lib
    return None


class AllocationError(RuntimeError):
    """Not enough free pages for the request (admission should back off)."""


class BlockAllocator:
    """Refcounted free-list page allocator (native-backed when built)."""

    def __init__(self, num_pages: int, prefer_native: bool = True):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        self._lib = _load_native() if prefer_native else None
        if self._lib is not None:
            self._handle = self._lib.pk_allocator_new(num_pages)
        else:
            self._free = list(range(num_pages - 1, 0, -1))
            self._refcount = [0] * num_pages
            self._refcount[0] = 1
        self.is_native = self._lib is not None

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None:
            lib.pk_allocator_free(self._handle)
            self._lib = None

    @property
    def num_free(self) -> int:
        if self._lib is not None:
            return self._lib.pk_num_free(self._handle)
        return len(self._free)

    def alloc(self, count: int) -> list[int]:
        """Allocate `count` pages; all-or-nothing."""
        if count == 0:
            return []
        if self._lib is not None:
            out = (ctypes.c_int32 * count)()
            if not self._lib.pk_alloc(self._handle, count, out):
                raise AllocationError(
                    f"requested {count} pages, {self.num_free} free"
                )
            return list(out)
        if len(self._free) < count:
            raise AllocationError(
                f"requested {count} pages, {len(self._free)} free"
            )
        pages = [self._free.pop() for _ in range(count)]
        for p in pages:
            self._refcount[p] = 1
        return pages

    def retain(self, page: int) -> None:
        if self._lib is not None:
            if self._lib.pk_retain(self._handle, page) < 0:
                raise ValueError(f"retain of unallocated page {page}")
            return
        if page <= 0 or page >= self.num_pages or self._refcount[page] == 0:
            raise ValueError(f"retain of unallocated page {page}")
        self._refcount[page] += 1

    def release(self, page: int) -> None:
        if self._lib is not None:
            if self._lib.pk_release(self._handle, page) < 0:
                raise ValueError(f"release of unallocated page {page}")
            return
        if page <= 0 or page >= self.num_pages or self._refcount[page] == 0:
            raise ValueError(f"release of unallocated page {page}")
        self._refcount[page] -= 1
        if self._refcount[page] == 0:
            self._free.append(page)

    def release_all(self, pages: list[int]) -> None:
        for p in pages:
            self.release(p)


@struct.dataclass
class PagedKV:
    """Device-side page pools: k/v [L, num_pages, page_size, Hk, D].

    With int8 KV (EngineConfig.kv_dtype="int8") k/v hold int8 values and
    ks/vs hold per-(token, head) bf16 scales [L, num_pages, page_size, Hk]
    — symmetric absmax over the head_dim axis, quantized at write time
    (ops/paged_attention.paged_write) and dequantized at read time. The
    scale overhead is 1/(2·D) of the bf16 pool (~0.4% at D=128); the pool
    itself halves, which is the slot-count lever on a 16 GiB chip.
    ks/vs are None for fp pools (an empty pytree subtree — the fp paths
    never see extra buffers)."""

    k: jax.Array
    v: jax.Array
    ks: Optional[jax.Array] = None
    vs: Optional[jax.Array] = None

    @property
    def page_size(self) -> int:
        return self.k.shape[2]

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def quantized(self) -> bool:
        return self.ks is not None


def init_paged_kv(
    cfg: ModelConfig, num_pages: int, page_size: int, dtype=jnp.bfloat16,
    kv_dtype=None,
) -> PagedKV:
    """`kv_dtype=jnp.int8` builds quantized pools (+ bf16 scale pools);
    None keeps the full-precision layout in `dtype`."""
    shape = (cfg.num_layers, num_pages, page_size, cfg.num_kv_heads, cfg.head_dim)
    if kv_dtype is not None and jnp.dtype(kv_dtype) == jnp.int8:
        sshape = shape[:-1]
        return PagedKV(
            k=jnp.zeros(shape, jnp.int8), v=jnp.zeros(shape, jnp.int8),
            ks=jnp.zeros(sshape, jnp.bfloat16),
            vs=jnp.zeros(sshape, jnp.bfloat16),
        )
    return PagedKV(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def kv_pool_bytes(
    cfg: ModelConfig, num_pages: int, page_size: int, dtype=jnp.bfloat16,
    kv_dtype=None,
) -> int:
    if kv_dtype is not None and jnp.dtype(kv_dtype) == jnp.int8:
        per_slot = cfg.num_kv_heads * (cfg.head_dim * 1 + 2)  # values + scale
    else:
        per_slot = cfg.num_kv_heads * cfg.head_dim * jnp.dtype(dtype).itemsize
    return 2 * cfg.num_layers * num_pages * page_size * per_slot


def host_kv_page_bytes(
    cfg: ModelConfig, page_size: int, dtype=jnp.bfloat16, kv_dtype=None,
) -> int:
    """Bytes ONE page occupies in the host tier (K + V across all layers,
    plus the bf16 scale rows for int8 pools) — the unit
    POLYKEY_HOST_KV_BYTES divides into a page capacity."""
    return kv_pool_bytes(cfg, 1, page_size, dtype, kv_dtype)


class HostKVPool:
    """Second KV tier in host RAM (ISSUE 15): preallocated numpy pools
    mirroring the device layout per page — k/v [L, capacity, page_size,
    Hk, D] (+ ks/vs scale pools [L, capacity, page_size, Hk] for int8)
    — holding COLD pages spilled from the device pool by the prefix
    cache. Pages here are never computed against: they exist to be
    scattered back into the device pool (`engine._jit_kv_restore`) when
    a prefix-cache lookup hits a spilled entry, so max cold capacity
    bounds on host RAM instead of HBM.

    Preallocation is deliberate: one contiguous buffer per pool at
    construction (the CPU analog of pinned host memory — on TPU hosts
    these become the staging buffers DMA engines copy from), no
    allocation on the spill/restore paths, and the capacity check is
    one free-list pop. Single-owner: only the engine thread touches it.
    """

    def __init__(self, cfg: ModelConfig, capacity_pages: int,
                 page_size: int, dtype, quantized: bool):
        if capacity_pages < 1:
            raise ValueError("HostKVPool needs capacity_pages >= 1")
        self.capacity = capacity_pages
        shape = (cfg.num_layers, capacity_pages, page_size,
                 cfg.num_kv_heads, cfg.head_dim)
        if quantized:
            self.k = np.zeros(shape, np.int8)
            self.v = np.zeros(shape, np.int8)
            self.ks = np.zeros(shape[:-1], jnp.dtype(jnp.bfloat16))
            self.vs = np.zeros(shape[:-1], jnp.dtype(jnp.bfloat16))
        else:
            self.k = np.zeros(shape, jnp.dtype(dtype))
            self.v = np.zeros(shape, jnp.dtype(dtype))
            self.ks = None
            self.vs = None
        self._free = list(range(capacity_pages - 1, -1, -1))

    @property
    def quantized(self) -> bool:
        return self.ks is not None

    @property
    def used(self) -> int:
        return self.capacity - len(self._free)

    @property
    def num_free(self) -> int:
        return len(self._free)

    def alloc(self) -> int:
        """One host page; AllocationError when the tier is full — the
        caller's LRU pressure policy decides what to drop."""
        if not self._free:
            raise AllocationError(
                f"host KV tier full ({self.capacity} pages)"
            )
        return self._free.pop()

    def release(self, page: int) -> None:
        if page < 0 or page >= self.capacity:
            raise ValueError(f"release of invalid host page {page}")
        self._free.append(page)

    def write(self, page: int, k: np.ndarray, v: np.ndarray,
              ks: Optional[np.ndarray] = None,
              vs: Optional[np.ndarray] = None) -> None:
        """Copy one page's contents ([L, page_size, Hk, D] slices of a
        gather result) into the host buffers — raw bytes, no dtype
        conversion, so a later restore is bit-identical."""
        self.k[:, page] = k
        self.v[:, page] = v
        if self.quantized:
            self.ks[:, page] = ks
            self.vs[:, page] = vs

    def read(self, page: int) -> tuple:
        """(k, v, ks, vs) views of one host page (restore operands are
        built by copying these into the padded upload buffer)."""
        if self.quantized:
            return (self.k[:, page], self.v[:, page],
                    self.ks[:, page], self.vs[:, page])
        return self.k[:, page], self.v[:, page], None, None


# -- KV handoff wire format (ISSUE 13) ----------------------------------------
# A prefill-tier worker ships a finished prompt's KV state to a
# decode-tier worker as one self-describing byte blob: gathered page
# contents (k/v, plus the int8 pair-form scale pools when quantized),
# the block-table ordering (implicit: pages ship in table order and the
# target re-maps them to its own page ids), and the prefix/prompt
# metadata the target needs to resume decode bit-identically (prompt
# ids, first sampled token, RNG seed). Everything is raw array bytes —
# no dtype conversion anywhere — so fp32 and int8 pools round-trip
# bit-identically; bf16 rides ml_dtypes through numpy unchanged.
#
# Layout:  MAGIC(4) | version u16 | header_len u32 | header JSON |
#          payload bytes | crc32(payload) u32
# The header's `arrays` table records each array's dtype/shape/offset
# within the payload. A truncated blob fails the length check (or the
# trailing CRC) and raises KVWireError — a typed, recoverable rejection
# the coordinator turns into a clean re-route instead of a corrupted
# target pool.

KV_WIRE_MAGIC = b"PKKV"
KV_WIRE_VERSION = 1


class KVWireError(RuntimeError):
    """The handoff blob cannot be (safely) applied: bad magic/version,
    geometry mismatch against the target pool, or a truncated/corrupt
    payload. Always raised BEFORE any target-pool write, so a rejected
    handoff never leaves partial state behind."""


@dataclass
class KVHandoffState:
    """One request's prefill-complete KV state, host-side.

    Arrays use the pool layout with the page axis restricted to this
    request's pages in block-table order: k/v are
    [L, n_pages, page_size, Hk, D]; ks/vs (int8 pools only) are
    [L, n_pages, page_size, Hk]. `prompt_ids` is the tokenized (and
    possibly tail-truncated) prompt — positions 0..prompt_len-1 are the
    ones the pages hold KV for. `first_token` was sampled at position
    key prompt_len with `seed`, exactly as a single-process prefill
    would; the target resumes decode at seq_len = prompt_len + 1."""

    model: str
    page_size: int
    prompt_len: int
    first_token: int
    seed: int
    prompt_ids: np.ndarray
    k: np.ndarray
    v: np.ndarray
    ks: Optional[np.ndarray] = None
    vs: Optional[np.ndarray] = None

    @property
    def num_pages(self) -> int:
        return int(self.k.shape[1])

    @property
    def quantized(self) -> bool:
        return self.ks is not None

    def validate_for(self, cfg: ModelConfig, page_size: int,
                     quantized: bool) -> None:
        """Raise KVWireError unless this state fits the target pool's
        geometry exactly — the guard that keeps a mismatched handoff a
        typed rejection instead of silent pool corruption."""
        expect = (cfg.num_layers, self.num_pages, page_size,
                  cfg.num_kv_heads, cfg.head_dim)
        if self.model != cfg.name:
            raise KVWireError(
                f"kv-handoff model mismatch: blob for {self.model!r}, "
                f"target serves {cfg.name!r}"
            )
        if self.page_size != page_size:
            raise KVWireError(
                f"kv-handoff page_size mismatch: blob {self.page_size}, "
                f"target pool {page_size}"
            )
        if tuple(self.k.shape) != expect or tuple(self.v.shape) != expect:
            raise KVWireError(
                f"kv-handoff geometry mismatch: pages {self.k.shape} vs "
                f"target {expect}"
            )
        if quantized != self.quantized:
            raise KVWireError(
                "kv-handoff dtype mismatch: blob is "
                f"{'int8' if self.quantized else 'full-precision'}, target "
                f"pool is {'int8' if quantized else 'full-precision'}"
            )
        needed = -(-self.prompt_len // page_size)
        if self.num_pages != needed:
            raise KVWireError(
                f"kv-handoff page count {self.num_pages} does not cover "
                f"prompt_len {self.prompt_len} (need {needed})"
            )


def _array_entries(state: KVHandoffState) -> list[tuple[str, np.ndarray]]:
    entries = [
        ("prompt_ids", np.ascontiguousarray(state.prompt_ids, np.int32)),
        ("k", np.ascontiguousarray(state.k)),
        ("v", np.ascontiguousarray(state.v)),
    ]
    if state.ks is not None:
        entries.append(("ks", np.ascontiguousarray(state.ks)))
        entries.append(("vs", np.ascontiguousarray(state.vs)))
    return entries


def serialize_kv_state(state: KVHandoffState) -> bytes:
    """Render a KVHandoffState as one wire blob (see module comment)."""
    entries = _array_entries(state)
    arrays = []
    payload_parts = []
    offset = 0
    for name, arr in entries:
        raw = arr.tobytes()
        arrays.append({
            "name": name,
            # jnp.dtype resolves ml_dtypes names (bfloat16) that plain
            # numpy's dtype constructor does not.
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
            "offset": offset,
            "nbytes": len(raw),
        })
        payload_parts.append(raw)
        offset += len(raw)
    payload = b"".join(payload_parts)
    header = json.dumps({
        "model": state.model,
        "page_size": state.page_size,
        "prompt_len": state.prompt_len,
        "first_token": int(state.first_token),
        "seed": int(state.seed),
        "quantized": state.quantized,
        "arrays": arrays,
        "payload_bytes": len(payload),
    }).encode()
    return b"".join([
        KV_WIRE_MAGIC,
        _struct.pack("!HI", KV_WIRE_VERSION, len(header)),
        header,
        payload,
        _struct.pack("!I", zlib.crc32(payload) & 0xFFFFFFFF),
    ])


def _parse_header(buf: bytes) -> tuple[dict, int]:
    """(header dict, payload start offset); raises KVWireError on a blob
    too short or malformed to even carry a header."""
    head = len(KV_WIRE_MAGIC) + 6
    if len(buf) < head:
        raise KVWireError(
            f"kv-handoff blob truncated: {len(buf)} bytes is shorter than "
            "the fixed header"
        )
    if buf[:4] != KV_WIRE_MAGIC:
        raise KVWireError(
            f"kv-handoff bad magic {buf[:4]!r} (expected {KV_WIRE_MAGIC!r})"
        )
    version, header_len = _struct.unpack("!HI", buf[4:head])
    if version != KV_WIRE_VERSION:
        raise KVWireError(
            f"kv-handoff version {version} unsupported (this build speaks "
            f"{KV_WIRE_VERSION})"
        )
    if len(buf) < head + header_len:
        raise KVWireError("kv-handoff blob truncated inside the header")
    try:
        header = json.loads(buf[head:head + header_len])
    except ValueError as e:
        raise KVWireError(f"kv-handoff header unparsable: {e}") from e
    return header, head + header_len


def validate_kv_blob(buf: bytes) -> dict:
    """Light structural validation (header + framing + CRC) WITHOUT
    materializing arrays — what the coordinator runs on a fetched blob
    before paying a ship to the decode tier. Returns the header dict;
    raises KVWireError on any truncation/corruption."""
    header, start = _parse_header(buf)
    payload_bytes = int(header.get("payload_bytes", -1))
    expected = start + payload_bytes + 4
    if payload_bytes < 0 or len(buf) < expected:
        raise KVWireError(
            f"kv-handoff blob truncated: have {len(buf)} bytes, framing "
            f"declares {expected} (partial write?)"
        )
    payload = buf[start:start + payload_bytes]
    (crc,) = _struct.unpack(
        "!I", buf[start + payload_bytes:start + payload_bytes + 4]
    )
    if crc != (zlib.crc32(payload) & 0xFFFFFFFF):
        raise KVWireError("kv-handoff payload CRC mismatch (corrupt blob)")
    return header


def deserialize_kv_state(buf: bytes) -> KVHandoffState:
    """Parse a wire blob back into a KVHandoffState, bit-identically
    (raw-byte round-trip, no dtype conversion). Raises KVWireError on
    bad magic/version, truncation, or CRC mismatch — never applies a
    partial blob."""
    header = validate_kv_blob(buf)
    _, start = _parse_header(buf)
    payload = buf[start:start + int(header["payload_bytes"])]
    arrays: dict[str, np.ndarray] = {}
    for entry in header["arrays"]:
        raw = payload[entry["offset"]:entry["offset"] + entry["nbytes"]]
        if len(raw) != entry["nbytes"]:
            raise KVWireError(
                f"kv-handoff array {entry['name']!r} truncated"
            )
        arr = np.frombuffer(
            raw, dtype=jnp.dtype(entry["dtype"])
        ).reshape(entry["shape"])
        arrays[entry["name"]] = arr
    for required in ("prompt_ids", "k", "v"):
        if required not in arrays:
            raise KVWireError(f"kv-handoff blob missing array {required!r}")
    # The header's `quantized` flag must agree with the arrays actually
    # shipped — a mismatch means the serializer and this reader disagree
    # about the pool form, and applying the blob would mix int8 values
    # with a full-precision target (racelint CL005 pins this field as
    # read-back on both sides).
    if bool(header.get("quantized")) != ("ks" in arrays):
        raise KVWireError(
            "kv-handoff header/payload mismatch: quantized="
            f"{bool(header.get('quantized'))} but scale pools are "
            f"{'present' if 'ks' in arrays else 'absent'}"
        )
    return KVHandoffState(
        model=header["model"],
        page_size=int(header["page_size"]),
        prompt_len=int(header["prompt_len"]),
        first_token=int(header["first_token"]),
        seed=int(header["seed"]),
        prompt_ids=arrays["prompt_ids"],
        k=arrays["k"],
        v=arrays["v"],
        ks=arrays.get("ks"),
        vs=arrays.get("vs"),
    )
