"""Paged KV cache: device-side page pools + host-side block allocator.

The north star's core memory structure (no reference analog — the reference
is stateless; SURVEY.md §2b "Paged KV cache"): KV for all sequences lives in
fixed-size pages inside one preallocated pool per layer, so sequences grow
without reallocation or fragmentation, and the decode batch is composed by
page-table indirection rather than copying.

Layout (per K and V):  [num_layers, num_pages, page_size, num_kv_heads,
head_dim]. The trailing (page_size·num_kv_heads, head_dim) footprint of one
page is contiguous in HBM — what the Pallas decode kernel DMAs per grid step.

The allocator is host-side bookkeeping: the C++ implementation
(native/block_allocator.cc, loaded via ctypes) with a pure-Python fallback of
identical semantics. Page 0 is reserved as the garbage page — inactive decode
slots point at it so masked lanes always have a safe write target.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from ..models.config import ModelConfig

_NATIVE_PATHS = (
    os.path.join(os.path.dirname(__file__), "..", "..", "build",
                 "libblock_allocator.so"),
    "build/libblock_allocator.so",
)


def _load_native() -> Optional[ctypes.CDLL]:
    for path in _NATIVE_PATHS:
        if os.path.exists(path):
            lib = ctypes.CDLL(os.path.abspath(path))
            lib.pk_allocator_new.restype = ctypes.c_void_p
            lib.pk_allocator_new.argtypes = [ctypes.c_int32]
            lib.pk_allocator_free.argtypes = [ctypes.c_void_p]
            lib.pk_num_free.restype = ctypes.c_int32
            lib.pk_num_free.argtypes = [ctypes.c_void_p]
            lib.pk_alloc.restype = ctypes.c_int32
            lib.pk_alloc.argtypes = [
                ctypes.c_void_p, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
            ]
            lib.pk_retain.restype = ctypes.c_int32
            lib.pk_retain.argtypes = [ctypes.c_void_p, ctypes.c_int32]
            lib.pk_release.restype = ctypes.c_int32
            lib.pk_release.argtypes = [ctypes.c_void_p, ctypes.c_int32]
            return lib
    return None


class AllocationError(RuntimeError):
    """Not enough free pages for the request (admission should back off)."""


class BlockAllocator:
    """Refcounted free-list page allocator (native-backed when built)."""

    def __init__(self, num_pages: int, prefer_native: bool = True):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        self._lib = _load_native() if prefer_native else None
        if self._lib is not None:
            self._handle = self._lib.pk_allocator_new(num_pages)
        else:
            self._free = list(range(num_pages - 1, 0, -1))
            self._refcount = [0] * num_pages
            self._refcount[0] = 1
        self.is_native = self._lib is not None

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None:
            lib.pk_allocator_free(self._handle)
            self._lib = None

    @property
    def num_free(self) -> int:
        if self._lib is not None:
            return self._lib.pk_num_free(self._handle)
        return len(self._free)

    def alloc(self, count: int) -> list[int]:
        """Allocate `count` pages; all-or-nothing."""
        if count == 0:
            return []
        if self._lib is not None:
            out = (ctypes.c_int32 * count)()
            if not self._lib.pk_alloc(self._handle, count, out):
                raise AllocationError(
                    f"requested {count} pages, {self.num_free} free"
                )
            return list(out)
        if len(self._free) < count:
            raise AllocationError(
                f"requested {count} pages, {len(self._free)} free"
            )
        pages = [self._free.pop() for _ in range(count)]
        for p in pages:
            self._refcount[p] = 1
        return pages

    def retain(self, page: int) -> None:
        if self._lib is not None:
            if self._lib.pk_retain(self._handle, page) < 0:
                raise ValueError(f"retain of unallocated page {page}")
            return
        if page <= 0 or page >= self.num_pages or self._refcount[page] == 0:
            raise ValueError(f"retain of unallocated page {page}")
        self._refcount[page] += 1

    def release(self, page: int) -> None:
        if self._lib is not None:
            if self._lib.pk_release(self._handle, page) < 0:
                raise ValueError(f"release of unallocated page {page}")
            return
        if page <= 0 or page >= self.num_pages or self._refcount[page] == 0:
            raise ValueError(f"release of unallocated page {page}")
        self._refcount[page] -= 1
        if self._refcount[page] == 0:
            self._free.append(page)

    def release_all(self, pages: list[int]) -> None:
        for p in pages:
            self.release(p)


@struct.dataclass
class PagedKV:
    """Device-side page pools: k/v [L, num_pages, page_size, Hk, D].

    With int8 KV (EngineConfig.kv_dtype="int8") k/v hold int8 values and
    ks/vs hold per-(token, head) bf16 scales [L, num_pages, page_size, Hk]
    — symmetric absmax over the head_dim axis, quantized at write time
    (ops/paged_attention.paged_write) and dequantized at read time. The
    scale overhead is 1/(2·D) of the bf16 pool (~0.4% at D=128); the pool
    itself halves, which is the slot-count lever on a 16 GiB chip.
    ks/vs are None for fp pools (an empty pytree subtree — the fp paths
    never see extra buffers)."""

    k: jax.Array
    v: jax.Array
    ks: Optional[jax.Array] = None
    vs: Optional[jax.Array] = None

    @property
    def page_size(self) -> int:
        return self.k.shape[2]

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def quantized(self) -> bool:
        return self.ks is not None


def init_paged_kv(
    cfg: ModelConfig, num_pages: int, page_size: int, dtype=jnp.bfloat16,
    kv_dtype=None,
) -> PagedKV:
    """`kv_dtype=jnp.int8` builds quantized pools (+ bf16 scale pools);
    None keeps the full-precision layout in `dtype`."""
    shape = (cfg.num_layers, num_pages, page_size, cfg.num_kv_heads, cfg.head_dim)
    if kv_dtype is not None and jnp.dtype(kv_dtype) == jnp.int8:
        sshape = shape[:-1]
        return PagedKV(
            k=jnp.zeros(shape, jnp.int8), v=jnp.zeros(shape, jnp.int8),
            ks=jnp.zeros(sshape, jnp.bfloat16),
            vs=jnp.zeros(sshape, jnp.bfloat16),
        )
    return PagedKV(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def kv_pool_bytes(
    cfg: ModelConfig, num_pages: int, page_size: int, dtype=jnp.bfloat16,
    kv_dtype=None,
) -> int:
    if kv_dtype is not None and jnp.dtype(kv_dtype) == jnp.int8:
        per_slot = cfg.num_kv_heads * (cfg.head_dim * 1 + 2)  # values + scale
    else:
        per_slot = cfg.num_kv_heads * cfg.head_dim * jnp.dtype(dtype).itemsize
    return 2 * cfg.num_layers * num_pages * page_size * per_slot
