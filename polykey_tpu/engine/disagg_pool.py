"""Disaggregated prefill/decode tiers: cross-process workers with
crash-safe KV handoff (ISSUE 13, ROADMAP item 2 stages (b)/(c)).

PR 7's replica pool scaled the engine INSIDE one process: a prefill
burst still steals decode device time, and a process death still takes
every replica down. This coordinator takes the same contracts across
process boundaries:

- **Tiers.** ``POLYKEY_DISAGG="PxD"`` runs P prefill-tier and D
  decode-tier worker processes (engine/worker.py) on localhost, each an
  independently supervised engine behind a socket control plane. Prefill
  never shares a process with decode, so tier capacity scales
  independently and a prefill burst cannot inflate decode ITL.
- **KV handoff.** A finished prefill ships as one versioned wire blob
  (kv_cache.serialize_kv_state: pages + block-table order + prompt/seed
  metadata, raw bytes — fp32 and int8 pair-form pools round-trip
  bit-identically). The hand-over is two-phase: the prefill worker
  RETAINS the serialized state until the coordinator releases it after
  decode completes, so a decode-side death re-ships the same blob
  instead of re-running prefill.
- **NetKV routing** (PAPERS.md): the decode worker is chosen by
  estimated KV-transfer cost (blob bytes over a measured per-worker
  bandwidth EWMA) plus the queue-delay EWMA its heartbeat reports —
  route to where the transfer is cheap AND the queue is short. Prefill
  routing is session-sticky: multi-turn prompts hash to a session key
  (first page-aligned token window) and return to the worker holding
  their warm prefix; a restarted worker re-advertises its persisted
  prefix index, so stickiness survives worker death.
- **Crash safety.** Worker death at ANY phase — queued, mid-prefill,
  mid-handoff, mid-decode — re-routes through the PR 7 resume machinery:
  the orchestration replays from the earliest surviving artifact (the
  retained blob if the prefill side still holds it, a fresh prefill
  otherwise) with the delivered token prefix suppressed, bounded by
  ``max_reroutes``. Greedy streams stay bit-identical to a
  single-process run (same params/seed/positions; the decode worker
  replays and the coordinator drops what the client already holds).
  Heartbeat liveness (+ process exit) feeds the PR 7 replica state
  machine: NEW → SERVING → DRAINING → RESTARTING → DEAD, with aggregate
  health flipping only when a TIER loses its last serving worker.

``POLYKEY_DISAGG`` unset builds no processes and no pool — every
single-process path is untouched. The pool quacks like an engine where
the gateway needs it to (config/tokenizer/submit/stats/dead/shutdown),
exactly like ReplicaPool.
"""

from __future__ import annotations

import json
import os
import queue
import subprocess
import sys
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from ..obs.clocks import ClockSync
from ..obs.histogram import Histogram, estimate_quantile
from ..obs.postmortem import BlackBox
from ..obs.signals import window_label, windows_from_spec
from ..obs.timeline import TimelineRecorder, merge_timelines, to_perfetto
from .config import EngineConfig
from .engine import EngineDeadError, EngineOverloadedError, GenRequest
from .kv_cache import KVWireError, validate_kv_blob
from .replica_pool import _ADDITIVE_KEYS  # shared aggregation contract
from .replica_pool import DEAD, DRAINING, NEW, RESTARTING, SERVING
from .tokenizer import load_tokenizer
from .worker import WorkerConn, session_key

PREFILL = "prefill"
DECODE = "decode"

# Handoff outcome labels (polykey_handoffs_total{outcome}).
_OUTCOMES = ("ok", "retried", "aborted")

# Bandwidth prior before the first measured ship (bytes/s). Localhost
# sockets measure orders of magnitude above this; the prior only has to
# make the transfer term non-zero so routing is defined on a cold pool.
_BW_PRIOR = 200e6


class _HandoffRetry(Exception):
    """One attempt failed at a recoverable phase. `restart_prefill`
    says whether the retained blob is gone/bad (re-run prefill) or
    still shippable (re-route decode only); `mark_down` distinguishes
    worker death (heartbeat will confirm; re-route now) from flow
    control like a shed (the worker is fine, just busy)."""

    def __init__(self, cause: str, phase: str, restart_prefill: bool,
                 mark_down: bool = True, flow_control: bool = False,
                 retry_after_s: float = 0.0):
        super().__init__(cause)
        self.phase = phase
        self.restart_prefill = restart_prefill
        self.mark_down = mark_down
        # Flow control (a worker SHED, not a worker death): the retry
        # waits out the worker's retry-after hint, never burns the
        # re-route budget, and never counts as a failover metric —
        # mirroring how a shed at the gateway is RESOURCE_EXHAUSTED,
        # not a failure.
        self.flow_control = flow_control
        self.retry_after_s = retry_after_s
        self.delivered = 0


@dataclass
class _Worker:
    tier: str
    index: int
    addr: Optional[tuple] = None
    proc: Optional[subprocess.Popen] = None
    spawn: Optional[Callable[[], tuple]] = None   # () -> (addr, proc)
    state: str = NEW
    misses: int = 0
    restarts: int = 0
    # Elastic scale-down (ISSUE 18): marks a worker the autopilot is
    # deliberately draining out of the pool — its eventual death is
    # the PLAN, so _on_worker_down must retire it instead of spending
    # restart budget respawning it.
    retiring: bool = False
    restart_times: list = field(default_factory=list)
    ping: dict = field(default_factory=dict)
    stats: dict = field(default_factory=dict)
    bw_ewma: float = 0.0          # measured ship bandwidth, bytes/s
    # Clock alignment (ISSUE 16): maps this worker's monotonic clock
    # onto the coordinator's; fed by the heartbeat's ping samples and
    # reset when the worker's pid changes (new process = new epoch).
    clock: ClockSync = field(default_factory=ClockSync)
    last_pid: Optional[int] = None

    @property
    def name(self) -> str:
        return f"{self.tier}/{self.index}"

    @property
    def role(self) -> str:
        """Black-box / clock-offset key: matches the worker-side
        blackbox-<tier>-<replica>.json file name."""
        return f"{self.tier}-{self.index}"


class DisaggPool:
    """Engine-shaped coordinator over the prefill and decode worker
    tiers. One orchestration thread per in-flight request drives the
    prefill → handoff → decode pipeline over the workers' control
    planes and forwards tokens into the request's out queue."""

    def __init__(self, config: EngineConfig, health=None, logger=None,
                 recorder=None):
        config.validate()
        self.config = config
        self.health = health
        self.logger = logger
        self.recorder = recorder
        self.tokenizer = load_tokenizer(config.tokenizer)
        self.workers: list[_Worker] = []
        self._lock = threading.Lock()
        self._closing = False
        self._serving_advertised = True
        self._inflight = 0
        self._heartbeat_thread: Optional[threading.Thread] = None
        self._stop_heartbeat = threading.Event()
        # Handoff observability (ISSUE 13 satellites): counters +
        # latency histogram owned HERE (the coordinator is the only
        # process that sees a handoff end to end), plus a pool-level
        # timeline ring for handoff_start/ack/abort events
        # (obs.timeline.to_perfetto renders notes on the engine-events
        # track; /debug/timeline reaches it through engine_timelines).
        self.handoffs = {outcome: 0 for outcome in _OUTCOMES}
        self.handoff_bytes = 0
        self.handoff_ms = Histogram()
        self.timeline = (
            TimelineRecorder(config.timeline_capacity)
            if config.timeline_capacity > 0 else None
        )
        self.requests_rerouted = 0
        self.streams_resumed = 0
        # Cross-tier signal windows (ISSUE 16): a bounded ring of
        # heartbeat-cadence samples of the pool's handoff counters, so
        # signals_snapshot() can answer with WINDOWED wire bandwidth,
        # handoff-latency delta-quantiles, and per-tier fault/restore
        # rates — the autopilot's read API for tier scaling, and the
        # observable counterpart of the NetKV bandwidth EWMA.
        self.tier_faults = {PREFILL: 0, DECODE: 0}
        self.tier_restores = {PREFILL: 0, DECODE: 0}
        self._signal_windows = windows_from_spec(config.signals_windows)
        interval = max(0.05, config.disagg_heartbeat_s)
        self._signal_ring: deque = deque(maxlen=min(
            8192, int(self._signal_windows[-1] / interval) + 2
        ))
        # Boot baseline: handoffs that land before the heartbeat's first
        # cadence sample must still show up as window deltas.
        self._sample_signals()
        # Coordinator black box (obs/postmortem.py): created by
        # create() when the pool has a state dir; carries the clock
        # offsets a postmortem needs to merge the workers' rings.
        self.blackbox: Optional[BlackBox] = None
        # Session stickiness (stage (c)): session key → worker index,
        # per tier. Prefill stickiness lands multi-turn users on their
        # warm prefix; decode stickiness amortizes the router's
        # transfer-cost learning per session.
        self._sticky: dict[str, dict[str, int]] = {PREFILL: {}, DECODE: {}}
        self._seed_rng = np.random.default_rng()
        self._stats_cache: dict = {}
        self._stats_cache_t = 0.0
        # Autopilot attachment point (ISSUE 18): the running controller
        # publishes itself here so /debug/slo and /metrics see it; the
        # knob setpoints it pushed are remembered so a respawned worker
        # (fresh process, config-default knobs) gets them re-applied.
        self.autopilot = None
        self._knob_setpoints: dict = {}
        # Requests currently parked in _wait_for_worker because their
        # tier has no SERVING member: token -> wait start. The age of
        # the oldest waiter is tier_now's queue-delay evidence DURING
        # an outage, when the dead tier's pings can say nothing — it
        # lets the controller scale up in parallel with the respawn
        # instead of discovering the backlog only after it.
        self._tier_waiters: dict = {PREFILL: {}, DECODE: {}}
        self._waiter_seq = 0

    # -- construction ---------------------------------------------------------

    @classmethod
    def create(
        cls,
        config: EngineConfig,
        health=None,
        logger=None,
        obs=None,
        seed: int = 0,
        workers: Optional[list] = None,
        restart_cb: Optional[Callable] = None,
        state_dir: Optional[str] = None,
        ready_timeout_s: float = 300.0,
        heartbeat: bool = True,
    ) -> "DisaggPool":
        """Build and start a wired pool.

        Default mode spawns ``config.disagg_tiers()`` worker PROCESSES
        (``python -m polykey_tpu.engine.worker``) and learns their ports
        from the readiness handshake. Tests pass ``workers`` as
        ``[(tier, (host, port)), ...]`` for pre-started in-process
        servers, plus ``restart_cb(worker) -> addr | None`` to stand in
        for process respawn."""
        tiers = config.disagg_tiers()
        if tiers is None and workers is None:
            raise ValueError("DisaggPool needs a POLYKEY_DISAGG spec or "
                             "an explicit worker list")
        recorder = obs.recorder if obs is not None else None
        pool = cls(config, health=health, logger=logger, recorder=recorder)
        pool._seed = seed
        pool._state_dir = state_dir
        pool._ready_timeout_s = ready_timeout_s
        pool._restart_cb = restart_cb
        if state_dir and config.blackbox_every > 0:
            pool.blackbox = BlackBox(
                state_dir, "coordinator",
                timeline=pool.timeline, recorder=recorder,
                every=config.blackbox_every,
                meta={"tier": "coordinator"},
            )
        if workers is not None:
            counts: dict[str, int] = {}
            for tier, addr in workers:
                index = counts.get(tier, 0)
                counts[tier] = index + 1
                pool.workers.append(_Worker(
                    tier=tier, index=index, addr=tuple(addr), state=SERVING,
                ))
        else:
            n_prefill, n_decode = tiers
            for tier, count in ((PREFILL, n_prefill), (DECODE, n_decode)):
                for i in range(count):
                    worker = _Worker(tier=tier, index=i)
                    worker.spawn = pool._spawner(worker)
                    pool.workers.append(worker)
            # Spawn concurrently: each worker pays jax import + engine
            # build + warmup before its readiness line, and the spawns
            # are independent — serial boot would cost N × that wall.
            spawn_errors: list = []

            def _boot(worker: _Worker) -> None:
                try:
                    worker.addr, worker.proc = worker.spawn()
                    worker.state = SERVING
                except Exception as e:
                    spawn_errors.append((worker.name, e))

            boot_threads = [
                threading.Thread(target=_boot, args=(w,), daemon=True)
                for w in pool.workers
            ]
            for thread in boot_threads:
                thread.start()
            for thread in boot_threads:
                thread.join(timeout=ready_timeout_s + 10)
            if spawn_errors:
                pool.shutdown()
                name, error = spawn_errors[0]
                raise RuntimeError(
                    f"disagg worker {name} failed to start: {error}"
                )
        # Seed stickiness from the workers' persisted prefix indexes
        # (warm rejoin: a restarted tier comes back knowing its users).
        for worker in pool.workers:
            pool._absorb_warm_sessions(worker)
        if heartbeat:
            pool._heartbeat_thread = threading.Thread(
                target=pool._heartbeat_loop, name="polykey-disagg-heartbeat",
                daemon=True,
            )
            pool._heartbeat_thread.start()
        if recorder is not None:
            recorder.event(
                "disagg_pool_started",
                prefill=sum(w.tier == PREFILL for w in pool.workers),
                decode=sum(w.tier == DECODE for w in pool.workers),
            )
        if logger is not None:
            logger.info(
                "disagg pool started",
                prefill=sum(w.tier == PREFILL for w in pool.workers),
                decode=sum(w.tier == DECODE for w in pool.workers),
                model=config.model,
            )
        return pool

    def _spawner(self, worker: _Worker) -> Callable[[], tuple]:
        """Process factory for one tier slot: spawn, wait for the
        readiness handshake, return (addr, proc)."""

        def spawn() -> tuple:
            env = dict(os.environ)
            # Ship THIS pool's config: workers rebuild EngineConfig from
            # env, and a programmatically-constructed pool (soaks,
            # tests) would otherwise spawn default-geometry engines —
            # breaking bit-identity with the coordinator's reference.
            env.update(_config_env(self.config))
            env["POLYKEY_DISAGG"] = ""          # workers never recurse
            env["POLYKEY_REPLICAS"] = "1"
            # Workers never run their own control loop: the
            # coordinator's autopilot actuates them via the knobs op,
            # and two controllers fighting over one knob diverge.
            env["POLYKEY_AUTOPILOT"] = "0"
            env["POLYKEY_METRICS_PORT"] = "0"   # no port clash with the
            # gateway's exposition sidecar
            repo_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            ))
            env["PYTHONPATH"] = (
                repo_root + os.pathsep + env.get("PYTHONPATH", "")
            ).rstrip(os.pathsep)
            cmd = [
                sys.executable, "-m", "polykey_tpu.engine.worker",
                "--tier", worker.tier, "--replica", str(worker.index),
                "--port", "0", "--seed", str(self._seed),
            ]
            stderr = subprocess.DEVNULL
            if self._state_dir:
                cmd += ["--state-dir", self._state_dir]
                os.makedirs(self._state_dir, exist_ok=True)
                stderr = open(os.path.join(
                    self._state_dir, f"worker-{worker.name.replace('/', '-')}.log"
                ), "ab")
            proc = subprocess.Popen(
                cmd, cwd=repo_root, env=env, stdout=subprocess.PIPE,
                stderr=stderr, start_new_session=True,
            )
            line_q: queue.Queue = queue.Queue()
            threading.Thread(
                target=lambda: line_q.put(proc.stdout.readline()),
                daemon=True,
            ).start()
            try:
                line = line_q.get(timeout=self._ready_timeout_s)
                ready = json.loads(line)
                assert ready.get("ready")
            except Exception:
                proc.kill()
                raise RuntimeError(
                    f"worker {worker.name} never became ready "
                    f"(within {self._ready_timeout_s}s)"
                ) from None
            if self.logger is not None:
                self.logger.info("disagg worker ready", worker=worker.name,
                                 port=ready["port"], pid=ready.get("pid"))
            return ("127.0.0.1", int(ready["port"])), proc

        return spawn

    def _absorb_warm_sessions(self, worker: _Worker) -> None:
        """Fold the worker's advertised warm-session keys into the
        sticky map (first claim wins — a session already stuck
        elsewhere stays there)."""
        try:
            with WorkerConn(worker.addr, timeout=5.0) as conn:
                t_send = time.monotonic()
                reply, _ = conn.request({"op": "ping"}, timeout=5.0)
                t_recv = time.monotonic()
        except (OSError, ConnectionError, ValueError):
            return
        worker.ping = reply
        self._sync_clock(worker, reply, t_send, t_recv)
        sticky = self._sticky[worker.tier]
        with self._lock:
            for key in reply.get("warm_sessions", ()):
                sticky.setdefault(key, worker.index)

    # -- state machine / liveness --------------------------------------------

    def _transition(self, worker: _Worker, state: str,
                    only_from: Optional[tuple] = None) -> None:
        flip_down = flip_up = False
        with self._lock:
            if worker.state == state or worker.state == DEAD:
                return
            if only_from is not None and worker.state not in only_from:
                return
            previous = worker.state
            worker.state = state
            serving = self._tiers_serving_locked()
            if self._serving_advertised and not serving:
                self._serving_advertised = False
                flip_down = True
            elif not self._serving_advertised and serving:
                self._serving_advertised = True
                flip_up = True
        if self.timeline is not None:
            self.timeline.note(
                "worker_state", worker=worker.name, state=state,
                previous=previous,
            )
        if self.recorder is not None:
            self.recorder.event(
                "disagg_worker_state", worker=worker.name, state=state,
                previous=previous,
            )
        if self.logger is not None:
            self.logger.info("disagg worker state change",
                             worker=worker.name, state=state,
                             previous=previous)
        if self.health is not None and not self._closing:
            # Aggregate health flips on the "every tier has >= 1
            # SERVING worker" boundary — one worker's death is the
            # pool's problem, a whole tier's death is the balancer's.
            if flip_down:
                self.health.shutdown()
            elif flip_up:
                self.health.resume_serving()

    def _tiers_serving_locked(self) -> bool:
        return all(
            any(w.tier == tier and w.state == SERVING for w in self.workers)
            for tier in (PREFILL, DECODE)
        )

    def _on_worker_down(self, worker: _Worker, cause: str) -> None:
        if worker.retiring:
            # A draining scale-down target dying IS the plan (or close
            # enough): retire it instead of burning restart budget
            # respawning capacity the controller just decided to shed.
            self._transition(worker, DEAD)
            self._remove_worker(worker)
            return
        self._transition(worker, DRAINING, only_from=(NEW, SERVING))
        with self._lock:
            if worker.state != DRAINING:
                return
            self.tier_faults[worker.tier] = (
                self.tier_faults.get(worker.tier, 0) + 1
            )
            now = time.monotonic()
            worker.restart_times = [
                t for t in worker.restart_times
                if now - t < self.config.restart_window_s
            ]
            budget_left = (
                len(worker.restart_times) < self.config.max_engine_restarts
            )
            can_restart = (
                worker.spawn is not None or self._restart_cb is not None
            )
            if budget_left and can_restart and not self._closing:
                worker.state = RESTARTING
                worker.restart_times.append(now)
            else:
                worker.state = DEAD
        if worker.state == DEAD:
            self._transition(worker, DEAD)   # re-aggregate health + log
            return
        if self.logger is not None:
            self.logger.warn("disagg worker down; restarting",
                             worker=worker.name, cause=cause)
        threading.Thread(
            target=self._restart_worker, args=(worker,), daemon=True,
        ).start()

    def _restart_worker(self, worker: _Worker) -> None:
        if worker.proc is not None:
            try:
                worker.proc.kill()
            except OSError:
                pass
        if self._closing:
            self._transition(worker, DEAD)
            return
        try:
            if worker.spawn is not None:
                worker.addr, worker.proc = worker.spawn()
            else:
                addr = self._restart_cb(worker)
                if addr is None:
                    self._transition(worker, DEAD)
                    return
                worker.addr = tuple(addr)
        except Exception as e:
            if self.logger is not None:
                self.logger.error("disagg worker restart failed",
                                  worker=worker.name, error=str(e))
            self._transition(worker, DEAD)
            return
        if self._closing:
            # shutdown() raced the seconds-long spawn: its worker pass
            # already ran, so the FRESH process is ours to reap — left
            # alone it would outlive the pool with its port bound.
            if worker.proc is not None:
                try:
                    worker.proc.kill()
                except OSError:
                    pass
            self._transition(worker, DEAD)
            return
        worker.misses = 0
        worker.restarts += 1
        with self._lock:
            self.tier_restores[worker.tier] = (
                self.tier_restores.get(worker.tier, 0) + 1
            )
        self._absorb_warm_sessions(worker)   # rejoin warm (persisted index)
        self._push_knobs(worker)             # actuations outlive the respawn
        self._transition(worker, SERVING, only_from=(RESTARTING,))

    def _heartbeat_loop(self) -> None:
        interval = self.config.disagg_heartbeat_s
        while not self._stop_heartbeat.wait(interval):
            for worker in list(self.workers):
                if worker.state in (RESTARTING, DEAD) or self._closing:
                    continue
                if worker.proc is not None and worker.proc.poll() is not None:
                    self._on_worker_down(worker, "process exited")
                    continue
                try:
                    with WorkerConn(worker.addr, timeout=interval) as conn:
                        t_send = time.monotonic()
                        reply, _ = conn.request({"op": "ping"},
                                                timeout=interval)
                        t_recv = time.monotonic()
                    worker.ping = reply
                    worker.misses = 0
                    # Clock re-estimation rides every heartbeat: the
                    # drift-aged best-sample filter in ClockSync keeps
                    # the offset's uncertainty near RTT/2 forever.
                    self._sync_clock(worker, reply, t_send, t_recv)
                    if reply.get("state") == "DEAD":
                        self._transition(worker, DEAD)
                    elif reply.get("state") == "SERVING" and \
                            not worker.retiring:
                        # A retiring worker pings healthy all the way
                        # through its drain — never re-promote it.
                        self._transition(worker, SERVING,
                                         only_from=(NEW, DRAINING))
                except (OSError, ConnectionError, ValueError):
                    worker.misses += 1
                    if worker.misses >= self.config.disagg_miss:
                        self._on_worker_down(worker, "heartbeat missed")
            self._sample_signals()
            if self.blackbox is not None:
                # The coordinator's box carries the clock offsets a
                # postmortem needs to merge worker rings — refresh them
                # right before the checkpoint.
                self.blackbox.meta["clock_offsets"] = self.clock_offsets()
                self.blackbox.tick(force=True)

    def _sync_clock(self, worker: _Worker, reply: dict,
                    t_send: float, t_recv: float) -> None:
        pid = reply.get("pid")
        if pid is not None and pid != worker.last_pid:
            if worker.last_pid is not None:
                # New process, new monotonic epoch: the old offset is
                # meaningless and must not age gracefully.
                worker.clock.reset()
            worker.last_pid = pid
        mono = reply.get("mono")
        if isinstance(mono, (int, float)):
            worker.clock.update(t_send, t_recv, float(mono))

    # -- elastic capacity (autopilot actuation surface, ISSUE 18) -------------

    def tier_now(self) -> dict:
        """Instantaneous per-tier capacity + pressure: the autopilot's
        scaling evidence. queue_delay_s is the mean across the tier's
        serving workers' last heartbeat pings; during an outage, when
        the dead tier's pings can say nothing, the ages of the requests
        parked in _wait_for_worker join the mean instead. None (never
        zero) when neither exists: no evidence, no verdict."""
        out: dict = {}
        now = time.monotonic()
        with self._lock:
            members = {
                tier: [w for w in self.workers if w.tier == tier]
                for tier in (PREFILL, DECODE)
            }
            waiting = {
                tier: [now - t0 for t0 in self._tier_waiters[tier].values()]
                for tier in (PREFILL, DECODE)
            }
        for tier, workers in members.items():
            serving = [w for w in workers if w.state == SERVING]
            delays = [
                float(w.ping["queue_delay_s"])
                for w in serving
                if w.ping.get("queue_delay_s") is not None
            ]
            delays += waiting.get(tier, [])
            loads = [
                float(w.ping["load"]) for w in serving
                if w.ping.get("load") is not None
            ]
            out[tier] = {
                "serving": len(serving),
                "total": sum(w.state != DEAD for w in workers),
                "queue_delay_s": (
                    round(sum(delays) / len(delays), 4) if delays else None
                ),
                "load": (
                    round(sum(loads) / len(loads), 4) if loads else None
                ),
            }
        return out

    def scale_up(self, tier: str) -> Optional[str]:
        """Grow `tier` by one worker. The new member enters in
        RESTARTING (the heartbeat skips it until its addr exists) and
        the seconds-long spawn runs on a background thread — the
        controller tick must never block on a jax import. Returns the
        new worker's name, or None when the pool can't spawn."""
        if self._closing or not hasattr(self, "_seed"):
            return None   # test-constructed pool: no process factory
        with self._lock:
            indices = [w.index for w in self.workers if w.tier == tier]
            worker = _Worker(
                tier=tier, index=(max(indices) + 1 if indices else 0),
                state=RESTARTING,
            )
            self.workers.append(worker)
        # Closure construction only (the actual Popen + ready-wait run
        # on the _boot thread) — but it lives outside the lock so the
        # critical section provably never reaches a blocking call.
        worker.spawn = self._spawner(worker)
        if self.timeline is not None:
            self.timeline.note("tier_scale_up", tier=tier,
                               worker=worker.name)

        def _boot() -> None:
            try:
                worker.addr, worker.proc = worker.spawn()
            except Exception as e:
                if self.logger is not None:
                    self.logger.error("tier scale-up spawn failed",
                                      worker=worker.name, error=str(e))
                self._remove_worker(worker)
                return
            if self._closing:
                try:
                    worker.proc.kill()
                except OSError:
                    pass
                self._remove_worker(worker)
                return
            self._absorb_warm_sessions(worker)
            self._push_knobs(worker)
            self._transition(worker, SERVING, only_from=(RESTARTING,))

        threading.Thread(target=_boot, daemon=True).start()
        return worker.name

    def scale_down(self, tier: str) -> Optional[str]:
        """Shrink `tier` by one worker — drain before kill. The
        highest-index SERVING worker flips to DRAINING (instantly out
        of routing), then a background thread waits for its in-flight
        work to finish before the exit op + kill. Refuses (None) when
        the tier has no second serving worker to leave behind."""
        with self._lock:
            serving = sorted(
                (w for w in self.workers
                 if w.tier == tier and w.state == SERVING),
                key=lambda w: w.index,
            )
            if len(serving) < 2:
                return None
            worker = serving[-1]
            worker.retiring = True
        self._transition(worker, DRAINING, only_from=(SERVING,))
        if self.timeline is not None:
            self.timeline.note("tier_scale_down", tier=tier,
                               worker=worker.name)
        threading.Thread(
            target=self._drain_and_retire, args=(worker,), daemon=True,
        ).start()
        return worker.name

    def _drain_and_retire(self, worker: _Worker) -> None:
        deadline = time.monotonic() + max(
            5.0, 2.0 * self.config.disagg_recovery_wait_s
        )
        poll = min(0.2, self.config.disagg_heartbeat_s)
        while time.monotonic() < deadline and not self._closing:
            try:
                with WorkerConn(worker.addr, timeout=2.0) as conn:
                    reply, _ = conn.request({"op": "ping"}, timeout=2.0)
                if (reply.get("slots_busy", 0) == 0
                        and reply.get("queued", 0) == 0
                        and reply.get("retained_handoffs", 0) == 0):
                    break
            except (OSError, ConnectionError, ValueError):
                break   # already gone; retirement proceeds
            time.sleep(poll)
        try:
            with WorkerConn(worker.addr, timeout=2.0) as conn:
                conn.request({"op": "exit"}, timeout=2.0)
        except (OSError, ConnectionError, ValueError):
            pass
        if worker.proc is not None:
            try:
                worker.proc.terminate()
                worker.proc.wait(timeout=5.0)
            except (OSError, subprocess.TimeoutExpired):
                try:
                    worker.proc.kill()
                except OSError:
                    pass
        self._transition(worker, DEAD)
        self._remove_worker(worker)

    def _remove_worker(self, worker: _Worker) -> None:
        """Drop a retired/never-booted worker from the pool. Sticky
        entries pointing at the removed index are left alone: routing
        treats a sticky miss as a plain re-score (the removed-index
        safety the sticky map already guarantees)."""
        with self._lock:
            try:
                self.workers.remove(worker)
            except ValueError:
                pass

    def apply_knobs(self, knobs: dict) -> dict:
        """Broadcast live-knob setpoints to every SERVING worker (the
        autopilot's cross-process actuation path) and remember them so
        respawns and future scale-ups boot onto the same setpoints.
        Returns the last worker's post-clamp applied dict (tiers run
        identical configs, so any worker's clamp is THE clamp)."""
        with self._lock:
            # polylint: disable=ML002(keyed by knob name: 4 static engine-knob names from _ENGINE_KNOB_SETTERS, not per-request data)
            self._knob_setpoints.update(knobs)
            targets = [w for w in self.workers if w.state == SERVING]
        applied: dict = dict(knobs)
        for worker in targets:
            got = self._push_knobs(worker)
            if got:
                applied = got
        return applied

    def _push_knobs(self, worker: _Worker) -> Optional[dict]:
        with self._lock:
            knobs = dict(self._knob_setpoints)
        if not knobs or worker.addr is None:
            return None
        try:
            with WorkerConn(worker.addr, timeout=2.0) as conn:
                reply, _ = conn.request(
                    {"op": "knobs", "knobs": knobs}, timeout=2.0
                )
            return reply.get("applied") or None
        except (OSError, ConnectionError, ValueError):
            return None   # heartbeat owns liveness; a miss here is fine

    # -- engine-shaped surface ------------------------------------------------

    @property
    def dead(self) -> Optional[str]:
        if self._closing:
            return "engine is shut down"
        with self._lock:
            for tier in (PREFILL, DECODE):
                members = [w for w in self.workers if w.tier == tier]
                if members and all(w.state == DEAD for w in members):
                    return (f"all {tier}-tier workers dead "
                            "(restart budgets exhausted)")
        return None

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._inflight > 0

    def submit(self, request: GenRequest) -> None:
        """Tier-aware admission + one orchestration thread per request.
        Sheds (RESOURCE_EXHAUSTED + retry-after) when the in-flight set
        already oversubscribes the decode tier's slot capacity by the
        configured queue bound — the coordinator's O(1) mirror of the
        engine's bounded-queue discipline."""
        dead = self.dead
        if dead is not None:
            raise EngineDeadError(
                dead, retry_after_ms=int(
                    1000 * self.config.disagg_heartbeat_s * 2
                ),
            )
        limit = self.config.max_queue_depth
        if limit > 0:
            decode_slots = sum(
                self.config.max_decode_slots
                for w in self.workers if w.tier == DECODE
            )
            with self._lock:
                over = self._inflight >= decode_slots + limit
            if over:
                raise EngineOverloadedError(
                    f"disagg pool saturated ({self._inflight} in flight)",
                    retry_after_ms=100,
                )
        if request.seed is None and request.temperature > 0.0:
            # Fix the sampling root NOW: a re-routed attempt must replay
            # the same stream (the replica_pool contract).
            request.seed = int(self._seed_rng.integers(0, 1 << 63))
        with self._lock:
            self._inflight += 1
        threading.Thread(
            target=self._serve_request, args=(request,), daemon=True,
        ).start()

    def shutdown(self, timeout: float = 10.0) -> None:
        self._closing = True
        self._stop_heartbeat.set()
        if self.autopilot is not None:
            self.autopilot.stop()
        if self.blackbox is not None:
            # Final checkpoint with fresh offsets: a postmortem over a
            # cleanly-stopped pool should still merge.
            self.blackbox.meta["clock_offsets"] = self.clock_offsets()
            self.blackbox.tick(force=True)
        if self._heartbeat_thread is not None:
            self._heartbeat_thread.join(timeout=2.0)
        for worker in list(self.workers):
            if worker.addr is not None:
                try:
                    with WorkerConn(worker.addr, timeout=2.0) as conn:
                        conn.request({"op": "exit"}, timeout=2.0)
                except (OSError, ConnectionError, ValueError):
                    pass
            if worker.proc is not None:
                try:
                    worker.proc.terminate()
                    worker.proc.wait(timeout=timeout)
                except (OSError, subprocess.TimeoutExpired):
                    try:
                        worker.proc.kill()
                    except OSError:
                        pass

    # -- routing --------------------------------------------------------------

    def _serving(self, tier: str) -> list[_Worker]:
        with self._lock:
            return [
                w for w in self.workers
                if w.tier == tier and w.state == SERVING
            ]

    def _wait_for_worker(self, tier: str, skey: str,
                         payload_bytes: int = 0) -> Optional[_Worker]:
        """Pick the best SERVING worker of `tier`; when the tier is
        momentarily empty (a restart in flight), wait up to the
        recovery budget — the zero-loss contract rides on re-routes
        outlasting a supervised worker restart. Failed workers need no
        explicit exclusion: a death already moved them out of SERVING
        via the state machine."""
        deadline = time.monotonic() + self.config.disagg_recovery_wait_s
        token = None
        try:
            while True:
                candidates = self._serving(tier)
                if candidates:
                    return self._score(tier, candidates, skey,
                                       payload_bytes)
                if token is None:
                    with self._lock:
                        self._waiter_seq += 1
                        token = self._waiter_seq
                        self._tier_waiters[tier][token] = time.monotonic()
                if time.monotonic() >= deadline or self._closing:
                    return None
                time.sleep(min(0.05, self.config.disagg_heartbeat_s))
        finally:
            if token is not None:
                with self._lock:
                    self._tier_waiters[tier].pop(token, None)

    def _score(self, tier: str, candidates: list[_Worker], skey: str,
               payload_bytes: int) -> _Worker:
        """NetKV-style selection. Decode: minimize estimated transfer
        cost (bytes / measured bandwidth EWMA) + queue-delay EWMA, with
        a small session-sticky bonus. Prefill: session-sticky first
        (warm prefix beats any queue-delay difference at these scales),
        then least delay. Ties break on the lowest index —
        deterministic given equal state."""
        sticky = self._sticky[tier].get(skey)
        if tier == PREFILL and sticky is not None:
            for worker in candidates:
                if worker.index == sticky:
                    return worker
        scored = []
        for worker in candidates:
            delay = float(worker.ping.get("queue_delay_s", 0.0) or 0.0)
            load = float(worker.ping.get("load", 0.0) or 0.0)
            transfer = 0.0
            if tier == DECODE and payload_bytes:
                bw = worker.bw_ewma or _BW_PRIOR
                transfer = payload_bytes / bw
            bonus = 0.001 if sticky == worker.index else 0.0
            score = transfer + delay + 1e-3 * load - bonus
            scored.append((score, worker.index, worker))
        scored.sort(key=lambda entry: (entry[0], entry[1]))
        chosen = scored[0][2]
        with self._lock:
            self._sticky[tier][skey] = chosen.index
        return chosen

    # -- per-request orchestration --------------------------------------------

    def _serve_request(self, request: GenRequest) -> None:
        try:
            self._orchestrate(request)
        except _Terminal:
            pass   # the request already received its terminal event
        except Exception as e:  # the thread must never die silently
            request.out.put(("error", f"engine: disagg orchestration "
                                      f"crashed: {e}"))
            if self.logger is not None:
                import traceback

                self.logger.error("disagg orchestration crashed",
                                  error=str(e),
                                  traceback=traceback.format_exc())
        finally:
            with self._lock:
                self._inflight -= 1

    def _orchestrate(self, request: GenRequest) -> None:
        ids = np.asarray(self.tokenizer.encode(request.prompt), np.int32)
        skey = session_key(ids, self.config.page_size)
        handoff_id = uuid.uuid4().hex
        blob: Optional[bytes] = None
        meta: dict = {}
        source: Optional[_Worker] = None
        delivered = 0
        reroutes = 0
        # Flow-control retries (worker sheds) wait out the worker's
        # retry-after hint instead of burning the re-route budget; this
        # cap only backstops a tier that sheds for minutes on end.
        flow_retries = 0
        while True:
            if request.cancelled.is_set():
                request.out.put(("error", "cancelled"))
                return
            if (request.deadline is not None
                    and time.monotonic() >= request.deadline):
                request.out.put((
                    "error", "deadline exceeded while re-routing",
                ))
                return
            t_handoff = time.monotonic()
            try:
                if blob is None:
                    prefill_worker = self._wait_for_worker(PREFILL, skey)
                    if prefill_worker is None:
                        self._count("aborted")
                        request.out.put((
                            "error",
                            "engine: no serving prefill-tier worker",
                        ))
                        return
                    blob, meta, source = self._run_prefill(
                        prefill_worker, request, handoff_id, skey
                    )
                decode_worker = self._wait_for_worker(
                    DECODE, skey, payload_bytes=len(blob)
                )
                if decode_worker is None:
                    self._count("aborted")
                    request.out.put((
                        "error", "engine: no serving decode-tier worker",
                    ))
                    return
                delivered = self._run_decode(
                    decode_worker, request, blob, meta, delivered, source,
                    t_handoff,
                )
                self._release(source, handoff_id)
                return
            except _HandoffRetry as e:
                delivered = max(delivered, getattr(e, "delivered", delivered))
                if e.restart_prefill:
                    blob = None
                    source = None
                if e.flow_control:
                    # A shed, not a death: honor the worker's
                    # retry-after hint; no budget burn, no failover
                    # metrics (the gateway-level shed already carries
                    # the client-facing RESOURCE_EXHAUSTED contract).
                    flow_retries += 1
                    if flow_retries > 100:
                        self._count("aborted")
                        request.out.put((
                            "error",
                            f"engine: tier kept shedding ({e.phase}: {e})",
                        ))
                        return
                    time.sleep(max(0.02, e.retry_after_s))
                    continue
                reroutes += 1
                if self.timeline is not None:
                    self.timeline.note(
                        "handoff_abort", phase=e.phase, cause=str(e),
                        reroutes=reroutes, handoff_id=handoff_id,
                        trace=self._trace_id(request),
                    )
                if self.recorder is not None:
                    self.recorder.event(
                        "disagg_handoff_abort", phase=e.phase,
                        cause=str(e), reroutes=reroutes,
                    )
                if reroutes > self.config.max_reroutes:
                    self._count("aborted")
                    request.out.put((
                        "error",
                        f"engine: handoff failed after {reroutes - 1} "
                        f"re-routes ({e.phase}: {e})",
                    ))
                    return
                self._count("retried")
                with self._lock:
                    self.requests_rerouted += 1
                    if delivered > 0:
                        self.streams_resumed += 1
                if delivered > 0:
                    request.restarted = True
                if not e.mark_down:
                    time.sleep(0.05)   # link event, not death: brief pause

    def _count(self, outcome: str) -> None:
        with self._lock:
            self.handoffs[outcome] += 1

    def _release(self, source: Optional[_Worker], handoff_id: str) -> None:
        """Phase 2 of the hand-over: decode is done, the source may drop
        its retained copy. Best-effort — a dead source already lost it."""
        if source is None or source.addr is None:
            return
        try:
            with WorkerConn(source.addr, timeout=2.0) as conn:
                conn.request({"op": "release", "handoff_id": handoff_id},
                             timeout=2.0)
        except (OSError, ConnectionError, ValueError):
            pass

    def _deadline_in_s(self, request: GenRequest) -> Optional[float]:
        if request.deadline is None:
            return None
        return max(0.0, request.deadline - time.monotonic())

    @staticmethod
    def _trace_id(request: GenRequest) -> Optional[str]:
        return request.trace.trace_id if request.trace is not None else None

    def _req_dict(self, request: GenRequest) -> dict:
        return {
            "prompt": request.prompt,
            "max_new_tokens": request.max_new_tokens,
            "temperature": request.temperature,
            "top_p": request.top_p,
            "top_k": request.top_k,
            "seed": request.seed,
            "deadline_in_s": self._deadline_in_s(request),
            # Trace propagation (ISSUE 16): the gateway's x-trace-id
            # rides every control-plane op so worker-side spans and
            # timeline notes join the same distributed trace.
            "trace_id": self._trace_id(request),
        }

    def _graft_worker_trace(self, request: GenRequest, worker: _Worker,
                            wire: Optional[dict]) -> None:
        """Attach a worker's shipped span tree (absolute monotonic
        start/end on ITS clock) under the gateway root, re-timed onto
        the coordinator clock via the worker's heartbeat offset. Skipped
        when no offset has landed yet — an unaligned subtree would
        mis-order the root's children."""
        if wire is None or request.trace is None:
            return
        offset = worker.clock.offset
        if offset is None:
            return
        self._graft_node(request.trace, wire, offset, worker=worker.name)

    def _graft_node(self, parent, wire: dict, offset: float,
                    **extra) -> None:
        start = wire.get("start")
        end = wire.get("end")
        child = parent.child(
            str(wire.get("name", "span")),
            start=(start + offset
                   if isinstance(start, (int, float)) else None),
            end=(end + offset if isinstance(end, (int, float)) else None),
            **{**(wire.get("attrs") or {}), **extra},
        )
        for sub in wire.get("children") or ():
            if isinstance(sub, dict):
                self._graft_node(child, sub, offset)

    def _run_prefill(self, worker: _Worker, request: GenRequest,
                     handoff_id: str, skey: str) -> tuple:
        """Prefill + fetch: returns (blob, meta, worker). Any failure —
        socket death, worker error, corrupt blob — marks the worker and
        raises a retryable _HandoffRetry (the blob never half-applies:
        validation precedes any ship)."""
        if self.timeline is not None:
            self.timeline.note(
                "handoff_start", worker=worker.name,
                handoff_id=handoff_id, session=skey,
                trace=self._trace_id(request),
            )
        try:
            with WorkerConn(worker.addr, timeout=30.0) as conn:
                req = self._req_dict(request)
                req["handoff_id"] = handoff_id
                conn.send({"op": "prefill", "req": req})
                meta: dict = {}
                timeout = self.config.request_timeout_s
                while True:
                    event, _ = conn.recv(timeout=timeout)
                    kind = event.get("event")
                    if kind == "handoff_ready":
                        meta = event
                        request.timings.prompt_tokens = int(
                            event.get("prompt_tokens", 0)
                        )
                    elif kind == "done":
                        self._graft_worker_trace(request, worker,
                                                 event.get("trace"))
                        break
                    elif kind == "error":
                        if event.get("shed"):
                            raise _HandoffRetry(
                                event.get("message", "shed"),
                                "prefill", restart_prefill=True,
                                mark_down=False, flow_control=True,
                                retry_after_s=(
                                    event.get("retry_after_ms") or 100
                                ) / 1000.0,
                            )
                        message = event.get("message", "prefill failed")
                        if message.startswith("engine"):
                            raise _HandoffRetry(message, "prefill",
                                                restart_prefill=True)
                        # Request-outcome failure (deadline, bad input):
                        # not the worker's fault, never re-routed.
                        request.out.put(("error", message))
                        raise _Terminal()
                    else:
                        raise _HandoffRetry(
                            f"unexpected prefill event {kind!r}",
                            "prefill", restart_prefill=True,
                        )
                if not meta:
                    raise _HandoffRetry("prefill produced no handoff",
                                        "prefill", restart_prefill=True)
                t_fetch = time.monotonic()
                reply, blob = conn.request(
                    {"op": "fetch", "handoff_id": handoff_id},
                    timeout=timeout,
                )
                if request.trace is not None and reply.get("ok"):
                    # Wire hop 1 of the handoff: prefill → coordinator.
                    request.trace.child(
                        "handoff_fetch", start=t_fetch,
                        end=time.monotonic(), bytes=len(blob),
                        worker=worker.name, handoff_id=handoff_id,
                    )
                if not reply.get("ok"):
                    raise _HandoffRetry(
                        reply.get("error", "fetch failed"), "handoff",
                        restart_prefill=True,
                    )
        except _Terminal:
            raise
        except _HandoffRetry as e:
            if e.mark_down:
                self._on_worker_down(worker, "prefill attempt failed")
            raise
        except (OSError, ConnectionError, ValueError) as e:
            self._on_worker_down(worker, f"prefill/handoff failed: {e}")
            raise _HandoffRetry(str(e) or "connection lost", "handoff",
                                restart_prefill=True) from e
        try:
            validate_kv_blob(blob)
        except KVWireError as e:
            # Partial write / corrupt ship: clean re-route (re-run the
            # prefill), never a half-applied pool — the decode tier
            # never sees this blob. The worker itself stays SERVING: a
            # torn transfer is a link event, and killing the source
            # would turn one bad ship into lost tier capacity.
            raise _HandoffRetry(str(e), "handoff", restart_prefill=True,
                                mark_down=False) from e
        with self._lock:
            self.handoff_bytes += len(blob)
        return blob, meta, worker

    def _run_decode(self, worker: _Worker, request: GenRequest,
                    blob: bytes, meta: dict, delivered: int,
                    source: Optional[_Worker],
                    t_handoff: float) -> int:
        """Ship the blob, stream the decode, forward the suffix the
        client is missing. Returns the total delivered count; raises
        _HandoffRetry carrying it on a recoverable failure."""
        seen = 0
        try:
            with WorkerConn(worker.addr, timeout=30.0) as conn:
                req = self._req_dict(request)
                req["handoff_id"] = meta.get("handoff_id")
                t_ship = time.monotonic()
                conn.send({"op": "decode", "req": req}, blob)
                timeout = self.config.request_timeout_s
                event, _ = conn.recv(timeout=timeout)
                if event.get("event") != "accepted":
                    message = event.get("message", "decode rejected")
                    if event.get("shed"):
                        raise _HandoffRetry(
                            message, "decode", restart_prefill=False,
                            mark_down=False, flow_control=True,
                            retry_after_s=(
                                event.get("retry_after_ms") or 100
                            ) / 1000.0,
                        )
                    if "kv-handoff" in message:
                        # The blob itself was rejected (the engine wraps
                        # the typed marker as "admission failed:
                        # kv-handoff rejected: …"): re-run prefill —
                        # re-shipping the same bytes cannot succeed.
                        raise _HandoffRetry(message, "decode",
                                            restart_prefill=True,
                                            mark_down=False)
                    if message.startswith("engine"):
                        raise _HandoffRetry(message, "decode",
                                            restart_prefill=False)
                    request.out.put(("error", message))
                    raise _Terminal()
                t_accepted = time.monotonic()
                ship_s = max(1e-6, t_accepted - t_ship)
                measured = len(blob) / ship_s
                worker.bw_ewma = (
                    measured if worker.bw_ewma == 0.0
                    else 0.7 * worker.bw_ewma + 0.3 * measured
                )
                # Exemplar (ISSUE 16 satellite): the handoff-latency
                # bucket this observation lands in links back to the
                # request's span tree on an OpenMetrics scrape.
                self.handoff_ms.observe(
                    (t_accepted - t_handoff) * 1e3,
                    trace_id=self._trace_id(request),
                )
                if request.trace is not None:
                    # Wire hop 2: coordinator → decode worker, ending
                    # when the worker accepted (deserialize included —
                    # its split ships back in the accepted frame and the
                    # worker's own tree carries the exact child).
                    request.trace.child(
                        "handoff_ship", start=t_ship, end=t_accepted,
                        bytes=len(blob), worker=worker.name,
                        deserialize_ms=event.get("deserialize_ms"),
                    )
                if self.timeline is not None:
                    self.timeline.note(
                        "handoff_ack", worker=worker.name,
                        bytes=len(blob),
                        ship_ms=round(ship_s * 1e3, 3),
                        handoff_id=meta.get("handoff_id"),
                        trace=self._trace_id(request),
                    )
                request.replica = worker.index
                request.tier = (
                    f"prefill={source.index if source else '?'},"
                    f"decode={worker.index}"
                )
                while True:
                    event, _ = conn.recv(timeout=timeout)
                    kind = event.get("event")
                    if kind == "token":
                        seen += 1
                        if seen <= delivered:
                            continue     # client already holds it
                        delivered += 1
                        timings = request.timings
                        if timings.first_token == 0.0:
                            timings.first_token = time.monotonic()
                            if timings.prefill_start == 0.0:
                                timings.prefill_start = timings.enqueued
                        request.out.put(("token", int(event["id"])))
                        if request.cancelled.is_set():
                            request.out.put(("error", "cancelled"))
                            raise _Terminal()
                    elif kind == "done":
                        timings = request.timings
                        timings.finished = time.monotonic()
                        timings.completion_tokens = delivered
                        remote = event.get("timings") or {}
                        timings.device_ms += float(
                            remote.get("device_ms", 0.0) or 0.0
                        )
                        self._graft_worker_trace(request, worker,
                                                 event.get("trace"))
                        # Count BEFORE delivering the terminal event: a
                        # client that consumes "done" and immediately
                        # reads stats() must see this handoff as ok.
                        self._count("ok")
                        request.out.put(("done", timings))
                        return delivered
                    elif kind == "error":
                        message = event.get("message", "decode failed")
                        if "kv-handoff" in message:
                            raise _HandoffRetry(message, "decode",
                                                restart_prefill=True,
                                                mark_down=False)
                        if message.startswith("engine"):
                            raise _HandoffRetry(message, "decode",
                                                restart_prefill=False)
                        request.out.put(("error", message))
                        raise _Terminal()
                    else:
                        raise _HandoffRetry(
                            f"unexpected decode event {kind!r}", "decode",
                            restart_prefill=False,
                        )
        except (_Terminal, _HandoffRetry) as e:
            if isinstance(e, _HandoffRetry):
                e.delivered = delivered
                if e.mark_down:
                    self._on_worker_down(worker,
                                         f"decode attempt failed: {e}")
            raise
        except (OSError, ConnectionError, ValueError) as e:
            self._on_worker_down(worker, f"decode stream died: {e}")
            retry = _HandoffRetry(str(e) or "connection lost", "decode",
                                  restart_prefill=False)
            retry.delivered = delivered
            raise retry from e

    # -- stats / exposition ---------------------------------------------------

    def _worker_stats(self, worker: _Worker) -> dict:
        try:
            with WorkerConn(worker.addr, timeout=3.0) as conn:
                reply, _ = conn.request({"op": "stats"}, timeout=3.0)
            if reply.get("ok"):
                worker.stats = reply["stats"]
        except (OSError, ConnectionError, ValueError):
            pass  # keep the cached snapshot; liveness is heartbeat's job
        snap = dict(worker.stats)
        snap["tier"] = worker.tier
        snap["replica"] = worker.index
        snap["state"] = worker.state
        snap["worker_restarts"] = worker.restarts
        return snap

    def stats(self) -> dict:
        """Aggregate pool stats, replica_pool-shaped: additive engine
        counters summed across workers, per-worker snapshots under
        `per_worker`, tier/handoff extras on top. Snapshots refresh at
        most every 0.5 s so scrape storms never amplify into control-
        plane storms."""
        now = time.monotonic()
        with self._lock:
            cached = self._stats_cache if (
                self._stats_cache and now - self._stats_cache_t < 0.5
            ) else None
        if cached is not None:
            return cached
        per = [self._worker_stats(w) for w in list(self.workers)]
        agg: dict = {}
        for snap in per:
            for key, value in snap.items():
                if key in _ADDITIVE_KEYS and isinstance(value, (int, float)):
                    agg[key] = agg.get(key, 0) + value
        agg["model"] = self.config.model
        with self._lock:
            agg["workers_total"] = len(self.workers)
            agg["workers_serving"] = sum(
                w.state == SERVING for w in self.workers
            )
            agg["tier_states"] = {
                w.name: w.state for w in self.workers
            }
            agg["tiers"] = {
                tier: {
                    "total": sum(w.tier == tier for w in self.workers),
                    "serving": sum(
                        w.tier == tier and w.state == SERVING
                        for w in self.workers
                    ),
                }
                for tier in (PREFILL, DECODE)
            }
            agg["requests_rerouted"] = self.requests_rerouted
            agg["streams_resumed"] = self.streams_resumed
            agg["handoffs"] = dict(self.handoffs)
            agg["handoff_bytes"] = self.handoff_bytes
            agg["inflight_requests"] = self._inflight
        agg["handoff_ms_p50"] = round(self.handoff_ms.percentile(50), 2)
        agg["handoff_ms_p95"] = round(self.handoff_ms.percentile(95), 2)
        agg["per_worker"] = per
        agg["tier_faults"] = dict(self.tier_faults)
        agg["tier_restores"] = dict(self.tier_restores)
        agg["clock_offsets"] = self.clock_offsets()
        with self._lock:
            self._stats_cache = agg
            self._stats_cache_t = now
        return agg

    # -- cross-process flight deck (ISSUE 16) ---------------------------------

    def clock_offsets(self) -> dict:
        """Per-worker ClockSync snapshots, keyed by black-box role —
        the merge key shared by live merged_timelines() and the
        postmortem's offline merge."""
        return {w.role: w.clock.snapshot() for w in list(self.workers)}

    def handoff_now(self) -> dict:
        """Instantaneous handoff signals: the per-decode-worker ship
        bandwidth EWMA the NetKV router scores on — flightwatch's
        HANDOFF row reads this next to the windowed deltas."""
        return {
            "wire_bw_ewma_bytes_per_s": {
                w.role: round(w.bw_ewma, 1)
                for w in list(self.workers)
                if w.tier == DECODE and w.bw_ewma > 0.0
            },
        }

    def _sample_signals(self) -> None:
        """One heartbeat-cadence sample of the pool's handoff counters.
        The ring stores ABSOLUTE counters; signal_windows() diffs two
        samples into per-window deltas — same discipline as the
        engine-side SignalPlane, so quantiles are over the window, not
        since boot."""
        counts, hsum = self.handoff_ms.counts_snapshot()
        with self._lock:
            self._signal_ring.append((
                time.monotonic(), counts, hsum, self.handoff_bytes,
                dict(self.handoffs), dict(self.tier_faults),
                dict(self.tier_restores),
            ))

    def signal_windows(self) -> dict:
        """Windowed cross-tier handoff signals — the autopilot read API
        for tier scaling. Per configured window: handoff outcome deltas,
        wire bandwidth (handoff bytes over covered wall time), handoff
        latency delta-quantiles, and per-tier fault/restore rates."""
        with self._lock:
            ring = list(self._signal_ring)
        if len(ring) < 2:
            return {}
        now_t, now_counts, _, now_bytes, now_outcomes, now_faults, \
            now_restores = ring[-1]
        out: dict = {}
        for window in self._signal_windows:
            base = ring[0]
            # Oldest-first fallback: a young pool reports what it has,
            # with covered_s telling the truth about how much that is.
            for sample in reversed(ring[:-1]):
                if now_t - sample[0] >= window:
                    base = sample
                    break
            (base_t, base_counts, _, base_bytes, base_outcomes,
             base_faults, base_restores) = base
            covered = now_t - base_t
            if covered <= 0:
                continue
            delta_counts = [
                max(0, n - b) for n, b in zip(now_counts, base_counts)
            ]
            n = sum(delta_counts)
            bytes_delta = max(0, now_bytes - base_bytes)
            faults = {
                tier: max(0, now_faults.get(tier, 0)
                          - base_faults.get(tier, 0))
                for tier in (PREFILL, DECODE)
            }
            out[window_label(window)] = {
                "covered_s": round(covered, 3),
                "handoffs": {
                    outcome: max(0, now_outcomes.get(outcome, 0)
                                 - base_outcomes.get(outcome, 0))
                    for outcome in _OUTCOMES
                },
                "handoff_bytes": bytes_delta,
                "wire_bandwidth_bytes_per_s": round(
                    bytes_delta / covered, 1),
                "handoff_ms_count": n,
                "handoff_ms_p50": round(estimate_quantile(
                    self.handoff_ms.bounds, delta_counts, n, 50), 2),
                "handoff_ms_p95": round(estimate_quantile(
                    self.handoff_ms.bounds, delta_counts, n, 95), 2),
                "tier_faults": faults,
                "tier_restores": {
                    tier: max(0, now_restores.get(tier, 0)
                              - base_restores.get(tier, 0))
                    for tier in (PREFILL, DECODE)
                },
                "fault_rate_per_min": round(
                    sum(faults.values()) * 60.0 / covered, 3),
            }
        return out

    def worker_timeline(self, worker: _Worker) -> Optional[list]:
        """Fetch one worker's live timeline ring over the control
        plane; None when the worker is unreachable (the caller falls
        back to its black-box file)."""
        if worker.addr is None:
            return None
        try:
            with WorkerConn(worker.addr, timeout=3.0) as conn:
                reply, _ = conn.request({"op": "timeline"}, timeout=3.0)
        except (OSError, ConnectionError, ValueError):
            return None
        if not reply.get("ok"):
            return None
        return reply.get("events") or []

    def merged_timelines(self) -> list:
        """The clock-aligned merged timeline: one (pid, label, events)
        group per process — the coordinator's own ring at offset 0 plus
        every worker's ring mapped onto the coordinator's clock by its
        ClockSync offset. Dead workers contribute their last black-box
        checkpoint, so a merge after a crash still shows the victim's
        final seconds."""
        groups: list = []
        if self.timeline is not None:
            groups.append((0, "coordinator",
                           self.timeline.events() or [], 0.0))
        state_dir = getattr(self, "_state_dir", None)
        for pid, worker in enumerate(list(self.workers), start=1):
            events = self.worker_timeline(worker)
            if events is None and state_dir:
                events = _blackbox_timeline(state_dir, worker.role)
            if not events:
                continue
            groups.append((pid, worker.role, events,
                           worker.clock.offset or 0.0))
        return merge_timelines(groups)

    def merged_perfetto(self) -> dict:
        """ONE Perfetto trace for the whole pool: one process row per
        worker plus the coordinator, all on the coordinator's clock, so
        a handoff renders as a single causally-ordered arc from the
        prefill worker's serialize end to the decode worker's scatter
        start."""
        return to_perfetto(
            self.merged_timelines(),
            meta={"clock_offsets": self.clock_offsets()},
        )


def _blackbox_timeline(state_dir: str, role: str) -> Optional[list]:
    """Last-checkpoint fallback for a dead worker's timeline."""
    from ..obs.postmortem import blackbox_path
    try:
        with open(blackbox_path(state_dir, role), encoding="utf-8") as f:
            return json.load(f).get("timeline") or []
    except (OSError, ValueError):
        return None


class _Terminal(Exception):
    """The request already received its terminal event; unwind only."""


def _config_env(config: EngineConfig) -> dict:
    """Render the engine-geometry knobs as the POLYKEY_* env vars
    `EngineConfig.from_env` reads — the spawn-time config channel.
    Identical geometry on every worker (and any in-process reference)
    is what makes the disaggregated greedy stream bit-identical."""
    flag = "1"
    return {
        "POLYKEY_MODEL": config.model,
        "POLYKEY_TOKENIZER": config.tokenizer,
        "POLYKEY_DTYPE": config.dtype,
        "POLYKEY_KV_DTYPE": config.kv_dtype,
        "POLYKEY_QUANTIZE": (
            ("int4" if config.quantize_bits == 4 else "int8")
            if config.quantize else "0"
        ),
        "POLYKEY_MAX_DECODE_SLOTS": str(config.max_decode_slots),
        "POLYKEY_PAGE_SIZE": str(config.page_size),
        "POLYKEY_NUM_PAGES": str(config.num_pages),
        "POLYKEY_MAX_SEQ_LEN": str(config.max_seq_len),
        "POLYKEY_PREFILL_BUCKETS": ",".join(
            str(b) for b in config.prefill_buckets
        ),
        "POLYKEY_PREFILL_CHUNK": str(config.prefill_chunk),
        "POLYKEY_PREFILL_BUDGET": str(config.prefill_budget),
        "POLYKEY_MAX_NEW_TOKENS_CAP": str(config.max_new_tokens_cap),
        "POLYKEY_DEFAULT_MAX_NEW_TOKENS": str(
            config.default_max_new_tokens
        ),
        "POLYKEY_RAGGED": flag if config.ragged_dispatch else "0",
        "POLYKEY_PREFIX_CACHE": flag if config.prefix_cache else "0",
        "POLYKEY_PREFIX_CACHE_PAGES": str(config.prefix_cache_pages),
        # Host-memory KV tier (ISSUE 15): a programmatic pool with the
        # tier on must not spawn tier-less workers (warm TTFT across
        # worker death silently off). The state dir ships as-is — the
        # worker harness scopes its own kv-<tier>-<replica> subdir.
        "POLYKEY_HOST_KV_BYTES": str(config.host_kv_bytes),
        "POLYKEY_KV_RESIDENT_PAGES": str(config.host_kv_resident_pages),
        "POLYKEY_KV_RESTORE_SLOTS": str(config.host_kv_restore_slots),
        "POLYKEY_KV_STATE_DIR": config.kv_state_dir,
        "POLYKEY_COMPILE_WARMUP": flag if config.compile_warmup else "0",
        "POLYKEY_DECODE_BLOCK": str(config.decode_block_steps),
        "POLYKEY_ADAPTIVE_BLOCK": flag if config.adaptive_block else "0",
        "POLYKEY_DISPATCH_LOOKAHEAD": str(config.lookahead_blocks),
        "POLYKEY_TIMELINE_CAPACITY": str(config.timeline_capacity),
        "POLYKEY_BLACKBOX_EVERY": str(config.blackbox_every),
        "POLYKEY_SIGNALS_INTERVAL": str(config.signals_interval_s),
        # Signal-plane policy (found by memlint ML005): a programmatic
        # pool with custom windows or an SLO must not spawn workers
        # that silently evaluate the defaults — burn rates would
        # disagree across tiers for the same traffic.
        "POLYKEY_SIGNALS_WINDOWS": config.signals_windows,
        "POLYKEY_SLO": config.slo_policy,
        "POLYKEY_TOP_P_CANDIDATES": str(config.top_p_candidates),
        "POLYKEY_WATCHDOG_TIMEOUT": str(config.watchdog_timeout_s),
        "POLYKEY_REQUEST_TIMEOUT": str(config.request_timeout_s),
        "POLYKEY_MAX_QUEUE": str(config.max_queue_depth),
        "POLYKEY_SUPERVISE": flag if config.supervise else "0",
        "POLYKEY_MAX_RESTARTS": str(config.max_engine_restarts),
        "POLYKEY_RESTART_WINDOW": str(config.restart_window_s),
        # Weights + mesh: a programmatic config with a checkpoint (or
        # tp>1) must not spawn random-init single-device workers.
        "POLYKEY_CHECKPOINT": config.checkpoint_path or "",
        "POLYKEY_TP": str(config.tp),
        "POLYKEY_DP": str(config.dp),
        "POLYKEY_EP": str(config.ep),
        "POLYKEY_SP": str(config.sp),
        "POLYKEY_PP": str(config.pp),
        "POLYKEY_NUM_SLICES": str(config.num_slices),
    }
