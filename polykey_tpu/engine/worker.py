"""Disaggregated worker harness: one engine process behind a localhost
control plane (ISSUE 13, ROADMAP item 2 stage (b)).

A worker is ONE tier member of the disaggregated pool
(engine/disagg_pool.py): a supervised `InferenceEngine` (its own
watchdog + `EngineSupervisor` restart budget, exactly the per-replica
wiring replica_pool.py uses) plus a tiny length-framed socket protocol
the coordinator drives. Prefill-tier workers run requests in
``prefill_only`` mode and RETAIN the serialized KV handoff blob until
the coordinator releases it (the two-phase hand-over: source keeps the
state until the target has decoded past any need for a re-ship);
decode-tier workers accept ``resume_state`` requests and stream tokens.

Protocol — every message is ``!II``-framed (header_len, payload_len) +
JSON header + raw payload bytes; one TCP connection carries one RPC
(the prefill/decode ops stream multiple response frames on it):

    {"op": "ping"}                  → liveness + routing signals
    {"op": "stats"}                 → full engine.stats() + histogram
                                      bucket counts (exposition)
    {"op": "prefill", "req": {…}}   → {"event": "handoff_ready", …}
                                      then {"event": "done"/"error"}
    {"op": "fetch", "handoff_id"}   → one frame whose payload is the
                                      retained KV wire blob
    {"op": "release", "handoff_id"} → drops the retained blob (phase 2)
    {"op": "decode", "req": {…}} + blob payload
                                    → {"event": "token", …}* then
                                      {"event": "done"/"error"}
    {"op": "arm_faults", "spec"}    → installs a POLYKEY_FAULTS spec
                                      mid-run (the cross-process mirror
                                      of the PR 7 mid-run kill pattern)
    {"op": "exit"}                  → clean shutdown

Fault points (faults.py, all honoring ``:tier=`` / ``:replica=``):
``worker-exit`` kills the process at the next consulted protocol site —
prefill intake (queued/mid-prefill death), payload fetch (mid-handoff
death), or after forwarding `value` tokens of a decode stream
(mid-decode death); ``handoff-delay`` sleeps before shipping a blob;
``kv-handoff-drop`` truncates the shipped blob to half (a partial
write), which the coordinator's validation turns into a clean re-route.

Run as a process: ``python -m polykey_tpu.engine.worker --tier prefill
--replica 0 --port 0`` (prints one ``{"ready": true, "port": N}`` JSON
line on stdout). Tests run `WorkerServer` on a background thread with
``exit_mode="simulate"`` — worker-exit then severs the control plane
(connections + listener) instead of killing the test process, which is
indistinguishable from death to the coordinator.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import socket
import struct
import sys
import threading
import time
import uuid
from collections import OrderedDict
from functools import partial
from typing import Optional

import numpy as np

from ..faults import get_injector
from ..obs import BlackBox, FlightRecorder, Span, Tracer
from .config import EngineConfig
from .engine import (
    EngineDeadError,
    EngineOverloadedError,
    GenRequest,
    InferenceEngine,
)
from .kv_cache import deserialize_kv_state, serialize_kv_state
from .supervisor import EngineSupervisor
from .watchdog import Watchdog

# Bounded retention of serialized handoff blobs awaiting release: the
# two-phase hand-over holds state for in-flight transfers only, so a
# coordinator that crashes without releasing cannot grow a worker
# without bound — oldest entries fall off.
_RETAIN_CAP = 64

# Warm-session index cap (memlint ML002): the persisted index always
# truncated to the newest 512 sessions, but the in-memory OrderedDict
# grew one key per session for the worker's lifetime — bound both to
# the same LRU window so they can't diverge.
_WARM_KEYS_CAP = 512


def session_key(prompt_ids: np.ndarray, page_size: int) -> str:
    """Session identity for sticky routing: a hash of the prompt's first
    page-aligned token window. Multi-turn conversations share their
    system-prompt/history head, so turns of one session map to one key —
    the signal that keeps them landing on their warm prefill worker."""
    import hashlib

    head = np.ascontiguousarray(
        np.asarray(prompt_ids, np.int32)[:page_size]
    ).tobytes()
    return hashlib.blake2b(head, digest_size=8).hexdigest()


# -- framing ------------------------------------------------------------------

def send_msg(sock: socket.socket, header: dict, payload: bytes = b"") -> None:
    raw = json.dumps(header).encode()
    sock.sendall(struct.pack("!II", len(raw), len(payload)) + raw + payload)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


def recv_msg(sock: socket.socket) -> tuple[dict, bytes]:
    header_len, payload_len = struct.unpack("!II", _read_exact(sock, 8))
    header = json.loads(_read_exact(sock, header_len)) if header_len else {}
    payload = _read_exact(sock, payload_len) if payload_len else b""
    return header, payload


def _json_safe(obj):
    """Engine stats are mostly plain Python; numpy scalars that slip
    through (histogram snapshots, mirrors) coerce here so the control
    plane never 500s a stats scrape."""
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    return obj


class _WorkerHealth:
    """Health shim for the worker's watchdog/supervisor: flips the
    worker's advertised state, which `ping` reports to the coordinator —
    the cross-process analog of replica_pool's per-replica shim."""

    def __init__(self, server: "WorkerServer"):
        self._server = server

    def shutdown(self) -> None:
        self._server.serving = False

    def resume_serving(self) -> None:
        self._server.serving = True

    def resume(self) -> None:
        pass

    def set_serving_status(self, service, status) -> None:
        pass


class WorkerServer:
    """One tier worker: engine + supervision + the socket control plane.

    `exit_mode="process"` (the real harness) honors ``worker-exit`` with
    ``os._exit`` — genuine process death, nothing flushes.
    `exit_mode="simulate"` (tests) severs the listener and every open
    connection instead, so an in-process test observes exactly what the
    coordinator would: a dead control plane."""

    def __init__(
        self,
        config: EngineConfig,
        tier: str,
        replica: int = 0,
        port: int = 0,
        host: str = "127.0.0.1",
        seed: int = 0,
        params: Optional[dict] = None,
        logger=None,
        exit_mode: str = "process",
        state_dir: Optional[str] = None,
        watchdog_interval_s: float = 5.0,
        supervisor_interval_s: float = 0.5,
    ):
        if tier not in ("prefill", "decode"):
            raise ValueError(f"tier must be prefill or decode, got {tier!r}")
        self.tier = tier
        self.replica = replica
        self.logger = logger
        self.exit_mode = exit_mode
        self.state_dir = state_dir
        self.serving = True
        self._closing = False
        self._died = False
        # Worker engines are single-engine by definition: the pool is
        # the cross-process scale-out, and tier identity scopes faults.
        # With the host KV tier on, the worker's durable prefix pages
        # land in a per-worker subdir of the state dir (alongside the
        # warm-session index below), so a respawned worker process
        # reloads its own spilled pages — warm TTFT across process
        # death, not just supervised in-process restarts.
        # ALWAYS per-worker: even an explicit POLYKEY_KV_STATE_DIR gets
        # a worker-scoped subdir, or every worker's durable-store gc()
        # (capped at ONE engine's host capacity) would delete the other
        # workers' batches out of the shared directory.
        kv_dir = config.kv_state_dir
        if not kv_dir and state_dir and config.host_kv_bytes > 0:
            kv_dir = state_dir
        if kv_dir:
            kv_dir = os.path.join(kv_dir, f"kv-{tier}-{replica}")
        worker_cfg = dataclasses.replace(
            config, replicas=1, disagg="", disagg_tier=tier,
            replica=replica, kv_state_dir=kv_dir,
        )
        self.config = worker_cfg
        self.engine = InferenceEngine(
            worker_cfg, params=params, health=_WorkerHealth(self),
            logger=logger, seed=seed,
        )
        self.watchdog = Watchdog(
            self.engine, health=_WorkerHealth(self), logger=logger,
            check_interval_s=watchdog_interval_s,
        )
        self.supervisor = None
        if worker_cfg.supervise:
            ctor = self.engine._ctor_args
            factory = partial(
                InferenceEngine, worker_cfg, params=ctor["params"],
                health=_WorkerHealth(self), logger=logger,
                seed=ctor["seed"],
            )
            self.supervisor = EngineSupervisor(
                self.engine, lambda: factory(),
                watchdog=self.watchdog, health=_WorkerHealth(self),
                logger=logger,
                max_restarts=worker_cfg.max_engine_restarts,
                restart_window_s=worker_cfg.restart_window_s,
                check_interval_s=supervisor_interval_s,
            )
            self.supervisor.add_restart_listener(self._on_engine_restart)
        self._retained: OrderedDict[str, bytes] = OrderedDict()
        self._retained_lock = threading.Lock()
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"polykey-worker-{tier}{replica}",
            daemon=True,
        )
        # Persisted prefix-cache index (warm-rejoin satellite): session
        # keys this worker prefilled, reloaded at boot so the restarted
        # worker re-advertises its warm sessions to the router.
        self._warm_keys: "OrderedDict[str, bool]" = OrderedDict()
        self._load_warm_index()
        # Autopilot knob setpoints last pushed by the coordinator
        # ({"op": "knobs"}); re-applied to the fresh engine after a
        # supervised restart so actuations survive worker recovery.
        self._knob_setpoints: dict = {}
        # Worker-local span trees (ISSUE 16): the engine appends
        # children to any request.trace, but the recorder that keeps
        # finished trees lives with the gateway — a worker needs its own
        # so its side of a cross-process request survives in the black
        # box. The black box itself (crash-durable checkpoint of both
        # rings) exists only when the pool gave this member a state dir.
        self.recorder = FlightRecorder(capacity=32)
        self.tracer = Tracer(self.recorder)
        self.blackbox: Optional[BlackBox] = None
        if state_dir and worker_cfg.blackbox_every > 0:
            self.blackbox = BlackBox(
                state_dir, f"{tier}-{replica}",
                timeline=getattr(self.engine, "timeline", None),
                recorder=self.recorder,
                every=worker_cfg.blackbox_every,
                meta={"tier": tier, "replica": replica},
            )
            if self.supervisor is not None:
                self.supervisor.add_trip_listener(self._on_engine_trip)

    def _on_engine_restart(self, fresh) -> None:
        self.engine = fresh
        if self._knob_setpoints:
            # A fresh engine boots with config-default knobs; the
            # coordinator's autopilot actuations must outlive this
            # worker's own supervised restart (adoption carries
            # metrics, not engine attributes).
            self._apply_knobs(self._knob_setpoints)
        if self.blackbox is not None:
            self.blackbox.rebind(getattr(fresh, "timeline", None),
                                 self.recorder)

    def _apply_knobs(self, knobs: dict) -> dict:
        """Apply coordinator-pushed live-knob setpoints (the autopilot's
        cross-process actuation path) and remember them so a supervised
        engine restart re-applies rather than silently reverting."""
        from .autopilot import apply_engine_knobs

        applied = apply_engine_knobs(self.engine, knobs)
        # polylint: disable=ML002(keyed by knob name: 4 static engine-knob names from _ENGINE_KNOB_SETTERS, not per-request data)
        self._knob_setpoints.update(applied)
        return applied

    def _on_engine_trip(self, dead_engine, reason: str) -> None:
        # Forced checkpoint of the DYING engine's rings: rebind to the
        # corpse for one flush so the trip evidence isn't lost to the
        # restart swapping a fresh (empty) timeline in underneath us.
        if self.blackbox is None:
            return
        self.blackbox.rebind(getattr(dead_engine, "timeline", None),
                             self.recorder)
        self.blackbox.tick(force=True)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "WorkerServer":
        self.watchdog.start()
        if self.supervisor is not None:
            self.supervisor.start()
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        # polylint: disable=CL002(one-way shutdown latch: a GIL-atomic bool publish; conn threads re-check it every loop and a stale read only costs one extra iteration)
        self._closing = True
        self._sever()
        # Lock-witness dump rides the clean exit-op path, BEFORE the
        # slow engine teardown: the coordinator's terminate() follow-up
        # beats both atexit and a post-shutdown dump (no-op unless
        # POLYKEY_LOCK_WITNESS armed the witness at import).
        from ..analysis import heapwitness, witness as lock_witness

        lock_witness.dump()
        heapwitness.checkpoint("worker-stop")
        heapwitness.dump()
        if self.supervisor is not None:
            self.supervisor.stop()
        self.watchdog.stop()
        self.engine.shutdown()

    def _sever(self) -> None:
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def _die(self) -> None:
        """worker-exit: the process is gone. In simulate mode only the
        control plane dies — which is all the coordinator can see."""
        if self.exit_mode == "process":
            os._exit(1)
        # polylint: disable=CL002(one-way death latch, simulate mode only: GIL-atomic bool publish mirroring the real os._exit which synchronizes nothing either)
        self._died = True
        self._sever()

    def simulate_death(self) -> None:
        """Test hook: kill this worker the way worker-exit would in
        simulate mode (sever the control plane, keep the test process)."""
        self._die()

    def _maybe_exit(self, site: str) -> Optional[int]:
        """Consult the worker-exit fault for one protocol site. The
        fault VALUE selects where death strikes (faults.py): 0 → op
        intake, 1 → payload fetch (mid-handoff), >= 2 → after that many
        forwarded decode tokens (mid-decode). Returns the value when the
        site matched (stream sites carry it as the token threshold)."""
        faults = get_injector()
        if faults is None:
            return None
        preds = {
            "intake": lambda v: v <= 0,
            "fetch": lambda v: v <= 1,     # 0 or 1: both die in-handoff
            "stream": lambda v: v >= 2,
        }
        value = faults.take_if("worker-exit", preds[site],
                               replica=self.replica, tier=self.tier)
        return None if value is None else int(value)

    # -- warm-index persistence ----------------------------------------------

    def _index_path(self) -> Optional[str]:
        if not self.state_dir:
            return None
        return os.path.join(
            self.state_dir, f"worker-{self.tier}-{self.replica}.prefix.json"
        )

    def _load_warm_index(self) -> None:
        path = self._index_path()
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                for key in json.load(f).get("sessions", []):
                    self._warm_keys[str(key)] = True
                while len(self._warm_keys) > _WARM_KEYS_CAP:
                    self._warm_keys.popitem(last=False)
        except (OSError, ValueError):
            pass  # a corrupt index only costs warmth, never liveness

    def _persist_warm_index(self) -> None:
        path = self._index_path()
        if path is None:
            return
        try:
            os.makedirs(self.state_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(
                    {"sessions": list(self._warm_keys)[-_WARM_KEYS_CAP:]},
                    f,
                )
            os.replace(tmp, path)
        except OSError:
            pass  # persistence is an optimization, never a failure

    # -- control plane --------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closing and not self._died:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._closing and not self._died:
                try:
                    header, payload = recv_msg(conn)
                except (ConnectionError, OSError, ValueError):
                    return
                op = header.get("op")
                if op == "ping":
                    send_msg(conn, self._ping_reply())
                elif op == "stats":
                    send_msg(conn, {"ok": True,
                                    "stats": self._stats_reply()})
                elif op == "timeline":
                    # Live ring pull for the merged flight deck; `mono`
                    # lets the caller sanity-check its clock offset.
                    timeline = getattr(self.engine, "timeline", None)
                    send_msg(conn, {
                        "ok": True,
                        "mono": time.monotonic(),
                        "events": _json_safe(
                            timeline.events()
                            if timeline is not None else []
                        ),
                    })
                elif op == "prefill":
                    self._handle_prefill(conn, header.get("req") or {})
                elif op == "fetch":
                    self._handle_fetch(conn, header.get("handoff_id", ""))
                elif op == "release":
                    with self._retained_lock:
                        self._retained.pop(header.get("handoff_id", ""),
                                           None)
                    send_msg(conn, {"ok": True})
                elif op == "decode":
                    self._handle_decode(conn, header.get("req") or {},
                                        payload)
                elif op == "arm_faults":
                    from .. import faults as faults_mod

                    injector = faults_mod.install(header.get("spec", ""))
                    # Engines cache the injector at construction — the
                    # mid-run arm must reach the LIVE engine (the PR 7
                    # mid-run kill pattern, across the process boundary).
                    self.engine._faults = injector
                    send_msg(conn, {"ok": True})
                elif op == "knobs":
                    # Autopilot actuation push: apply through the LIVE
                    # engine's setters, reply with what actually landed
                    # (post-clamp) so the coordinator records truth.
                    send_msg(conn, {
                        "ok": True,
                        "applied": self._apply_knobs(
                            header.get("knobs") or {}
                        ),
                    })
                elif op == "exit":
                    # Witness dump BEFORE the ack: the coordinator
                    # terminates this process right after the reply
                    # lands, and SIGTERM runs no atexit hooks.
                    from ..analysis import heapwitness, \
                        witness as lock_witness

                    lock_witness.dump()
                    heapwitness.checkpoint("worker-exit")
                    heapwitness.dump()
                    send_msg(conn, {"ok": True})
                    threading.Thread(target=self.stop, daemon=True).start()
                    return
                else:
                    send_msg(conn, {"ok": False,
                                    "error": f"unknown op {op!r}"})
        except (ConnectionError, OSError):
            pass  # peer went away; nothing to clean beyond the conn
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _ping_reply(self) -> dict:
        engine = self.engine
        state = "SERVING"
        if engine.dead is not None or not self.serving:
            state = "NOT_SERVING"
        if self.supervisor is not None and self.supervisor.gave_up:
            state = "DEAD"
        return {
            "ok": True, "tier": self.tier, "replica": self.replica,
            "state": state, "pid": os.getpid(),
            # Clock-sync sample (ISSUE 16, obs/clocks.py): this worker's
            # monotonic timestamp, assumed by the coordinator to be
            # taken at the ping's request/response midpoint.
            "mono": time.monotonic(),
            "queued": engine._submit.qsize(),
            "slots_busy": sum(s is not None for s in engine._slots),
            "slots_total": engine.config.max_decode_slots,
            "queue_delay_s": engine.queue_delay_estimate_s(),
            "load": engine.load_fraction(),
            "retained_handoffs": len(self._retained),
            "warm_sessions": list(self._warm_keys)[-_WARM_KEYS_CAP:],
            # Host-KV tier warmth advertisement (ISSUE 15): how much
            # cold-but-warm state this worker holds (host-resident pages
            # restore in ~ms; a cold recompute costs a full prefill) —
            # routing-relevant exactly like warm_sessions above.
            "kv_host_pages": (
                engine._host_kv.used
                if getattr(engine, "_host_kv", None) is not None else 0
            ),
            "kv_reloaded_pages": getattr(engine, "_kv_reloaded_pages", 0),
        }

    def _stats_reply(self) -> dict:
        snap = _json_safe(self.engine.stats())
        snap["tier"] = self.tier
        snap["replica"] = self.replica
        hists = {}
        for name, attr in (("ttft_ms", "ttft_hist"), ("itl_ms", "itl_hist")):
            hist = getattr(self.engine.metrics, attr)
            counts, total_sum = hist.counts_snapshot()
            hists[name] = {
                "bounds": list(hist.bounds),
                "counts": list(counts),
                "sum": total_sum,
            }
        snap["_hists"] = hists
        return snap

    def _build_request(self, req: dict, **extra) -> GenRequest:
        deadline = None
        if req.get("deadline_in_s") is not None:
            deadline = time.monotonic() + float(req["deadline_in_s"])
        # Trace propagation (ISSUE 16): a req carrying the gateway's
        # trace_id gets a worker-local root span with the SAME id, so
        # the engine's queue_wait/prefill/decode children — stamped on
        # this process's monotonic clock — join the distributed trace.
        # The finished tree ships back in the `done` frame and feeds the
        # local flight recorder (and therefore the black box).
        trace = None
        trace_id = req.get("trace_id")
        if trace_id:
            trace = Span(f"worker:{self.tier}{self.replica}",
                         trace_id=str(trace_id))
        return GenRequest(
            prompt=req.get("prompt", ""),
            max_new_tokens=int(req.get("max_new_tokens", 64)),
            temperature=float(req.get("temperature", 0.0)),
            top_p=float(req.get("top_p", 1.0)),
            top_k=int(req.get("top_k", 0)),
            seed=req.get("seed"),
            deadline=deadline,
            trace=trace,
            **extra,
        )

    def _box_note(self, note_kind: str, **attrs) -> None:
        """Timeline note + FORCED black-box checkpoint: op intake calls
        this so the fatal request's trace id is durably in the ring
        before any fault site can kill the process (``os._exit`` flushes
        nothing — the checkpoint must happen-before the death)."""
        timeline = getattr(self.engine, "timeline", None)
        if timeline is not None:
            timeline.note(
                note_kind,
                **{k: v for k, v in attrs.items() if v is not None},
            )
        if self.blackbox is not None:
            self.blackbox.tick(force=True)

    def _finish_trace(self, request: GenRequest) -> Optional[dict]:
        """Close a traced request's worker-side tree, file it in the
        local flight recorder, and render the wire form (absolute
        monotonic start/end — the coordinator grafts it onto the
        gateway root after clock alignment)."""
        if request.trace is None:
            return None
        self.tracer.finish_and_record(request.trace)
        return _span_wire(request.trace)

    def _submit(self, conn: socket.socket, request: GenRequest) -> bool:
        try:
            self.engine.submit(request)
            return True
        except EngineOverloadedError as e:
            send_msg(conn, {"event": "error", "shed": True,
                            "retry_after_ms": e.retry_after_ms,
                            "message": str(e)})
        except EngineDeadError as e:
            send_msg(conn, {"event": "error", "message": f"engine: {e}"})
        return False

    def _handle_prefill(self, conn: socket.socket, req: dict) -> None:
        handoff_id = req.get("handoff_id") or uuid.uuid4().hex
        self._box_note("prefill_op", trace=req.get("trace_id"),
                       handoff_id=handoff_id)
        if self._maybe_exit("intake") is not None:
            self._die()           # queued / mid-prefill death
            return
        request = self._build_request(req, prefill_only=True)
        if not self._submit(conn, request):
            return
        persist_index = False
        try:
            while True:
                kind, value = request.out.get()
                if kind == "handoff":
                    t_ser = time.monotonic()
                    blob = serialize_kv_state(value)
                    t_ser_end = time.monotonic()
                    serialize_ms = (t_ser_end - t_ser) * 1e3
                    if request.trace is not None:
                        request.trace.child(
                            "handoff_serialize", start=t_ser, end=t_ser_end,
                            handoff_id=handoff_id, bytes=len(blob),
                        )
                    with self._retained_lock:
                        self._retained[handoff_id] = blob
                        while len(self._retained) > _RETAIN_CAP:
                            self._retained.popitem(last=False)
                    key = session_key(value.prompt_ids, value.page_size)
                    self._warm_keys[key] = True
                    self._warm_keys.move_to_end(key)
                    while len(self._warm_keys) > _WARM_KEYS_CAP:
                        self._warm_keys.popitem(last=False)
                    persist_index = True
                    timeline = getattr(self.engine, "timeline", None)
                    if timeline is not None:
                        timeline.note("handoff_retained",
                                      handoff_id=handoff_id,
                                      bytes=len(blob))
                    # Arc source for the merged flight deck: serialize
                    # END on this process's clock (+ forced checkpoint —
                    # the next fault site is the mid-handoff fetch kill).
                    self._box_note("handoff_serialize",
                                   handoff_id=handoff_id,
                                   trace=req.get("trace_id"),
                                   bytes=len(blob),
                                   serialize_ms=round(serialize_ms, 3))
                    send_msg(conn, {
                        "event": "handoff_ready",
                        "handoff_id": handoff_id,
                        "bytes": len(blob),
                        "prompt_tokens": value.prompt_len,
                        "first_token": value.first_token,
                        "session": key,
                        "serialize_ms": round(serialize_ms, 3),
                    })
                elif kind == "done":
                    send_msg(conn, {"event": "done",
                                    "timings": _timings_dict(value),
                                    "trace": self._finish_trace(request)})
                    return
                else:
                    send_msg(conn, {"event": "error",
                                    "message": str(value)})
                    return
        except (ConnectionError, OSError):
            # Coordinator gone mid-prefill (timeout / re-route / death):
            # stop the work — chunked prefills check cancellation
            # between chunks — and drop the orphaned retention (nobody
            # will ever fetch or release this handoff_id).
            request.cancelled.set()
            with self._retained_lock:
                self._retained.pop(handoff_id, None)
        finally:
            if persist_index:
                # Off the handoff critical path: the index write lands
                # AFTER handoff_ready/done went out (it is an
                # optimization — a missing entry only costs warmth).
                self._persist_warm_index()

    def _handle_fetch(self, conn: socket.socket, handoff_id: str) -> None:
        faults = get_injector()
        if faults is not None:
            faults.maybe_sleep("handoff-delay", replica=self.replica,
                               tier=self.tier)
        if self._maybe_exit("fetch") is not None:
            self._die()           # mid-handoff death: blob never ships
            return
        with self._retained_lock:
            blob = self._retained.get(handoff_id)
        if blob is None:
            send_msg(conn, {"ok": False,
                            "error": f"unknown handoff {handoff_id!r}"})
            return
        if faults is not None and faults._take(
            "kv-handoff-drop", replica=self.replica, tier=self.tier
        ) is not None:
            blob = blob[:len(blob) // 2]     # partial write on the wire
        send_msg(conn, {"ok": True, "bytes": len(blob)}, blob)

    def _handle_decode(self, conn: socket.socket, req: dict,
                       payload: bytes) -> None:
        faults = get_injector()
        if faults is not None:
            faults.maybe_sleep("handoff-delay", replica=self.replica,
                               tier=self.tier)
        self._box_note("decode_op", trace=req.get("trace_id"),
                       handoff_id=req.get("handoff_id"),
                       bytes=len(payload))
        if self._maybe_exit("intake") is not None:
            self._die()           # death at resume intake
            return
        t_deser = time.monotonic()
        try:
            state = deserialize_kv_state(payload)
        except Exception as e:
            send_msg(conn, {"event": "error",
                            "message": f"kv-handoff rejected: {e}"})
            return
        t_deser_end = time.monotonic()
        deserialize_ms = (t_deser_end - t_deser) * 1e3
        request = self._build_request(req, resume_state=state)
        if request.trace is not None:
            request.trace.child(
                "handoff_deserialize", start=t_deser, end=t_deser_end,
                handoff_id=req.get("handoff_id"), bytes=len(payload),
            )
        if not self._submit(conn, request):
            return
        # Arc sink for the merged flight deck: the blob is resident and
        # the engine's restore-scatter begins at this submit — scatter
        # START on this process's clock.
        self._box_note("handoff_scatter",
                       handoff_id=req.get("handoff_id"),
                       trace=req.get("trace_id"),
                       deserialize_ms=round(deserialize_ms, 3))
        send_msg(conn, {"event": "accepted",
                        "deserialize_ms": round(deserialize_ms, 3)})
        # The stream-site kill arms only once a stream actually exists:
        # consuming the one-shot budget on a rejected/shed op would
        # silently lose the drill's armed mid-decode death.
        exit_after = self._maybe_exit("stream")
        forwarded = 0
        while True:
            kind, value = request.out.get()
            try:
                if kind == "token":
                    forwarded += 1
                    send_msg(conn, {"event": "token", "id": int(value)})
                    if self.blackbox is not None:
                        self.blackbox.tick()   # amortized (every K)
                    if exit_after is not None and forwarded >= exit_after:
                        request.cancelled.set()
                        self._die()  # mid-decode death, stream mid-flight
                        return
                elif kind == "done":
                    send_msg(conn, {"event": "done",
                                    "timings": _timings_dict(value),
                                    "trace": self._finish_trace(request)})
                    return
                else:
                    send_msg(conn, {"event": "error",
                                    "message": str(value)})
                    return
            except (ConnectionError, OSError):
                # Coordinator gone (client cancel / coordinator death):
                # stop the engine-side stream instead of decoding to
                # max_new for nobody — the lane and its pages free at
                # the next block boundary.
                request.cancelled.set()
                return


def _span_wire(span: Span) -> dict:
    """Wire form of a span tree: unlike `Span.to_dict` it keeps the
    ABSOLUTE monotonic start/end, which is exactly what the coordinator
    needs to re-time the tree onto its own clock (offset + graft)."""
    with span._lock:
        children = list(span.children)
        attrs = dict(span.attrs)
    out: dict = {"name": span.name, "start": span.start, "end": span.end}
    if attrs:
        out["attrs"] = _json_safe(attrs)
    if children:
        out["children"] = [_span_wire(c) for c in children]
    return out


def _timings_dict(timings) -> dict:
    if timings is None:
        return {}
    return {
        "prompt_tokens": timings.prompt_tokens,
        "completion_tokens": timings.completion_tokens,
        "ttft_ms": timings.ttft_ms,
        "tokens_per_sec": timings.tokens_per_sec,
        "device_ms": round(getattr(timings, "device_ms", 0.0), 3),
    }


# -- client side (used by the coordinator) ------------------------------------

class WorkerConn:
    """One RPC connection to a worker's control plane."""

    def __init__(self, addr: tuple[str, int], timeout: float = 10.0):
        self.sock = socket.create_connection(addr, timeout=timeout)

    def __enter__(self) -> "WorkerConn":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def request(self, header: dict, payload: bytes = b"",
                timeout: Optional[float] = None) -> tuple[dict, bytes]:
        if timeout is not None:
            self.sock.settimeout(timeout)
        send_msg(self.sock, header, payload)
        return recv_msg(self.sock)

    def send(self, header: dict, payload: bytes = b"") -> None:
        send_msg(self.sock, header, payload)

    def recv(self, timeout: Optional[float] = None) -> tuple[dict, bytes]:
        if timeout is not None:
            self.sock.settimeout(timeout)
        return recv_msg(self.sock)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


# -- process entry point ------------------------------------------------------

def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(description="polykey disagg worker")
    parser.add_argument("--tier", required=True,
                        choices=("prefill", "decode"))
    parser.add_argument("--replica", type=int, default=0)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--state-dir", default="")
    args = parser.parse_args(argv)

    # Honor the documented CPU mode before backend init (the server.py
    # pattern: some images pin a TPU plugin via sitecustomize).
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass

    config = EngineConfig.from_env()
    server = WorkerServer(
        config, tier=args.tier, replica=args.replica, port=args.port,
        seed=args.seed, state_dir=args.state_dir or None,
        exit_mode="process",
        watchdog_interval_s=min(5.0, config.watchdog_timeout_s / 3),
    ).start()
    # The readiness line is the spawn handshake: the coordinator reads
    # it from the worker's stdout to learn the bound port.
    print(json.dumps({"ready": True, "tier": args.tier,
                      "replica": args.replica, "port": server.port,
                      "pid": os.getpid()}), flush=True)
    try:
        while not server._closing and not server._died:
            time.sleep(0.2)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
