"""The inference engine: continuous batching over a paged KV cache.

This is the TPU-native replacement for the reference's mock backend — the
component the north star mounts at the Service seam (SURVEY.md §3.2: "the
handler keeps its signature; the implementation becomes enqueue-into-
scheduler, and the hot loop becomes the decode step loop on-device").

Design:

- One engine thread owns all device state (page pools, page tables, slot
  arrays). gRPC handler threads only enqueue GenRequests and read from
  per-request queues — no device access, no locks around jax calls.
- Static shapes everywhere: the decode batch is a fixed array of
  `max_decode_slots` slots; prompts prefill through a small set of padded
  length buckets. Slot occupancy is data (`active` mask), not shape.
- Latency-tolerant loop: decode runs in K-step blocks (one lax.scan
  dispatch each, device-side EOS/cap stopping), structured as a lookahead
  pipeline with two frontiers. The DISPATCH frontier runs ahead: block
  N+1 is dispatched before block N's results are read back, with up to
  `lookahead_blocks` slot-state generations device-resident (deepened
  proportionally when adaptive blocking shrinks K, so steps-in-flight —
  and therefore roundtrip hiding — stay constant). The PROCESSED frontier
  trails one (or more) blocks behind, reading each block's packed
  "done"/token buffer through the sanctioned `_host_crossing` path —
  landed copies drain in batches, and only a copy that has not landed
  yet blocks the host (measured as `host_stall_ms`). Depth 1 collapses
  the pipeline to synchronous dispatch-then-read, bit-identically.
  The per-step slot state (tokens / seq_lens / active) is DONATED through
  every decode dispatch, so the pipeline is double-buffered rather than
  allocating: at depth 2 exactly two generations exist on device — the
  in-flight block's inputs and the outputs the next dispatch consumes —
  and the donation chain guarantees they never alias. Admissions prefill
  in padded buckets (batched for bursts, chunked for long prompts) and
  activate their lanes via tiny on-device merge dispatches — no sync, no
  pipeline flush; retirements dispatch the mirror-image lane reset.
  Dispatch is asynchronous and effectively free; only first syncs of
  fresh results pay the host↔device roundtrip (PERF.md), so steady state
  pays ~one hidden sync per block regardless of latency.
- Inactive slots point their page tables at the reserved garbage page 0 and
  carry position 0; their lanes compute masked garbage that is never read.
- Page pools are donated through every jitted step (in-place update — the
  pool is by far the largest buffer); the donation chain also totally
  orders every dispatch on the device, which is what makes stale
  in-flight blocks' writes safe (see _retire_lane_fn / _merge_slot).
- RNG: no global chain — per-lane seed halves ride the device state and
  every sampled draw keys on fold_in(seed key, token position)
  (GenRequest.seed).
"""

from __future__ import annotations

import contextlib
import os
import queue
import threading
import time
import traceback
from dataclasses import dataclass, field
from functools import partial
from typing import TYPE_CHECKING, NamedTuple, Optional

if TYPE_CHECKING:
    from ..obs.trace import Span

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import schedwitness as _schedwitness
from ..faults import get_injector
from ..models.config import ModelConfig, get_config
from ..obs.timeline import TimelineRecorder
from ..models.transformer import (
    forward_paged,
    forward_ragged,
    init_params,
    unembed,
)
from ..parallel.mesh import MeshConfig, create_mesh
from ..parallel.sharding import paged_kv_sharding, shard_params
from .config import EngineConfig
from .kv_cache import (
    AllocationError,
    BlockAllocator,
    KVHandoffState,
    KVWireError,
    PagedKV,
    init_paged_kv,
)
from .metrics import EngineMetrics, RequestTimings
from .prefix_cache import TIER_DEVICE, TIER_HOST
from .sampling import sample_tail
from .tokenizer import load_tokenizer


# Sanctioned-crossing census (ISSUE 19): every _host_crossing scope names
# its site; entries count here so graphlint GL004 can pin the SET of
# crossing sites a serving smoke actually exercises per engine mode — the
# device-resident spec round's 5→2 per-round drop is a committed gate
# (analysis/graph.py SANCTIONED_CROSSINGS), not a claim. Engine-thread
# writes only; GL004 snapshots deltas around its guarded drive.
CROSSING_CENSUS: dict = {}


def _host_crossing(site: str = "unlabeled"):
    """Deliberate host<->device crossing point: resolve-point reads
    (np.asarray of landed blocks/tokens) and the tiny numpy scalars the
    lane merge/retire dispatches upload. graphlint GL004 smokes the
    serving loop under ``jax.transfer_guard("disallow")``; these scopes
    mark the sanctioned crossings, so any NEW implicit transfer added to
    the loop path trips the guard there instead of shipping silently.
    (PL001 is the source-tier mirror of the same invariant.)

    `site` labels the crossing for the census above; call sites pass a
    stable name (GL004 asserts the fired set against the committed
    table).

    Fast path: with no guard configured (every run except the GL004
    smoke) this is a nullcontext — the real jax context manager costs
    ~30 us per entry, which the per-block process path should not pay.
    The three per-direction options are what actually gate transfers
    (the umbrella jax_transfer_guard propagates INTO them on update but
    doesn't reflect a per-direction update), so they are what we check."""
    CROSSING_CENSUS[site] = CROSSING_CENSUS.get(site, 0) + 1
    if all(
        getattr(jax.config, opt) in (None, "allow")
        for opt in ("jax_transfer_guard_host_to_device",
                    "jax_transfer_guard_device_to_host",
                    "jax_transfer_guard_device_to_device")
    ):
        return contextlib.nullcontext()
    return jax.transfer_guard("allow")


@dataclass
class GenRequest:
    """One generation request, enqueued by a gRPC handler thread.

    The engine pushes ("token", id), then ("done", RequestTimings) or
    ("error", message) into `out`.
    """

    prompt: str
    max_new_tokens: int = 64
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0             # <= 0 → disabled
    # Reproducibility root: on a plain (non-speculative) engine, identical
    # (prompt, seed, params, sampling) yields an identical stream
    # regardless of batch composition or scheduling — every sampled draw
    # is keyed by fold_in(seed key, token position). Speculative engines
    # guarantee greedy exactness and distributional reproducibility only:
    # the spec path draws differently from the plain path, and which path
    # a block takes can depend on batchmates (engine._dispatch_step).
    # Seeds are taken mod 2**64. None → a fresh root from the engine's
    # seed RNG.
    seed: Optional[int] = None
    # Absolute monotonic deadline stamped by the gateway from the RPC's
    # time_remaining() (None → no deadline). The engine drops expired
    # requests at dequeue (before prefill) and at decode-block
    # boundaries, failing them with a "deadline exceeded" error the
    # gateway maps to DEADLINE_EXCEEDED — expired work never reaches the
    # device.
    deadline: Optional[float] = None
    out: queue.Queue = field(default_factory=queue.Queue)
    cancelled: threading.Event = field(default_factory=threading.Event)
    timings: RequestTimings = field(default_factory=RequestTimings)
    # Root span attached by the gateway; None means the request is
    # untraced and the engine records no spans for it (bench and embedder
    # paths pay zero tracing cost). The engine appends queue_wait /
    # prefill / decode children; decode gets per-block children as blocks
    # are processed.
    trace: Optional["Span"] = None
    # Disaggregated tiers (ISSUE 13). `prefill_only`: run prefill, then
    # instead of decoding emit ("handoff", KVHandoffState) + ("done", …)
    # — the prefill-tier worker's mode. `resume_state`: a deserialized
    # KVHandoffState; the engine skips tokenize/prefill entirely, maps
    # the shipped pages into its own pool, and resumes decode at
    # seq_len = prompt_len + 1 — the decode-tier worker's mode. Both
    # default off; every non-disaggregated path never sets them.
    prefill_only: bool = False
    resume_state: Optional[object] = None


@dataclass
class _Slot:
    request: GenRequest
    pages: list[int]
    generated: int = 0
    position_cap: int = 0      # absolute position limit for this request
    # Chunked-prefill state: prompts longer than the largest bucket hold
    # their ids here and prefill one chunk per engine-loop iteration;
    # `pending is None` ⇔ the slot is decoding (or short-prompt prefilled).
    pending: Optional[np.ndarray] = None
    filled: int = 0            # prompt positions already prefilled
    # The slot's page table stays HERE until activation: the decode batch's
    # inactive lanes write garbage KV at position 0 through whatever table
    # the device holds, so a mid-prefill slot's real table must never reach
    # the device mirrors — only the reserved garbage page 0 (see
    # _upload_slot_state) — or decode blocks would corrupt the prompt's
    # position-0 KV between prefill chunks.
    table: Optional[np.ndarray] = None
    # Async prefill: the dispatched-but-unread sampled token (a device
    # array, slot's row at `token_row`) — the lane was already activated
    # on device by the merge dispatch; this handle exists only so the host
    # can emit the first token to the client once the async D2H copy
    # lands (_resolve_prefills). The host never blocks the loop on it.
    token_dev: Optional[jax.Array] = None
    token_row: int = 0
    merged: bool = False       # device lane activated (merge dispatched)
    seed_row: Optional[np.ndarray] = None   # [2] int32 RNG root halves
    prompt_len: int = 0
    prompt_ids: Optional[np.ndarray] = None  # for prefix-cache insertion
    # Host-KV page faults (ISSUE 15): [(key, host_page, chain_index)]
    # for prefix pages whose contents sit in the host tier. While set,
    # the slot is FAULTING — it joins no prefill/ragged dispatch — until
    # the engine loop's restore frontier issues its scatter
    # (_issue_restores), after which the donation chain orders the page
    # contents ahead of every dispatch that could read them. The slot
    # owns the listed host pages (detached from the cache at admission);
    # _finish re-adopts them if the slot dies before its restore.
    restore_pages: Optional[list] = None
    # Open "decode" span for traced requests (None otherwise): opened when
    # the first token resolves, closed by _finish; per-block children are
    # appended by _process_step/_process_spec.
    decode_span: Optional["Span"] = None
    # End of this slot's previous emit window (first-token resolve or the
    # last processed block) — the inter-token-latency clock.
    last_emit: float = 0.0


class _RRCursor:
    """Starved-first round-robin cursor over a modulo-N slot space —
    the ONE shared implementation of the `_chunk_rr`/`_restore_rr`
    discipline (schedlint SL002 checks this class instead of divergent
    open-coded copies). A frontier sweep iterates :meth:`scan`; a
    completed sweep calls :meth:`advance` so index order alone never
    privileges a slot; an early exit (budget spent, stream width full)
    calls :meth:`reanchor` ON the first skipped slot so it scans first
    next iteration instead of losing its turn to the advance."""

    __slots__ = ("pos",)

    def __init__(self) -> None:
        self.pos = 0

    def scan(self, n: int):
        """Slot indices anchored at the cursor: (pos+0)%n … (pos+n-1)%n.
        The anchor is captured at the call, so a reanchor() fired by an
        early exit mid-sweep cannot perturb the remaining order."""
        base = self.pos
        return ((base + off) % n for off in range(n))

    def reanchor(self, i: int) -> None:
        """Early exit: the starved slot goes first next sweep."""
        self.pos = i

    def advance(self, n: int) -> None:
        """Completed sweep: rotate the anchor past the slot that led."""
        self.pos = (self.pos + 1) % n


def _prefill_fn(
    params, cfg: ModelConfig, paged: PagedKV,
    tokens, start, last_rel, page_table, seeds, temperature, top_p, top_k,
    *, greedy: bool, candidates: int = 0, mesh=None,
):
    """Prefill N windows (tokens [N, T]) at absolute positions
    start[i]..start[i]+T-1 and sample from each hidden state at relative
    index last_rel[i]. One compiled shape serves every path: single
    admissions (N=1), burst admissions batched by bucket (N up to the
    group cap), and long prompts chunk through it N=1 at a time (the
    engine discards the sampled token for all but the final chunk).
    Padded tail positions write KV that is either masked (position > any
    query), overwritten by later decode steps, or lands on the reserved
    garbage page — never read; padded GROUP rows point their whole table
    at the garbage page.

    `greedy` is a static variant selector: an all-greedy group takes a
    pure-argmax tail (no full-vocab sort, no RNG use) — at 128k-256k vocab
    the top-p sort is a real per-step cost, and greedy is the north-star
    benchmark mode. Sampled rows draw with fold_in(seed key, sampled
    token's position) — per-request streams, batch-independent.
    """
    N, T = tokens.shape
    positions = start[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    hidden, paged = forward_paged(
        params, cfg, tokens, positions, paged, page_table, mesh=mesh
    )
    last = hidden[jnp.arange(N), last_rel]                 # [N, H]
    logits = unembed(params, cfg, last)                    # [N, V]
    token = sample_tail(
        logits, seeds, start + last_rel + 1, temperature, top_p, top_k,
        greedy, candidates,
    )
    return token, paged


def _decode_fn(
    params, cfg: ModelConfig, paged: PagedKV,
    last_tokens, seq_lens, page_tables, active, caps, seeds, temperature,
    top_p, top_k,
    *, greedy: bool, steps: int, eos_id: int, candidates: int = 0, mesh=None,
):
    """`steps` decode steps for the whole slot batch in ONE dispatch.

    A lax.scan drives the block: each sub-step writes KV for the current
    tokens at position seq_lens-1, samples the next token for live slots,
    and advances device-resident state. Live-ness mirrors the host's
    _maybe_finish ON DEVICE — a slot stops at EOS or when seq_lens reaches
    its position cap — so a finished stream neither advances nor pollutes
    its own cache beyond its final position (its lane keeps computing
    masked garbage that the host discards via the returned emit masks).

    Blocking the decode this way amortizes per-dispatch host overhead
    (Python + transfer latency; dominant when the chip sits behind a
    network tunnel) over `steps` tokens. The host uploads nothing per block
    and downloads ONE packed [steps, B] int32 array (token id where the
    sub-step emitted for that lane, -1 where it did not) — a single D2H
    transfer per block instead of separate token/mask reads.

    `greedy` (static) selects the argmax-only tail when every active slot
    is greedy, skipping sample_dynamic's [B, vocab] sort entirely.
    """

    def one(carry, _):
        last, seq, act, paged = carry
        positions = jnp.maximum(seq - 1, 0)[:, None]       # [B, 1]
        hidden, paged = forward_paged(
            params, cfg, last[:, None], positions, paged, page_tables,
            mesh=mesh,
        )
        logits = unembed(params, cfg, hidden[:, 0])        # [B, V]
        # The new token lands at index seq → that position keys its draw.
        tokens = sample_tail(
            logits, seeds, seq, temperature, top_p, top_k, greedy, candidates
        )
        tokens = jnp.where(act, tokens, 0)
        new_seq = seq + act.astype(jnp.int32)
        cont = act & (tokens != eos_id) & (new_seq < caps)
        packed = jnp.where(act, tokens, -1)
        return (tokens, new_seq, cont, paged), packed

    carry = (last_tokens, seq_lens, active, paged)
    (last, seq, act, paged), packed = jax.lax.scan(
        one, carry, None, length=steps
    )
    return packed, last, seq, act, paged


def _ragged_fn(
    params, cfg: ModelConfig, paged,
    last_tokens, seq_lens, page_tables, active, caps, seeds, temperature,
    top_p, top_k,
    pre_tokens, pre_pos, pre_table_idx, pre_tables,
    pre_range_start, pre_range_len, pre_range_kv, pre_range_table,
    pre_sample_idx, pre_sample_pos, pre_seeds, pre_temp, pre_top_p,
    pre_top_k,
    *, greedy: bool, eos_id: int, candidates: int = 0, mesh=None,
):
    """ONE ragged dispatch for mixed prefill+decode (ISSUE 12): every
    decode lane advances exactly one step AND up to `W` prefill tokens
    (admission prompts and chunk advancement, appended as token ranges
    by the host-side batch builder) prefill — through a single flat
    [B+W]-token forward (models/transformer.forward_ragged; ragged
    Pallas kernel on TPU, per-token gather fallback elsewhere).

    Layout: flat rows [0, B) are the decode lanes' single tokens (row b
    = slot b, position seq_lens[b]-1 — inactive lanes compute masked
    garbage through their garbage tables exactly as in _decode_fn);
    rows [B, B+W) are the prefill stream. `pre_table_idx[w]` maps each
    prefill row to its owning slot's HOST-side page table in
    `pre_tables` [B, P] (index B → an all-garbage row: padding tokens
    write to and attend over the reserved page 0, like inactive lanes).
    `pre_range_*` [B] describe the appended ranges for the ragged
    kernel's per-sequence metadata (ascending flat offsets; unused rows
    are empty ranges past the stream end).

    Sampling mirrors the bucketed paths EXACTLY (bit-identity):
    - decode rows sample with position key seq_lens (the position the
      new token lands at), advance seq/active with the same EOS/cap
      stopping as _decode_fn, and return the same packed [1, B] emit
      row a steps=1 decode block would — so the result rides the
      lookahead pipeline's _process_step unchanged;
    - per slot b, `pre_sample_idx[b]` names the prefill-stream row
      whose hidden state samples that slot's FIRST token at position
      key `pre_sample_pos[b]` (= prompt_len, matching _prefill_fn's
      start + last_rel + 1); the host merges only final-chunk slots,
      the other rows' draws are discarded.
    """
    B = last_tokens.shape[0]
    W = pre_tokens.shape[0]
    dec_pos = jnp.maximum(seq_lens - 1, 0)
    tokens = jnp.concatenate([last_tokens, pre_tokens])          # [B+W]
    positions = jnp.concatenate([dec_pos, pre_pos])
    garbage_row = jnp.zeros_like(pre_tables[:1])
    tables_ext = jnp.concatenate([pre_tables, garbage_row])      # [B+1, P]
    token_tables = jnp.concatenate(
        [page_tables, tables_ext[pre_table_idx]]
    )                                                            # [B+W, P]
    # Ragged sequence metadata (kernel path): B decode singles then the
    # prefill ranges, starts ascending (unused ranges sit past the end).
    rng_starts = jnp.concatenate([
        jnp.arange(B, dtype=jnp.int32), B + pre_range_start,
    ])
    rng_lens = jnp.concatenate([
        jnp.ones((B,), jnp.int32), pre_range_len,
    ])
    rng_kv = jnp.concatenate([
        jnp.maximum(seq_lens, 1), pre_range_kv,
    ])
    seq_tables = jnp.concatenate(
        [page_tables, tables_ext[pre_range_table]]
    )                                                            # [2B, P]

    hidden, paged = forward_ragged(
        params, cfg, tokens, positions, paged, token_tables,
        rng_starts, rng_lens, rng_kv, seq_tables, mesh=mesh,
    )

    # Decode rows: one _decode_fn step, verbatim semantics.
    logits = unembed(params, cfg, hidden[:B])                    # [B, V]
    dec = sample_tail(
        logits, seeds, seq_lens, temperature, top_p, top_k, greedy,
        candidates,
    )
    dec = jnp.where(active, dec, 0)
    new_seq = seq_lens + active.astype(jnp.int32)
    cont = active & (dec != eos_id) & (new_seq < caps)
    packed = jnp.where(active, dec, -1)[None, :]                 # [1, B]

    # Prefill first tokens: one row per slot (garbage for slots without
    # a final chunk this dispatch — the host never reads those).
    rows = hidden[B + jnp.clip(pre_sample_idx, 0, W - 1)]        # [B, H]
    first = sample_tail(
        unembed(params, cfg, rows), pre_seeds, pre_sample_pos,
        pre_temp, pre_top_p, pre_top_k, greedy, candidates,
    )
    return packed, dec, new_seq, cont, first, paged


def _merge_lane_fn(
    last_tokens, seq_lens, page_tables, active, caps, temperature, top_p,
    top_k, seeds, tokens_vec, row, slot, seq_len, cap, temp, tp, tk,
    table_row, seed_row, accept_ewma=None, gamma_lane=None,
    gamma_reset=None,
    *, eos_id: int, spec: bool = False,
):
    """Activate ONE decode lane entirely on device: splice the prefill's
    sampled token (still a device array — no host sync) and the slot's
    geometry into the device-resident decode state. Dispatched right after
    the prefill that produced `tokens_vec`, so the lane joins the next
    decode block without the host ever waiting on the device — the
    mechanism that lets admissions ride the lookahead pipeline instead of
    flushing it.

    The lane is born live only if its first token isn't EOS and the
    position budget allows generation (the same conditions the host's
    _maybe_finish applies when it later emits the first token).

    Speculative engines (`spec=True`) also carry the per-lane gamma dial
    (ISSUE 19) in the donated slot state: a fresh lane starts with an
    optimistic acceptance EWMA of 1.0 and its dial at `gamma_reset`
    (= gamma_max), exactly like the old engine-global ladder's boot
    state — the dial is per-REQUEST evidence, so it must not inherit the
    previous occupant's history."""
    token = tokens_vec.reshape(-1)[row]   # [N] group/prefill token vector
    live = (token != eos_id) & (seq_len < cap)
    out = (
        last_tokens.at[slot].set(token),
        seq_lens.at[slot].set(seq_len),
        page_tables.at[slot].set(table_row),
        active.at[slot].set(live),
        caps.at[slot].set(cap),
        temperature.at[slot].set(temp),
        top_p.at[slot].set(tp),
        top_k.at[slot].set(tk),
        seeds.at[slot].set(seed_row),
    )
    if spec:
        out += (
            accept_ewma.at[slot].set(1.0),
            gamma_lane.at[slot].set(gamma_reset),
        )
    return out


def _retire_lane_fn(last_tokens, seq_lens, page_tables, active, caps, slot):
    """Deactivate ONE lane on device and point its page table at the
    reserved garbage page. Dispatched when the host retires a slot
    (EOS/cap/cancel): the lane's pages go back to the allocator, so later
    blocks must stop writing through the stale table — in-flight blocks
    dispatched before this merge still carry it, which is safe because
    their writes are ordered (pool chaining) before any reuse of the pages
    and masked by absolute position until overwritten."""
    return (
        last_tokens.at[slot].set(0),
        seq_lens.at[slot].set(0),
        page_tables.at[slot].set(jnp.zeros_like(page_tables[0])),
        active.at[slot].set(False),
        caps.at[slot].set(0),
    )


def _kv_restore_fn(paged: PagedKV, idx, k, v):
    """Scatter handed-off page contents into the pool at the target's
    own page ids (ISSUE 13 decode-side restore). `idx`/`k`/`v` are
    padded to a FIXED width (pages_per_seq) so one compiled executable
    serves every handoff size — pad rows target the reserved garbage
    page 0, whose contents are never read (inactive lanes write it
    constantly anyway). The pool is donated: the restore is an in-place
    page write ordered after every in-flight dispatch through the
    donation chain, exactly like a prefill's KV writes."""
    return paged.replace(
        k=paged.k.at[:, idx].set(k), v=paged.v.at[:, idx].set(v)
    )


def _kv_restore_quant_fn(paged: PagedKV, idx, k, v, ks, vs):
    """Int8 pair-form variant of `_kv_restore_fn`: the value pools and
    their bf16 scale pools restore together, byte-for-byte."""
    return paged.replace(
        k=paged.k.at[:, idx].set(k), v=paged.v.at[:, idx].set(v),
        ks=paged.ks.at[:, idx].set(ks), vs=paged.vs.at[:, idx].set(vs),
    )


def _kv_gather_fn(paged: PagedKV, idx):
    """Gather page contents out of the pool for host-tier eviction
    (ISSUE 15) — the read half of the fixed-width gather/scatter pair
    whose write half is `_kv_restore_fn`. `idx` is padded to
    pages_per_seq (pad rows read the reserved garbage page 0 and are
    discarded host-side), so ONE compiled executable serves every spill
    batch — the GL001 discipline. Read-only: the pool is NOT donated
    (the gathered copy leaves, the pool stays), so in-flight decode
    blocks are unaffected and the copy observes the donation-chain
    ordering of every dispatch issued before it."""
    return jnp.take(paged.k, idx, axis=1), jnp.take(paged.v, idx, axis=1)


def _kv_gather_quant_fn(paged: PagedKV, idx):
    """Int8 pair-form variant of `_kv_gather_fn`: values and their bf16
    scale pools gather together, byte-for-byte."""
    return (
        jnp.take(paged.k, idx, axis=1), jnp.take(paged.v, idx, axis=1),
        jnp.take(paged.ks, idx, axis=1), jnp.take(paged.vs, idx, axis=1),
    )


def ragged_zero_operands(B: int, W: int, P: int) -> tuple:
    """The 14 positional prefill operands of `_ragged_fn`, all-zero /
    all-garbage (no ranges, no sample rows) — the SINGLE builder for
    every synthetic ragged call (engine warmup, graphlint's donation
    audit and jaxpr trace). The operands are positionally typed int32/
    float32 arrays, so hand-built copies that drift from the signature
    would trace clean and compute garbage; build them here only."""
    return (
        np.zeros((W,), np.int32),            # pre_tokens
        np.zeros((W,), np.int32),            # pre_pos
        np.full((W,), B, np.int32),          # pre_table_idx → garbage row
        np.zeros((B, P), np.int32),          # pre_tables
        np.full((B,), W, np.int32),          # pre_range_start → past end
        np.zeros((B,), np.int32),            # pre_range_len
        np.zeros((B,), np.int32),            # pre_range_kv
        np.full((B,), B, np.int32),          # pre_range_table → garbage
        np.zeros((B,), np.int32),            # pre_sample_idx
        np.zeros((B,), np.int32),            # pre_sample_pos
        np.zeros((B, 2), np.int32),          # pre_seeds
        np.zeros((B,), np.float32),          # pre_temp
        np.ones((B,), np.float32),           # pre_top_p
        np.zeros((B,), np.int32),            # pre_top_k
    )


_MAX_PREFILL_GROUP = 8   # burst admissions batched per prefill dispatch

# Router weight of a HOST-resident cached prefix token relative to a
# device-resident one (prefix_warmth): warm — no recompute — but a
# restore scatter away from usable, so half credit keeps the router
# preferring truly resident replicas at equal warmth.
_HOST_WARMTH_WEIGHT = 0.5


class _InflightBlock(NamedTuple):
    """One dispatched-but-unprocessed decode block (or spec round) in the
    lookahead pipeline. A NamedTuple so legacy (kind, data, reqs) tuples
    still unpack (tests build minimal blocks by hand); `seq` is the
    block's dispatch sequence number — at process time,
    engine._dispatch_seq - seq is the OBSERVED lookahead (how many newer
    blocks were dispatched before this one's readback), the number the
    loop-trace regression test pins. `gap_ms` (the host gap preceding
    this dispatch) and `live` (slot indices active at dispatch) carry
    the device-time attribution inputs to process time (ISSUE 10)."""

    kind: str
    data: object
    reqs: list
    seq: int = 0
    gap_ms: float = 0.0
    live: tuple = ()


class EngineDeadError(RuntimeError):
    """The engine (or pool) cannot take work. `retry_after_ms`, when the
    raiser can estimate it (a replica pool with a supervised restart in
    flight), is the recovery hint the gateway ships as the
    `retry-after-ms` trailer on the resulting UNAVAILABLE — without it,
    well-behaved clients hammer a recovering tier at their own backoff
    schedule instead of the server's."""

    def __init__(self, message: str, retry_after_ms: Optional[int] = None):
        super().__init__(message)
        self.retry_after_ms = retry_after_ms


class EngineOverloadedError(RuntimeError):
    """Admission shed this request (queue bound or estimated-delay
    check). `retry_after_ms` is the engine's best guess at when a retry
    could be admitted — the gateway ships it as trailing metadata."""

    def __init__(self, message: str, retry_after_ms: int = 100):
        super().__init__(message)
        self.retry_after_ms = retry_after_ms


# Error-message prefix contract with the gateway: engine failures that
# begin with this map to gRPC DEADLINE_EXCEEDED (tpu_service).
DEADLINE_MSG = "deadline exceeded"


class InferenceEngine:
    def __init__(
        self,
        config: EngineConfig,
        params: Optional[dict] = None,
        health=None,
        logger=None,
        seed: int = 0,
        draft_params: Optional[dict] = None,
    ):
        config.validate()
        self.config = config
        # Constructor inputs AS PASSED (before checkpoint load / quantize /
        # shard mutate the local): the supervisor's default restart factory
        # replays them so a restarted engine is built from the same
        # weights/seed, not a fresh random init (None → the checkpoint or
        # random-init path reruns, which is already faithful). Pinning the
        # raw params tree costs its host memory for the engine's lifetime,
        # so it happens only when supervision can actually consume it.
        self._ctor_args = {
            "params": params if config.supervise else None,
            "seed": seed,
            "draft_params": draft_params if config.supervise else None,
        }
        # Whether weights came from the caller (vs checkpoint/seed
        # derivation) — one input to the durable-KV params fingerprint.
        self._params_explicit = params is not None
        self.model_cfg = get_config(config.model)
        self.tokenizer = load_tokenizer(config.tokenizer)
        self.metrics = EngineMetrics()
        self.health = health
        self.logger = logger
        # Fault injection (polykey_tpu/faults.py): None unless
        # POLYKEY_FAULTS is set, so every injection point below is one
        # attribute load + `is None` — nothing on the hot path when off.
        self._faults = get_injector()
        # Identity within a replica pool (engine/replica_pool.py): fault
        # targeting (":replica=N") and per-replica metric labels key on
        # it. A standalone engine is replica 0.
        self.replica_id = config.replica
        # Tier identity within a disaggregated worker (engine/worker.py):
        # scopes ":tier=prefill|decode" fault targeting. None everywhere
        # else, so tier-targeted faults can never fire in-process.
        self._tier = config.disagg_tier or None
        self._dtype = jnp.dtype(config.dtype)

        # --- Serving mesh: tp shards heads/hidden (Megatron specs,
        # parallel/sharding.py), dp shards the decode-slot batch, ep shards
        # MoE expert weights (token dispatch rides all-to-all over ep —
        # measurement config 4). tp=dp=ep=1 degenerates to a single-device
        # mesh with identical code paths (specs over size-1 axes are
        # no-ops, so there is no unsharded special case to keep in sync).
        n_devices = (
            config.tp * config.dp * config.ep * config.sp * config.pp
        ) * config.num_slices
        devices = jax.devices()
        if n_devices > len(devices):
            raise ValueError(
                f"tp={config.tp} x dp={config.dp} x ep={config.ep} x "
                f"sp={config.sp} x pp={config.pp} x "
                f"slices={config.num_slices} needs {n_devices} "
                f"devices, have {len(devices)}"
            )
        if self.model_cfg.num_kv_heads % config.tp != 0:
            raise ValueError(
                f"tp={config.tp} must divide num_kv_heads="
                f"{self.model_cfg.num_kv_heads} ({self.model_cfg.name})"
            )
        # dp is per-slice; the mesh's dp axis extent (what slots batch
        # over) is num_slices × dp.
        total_dp = config.dp * config.num_slices
        if config.max_decode_slots % total_dp != 0:
            raise ValueError(
                f"dp={config.dp} x num_slices={config.num_slices} must "
                f"divide max_decode_slots={config.max_decode_slots}"
            )
        if config.ep > 1:
            if not self.model_cfg.is_moe:
                raise ValueError(
                    f"ep={config.ep} requires an MoE model "
                    f"({self.model_cfg.name} has no experts)"
                )
            if self.model_cfg.num_experts % config.ep != 0:
                raise ValueError(
                    f"ep={config.ep} must divide num_experts="
                    f"{self.model_cfg.num_experts}"
                )
        if self.model_cfg.num_layers % config.pp != 0:
            raise ValueError(
                f"pp={config.pp} must divide num_layers="
                f"{self.model_cfg.num_layers}"
            )
        mesh_config = MeshConfig(
            dp=config.dp, pp=config.pp, sp=config.sp, ep=config.ep,
            tp=config.tp,
        )
        if config.num_slices > 1:
            # Hybrid DCN mesh: dp (the only axis whose collectives
            # amortize DCN latency) spans the slices; everything else
            # stays inside one ICI domain.
            from ..parallel.distributed import create_hybrid_mesh

            self.mesh = create_hybrid_mesh(
                mesh_config, config.num_slices, devices[:n_devices]
            )
        else:
            self.mesh = create_mesh(mesh_config, devices=devices[:n_devices])
        from jax.sharding import NamedSharding, PartitionSpec

        # int8 KV (config.kv_dtype): quantized pools + scale pools. The
        # pool sharding then becomes a PagedKV-shaped pytree (the scale
        # pools are 4-D — one broadcast NamedSharding can't serve both).
        self._kv_quantized = config.kv_dtype == "int8"
        data_sh = paged_kv_sharding(self.mesh)
        if self._kv_quantized:
            from ..parallel.sharding import paged_kv_scale_sharding

            scale_sh = paged_kv_scale_sharding(self.mesh)
            self._pool_sharding = PagedKV(
                k=data_sh, v=data_sh, ks=scale_sh, vs=scale_sh
            )
        else:
            self._pool_sharding = PagedKV(k=data_sh, v=data_sh)
        self._repl = NamedSharding(self.mesh, PartitionSpec())
        # Sequence-parallel prefill: the window's token axis shards over
        # sp, spreading prefill compute across chips; the page pools are
        # sp-replicated, so GSPMD exchanges the KV writes (sp=1 → a no-op
        # spec, same code path).
        self._prefill_tok = NamedSharding(self.mesh, PartitionSpec(None, "sp"))
        self._dp_vec = NamedSharding(self.mesh, PartitionSpec("dp"))
        self._dp_mat = NamedSharding(self.mesh, PartitionSpec("dp", None))
        # Pinned output shardings keep the donated pool's layout stable
        # across steps (donation requires matching input/output shardings).
        self._jit_prefill = jax.jit(
            _prefill_fn,
            static_argnames=("cfg", "greedy", "candidates", "mesh"),
            donate_argnames=("paged",),
            out_shardings=(self._repl, self._pool_sharding),
        )
        self._dp_steps = NamedSharding(self.mesh, PartitionSpec(None, "dp"))
        # Double-buffered slot state: the three per-step-advancing vectors
        # (last_tokens / seq_lens / active) are donated alongside the pool,
        # so the decode chain updates them in place instead of allocating a
        # fresh generation per block. With lookahead, the runtime keeps the
        # in-flight block's buffers alive until it completes while the next
        # dispatch writes the other generation — two device-resident copies
        # that never alias (GL002 audits the aliasing). Read-only geometry
        # (page_tables / caps / sampling params / seeds) is NOT donated:
        # it has no corresponding output to alias into.
        self._jit_decode = jax.jit(
            _decode_fn,
            static_argnames=(
                "cfg", "greedy", "steps", "eos_id", "candidates", "mesh",
            ),
            donate_argnames=("paged", "last_tokens", "seq_lens", "active"),
            out_shardings=(
                self._dp_steps, self._dp_vec, self._dp_vec,
                self._dp_vec, self._pool_sharding,
            ),
        )
        # Lane merges: tiny functional updates of the device-resident decode
        # state, chained between blocks so slot transitions never flush the
        # lookahead pipeline (out shardings must match the decode inputs so
        # the chain keeps stable layouts).
        lane_out = (
            self._dp_vec, self._dp_vec, self._dp_mat, self._dp_vec,
            self._dp_vec, self._dp_vec, self._dp_vec, self._dp_vec,
            self._dp_mat,
        )
        # Speculative engines carry two extra donated-state vectors (the
        # per-lane acceptance EWMA + gamma dial, ISSUE 19) that the merge
        # resets per admission.
        merge_out = lane_out + (
            (self._dp_vec, self._dp_vec)
            if config.draft_model is not None else ()
        )
        self._jit_merge = jax.jit(
            _merge_lane_fn, static_argnames=("eos_id", "spec"),
            out_shardings=merge_out,
        )
        self._jit_retire = jax.jit(
            _retire_lane_fn, out_shardings=lane_out[:5],
        )
        # KV handoff restore (ISSUE 13): scatter shipped pages into this
        # pool at the receiving slot's page ids. Donates the pool like
        # every other pool-touching dispatch; the fixed padded width
        # (pages_per_seq) keeps it ONE executable per engine.
        self._jit_kv_restore = jax.jit(
            _kv_restore_quant_fn if self._kv_quantized else _kv_restore_fn,
            donate_argnames=("paged",),
            out_shardings=self._pool_sharding,
        )
        # Host-tier eviction gather (ISSUE 15): the read half of the
        # gather/scatter pair (restore above is the write half). Same
        # fixed width (pages_per_seq), one executable; outputs land
        # replicated so the host copy is a straight np.asarray.
        n_gather_out = 4 if self._kv_quantized else 2
        self._jit_kv_gather = jax.jit(
            _kv_gather_quant_fn if self._kv_quantized else _kv_gather_fn,
            out_shardings=(self._repl,) * n_gather_out,
        )
        # Per-request RNG roots for seedless requests (GenRequest.seed
        # None): drawn once per admission from the engine seed.
        self._seed_rng = np.random.default_rng(seed + 3)

        if params is None:
            if config.checkpoint_path:
                from ..models.loader import load_checkpoint

                params = load_checkpoint(
                    config.checkpoint_path, self.model_cfg, self._dtype
                )
            else:
                # Random init — the dev/bench path.
                params = init_params(
                    jax.random.PRNGKey(seed), self.model_cfg, self._dtype
                )
        if config.quantize:
            # Int8 weight-only: halves weight HBM (the single-chip 8B
            # enabler — v5e has 16 GiB; see models/quant.py).
            from ..models.quant import quantize_params

            params = quantize_params(
                params, self.model_cfg, bits=config.quantize_bits
            )
        self.params = shard_params(params, self.model_cfg, self.mesh)

        B, P = config.max_decode_slots, config.pages_per_seq
        pool_fp_dtype = (
            jnp.dtype(config.kv_dtype)
            if config.kv_dtype in ("bfloat16", "float32") else self._dtype
        )
        kv_q = jnp.int8 if self._kv_quantized else None
        self.paged = jax.device_put(
            init_paged_kv(
                self.model_cfg, config.num_pages, config.page_size,
                pool_fp_dtype, kv_dtype=kv_q,
            ),
            self._pool_sharding,
        )
        self.allocator = BlockAllocator(config.num_pages)
        # --- Host-memory KV tier (ISSUE 15): a second page pool in host
        # RAM for COLD pages (prefix-cache entries of finished sticky
        # sessions, long-context middles). 0 bytes → no pool, no store,
        # every existing path byte-identical.
        self._host_kv = None
        self._kv_state = None
        self._kv_reloaded_pages = 0
        if config.host_kv_bytes > 0:
            from .kv_cache import HostKVPool, host_kv_page_bytes

            page_b = host_kv_page_bytes(
                self.model_cfg, config.page_size, pool_fp_dtype, kv_q
            )
            capacity = config.host_kv_bytes // max(1, page_b)
            if capacity < 1:
                raise ValueError(
                    f"POLYKEY_HOST_KV_BYTES={config.host_kv_bytes} is "
                    f"smaller than one KV page ({page_b} bytes for "
                    f"{self.model_cfg.name} at page_size "
                    f"{config.page_size})"
                )
            self._host_kv = HostKVPool(
                self.model_cfg, capacity, config.page_size,
                pool_fp_dtype, self._kv_quantized,
            )
        # Resident working set: _finish spills cold pages whenever a
        # retirement leaves fewer free device pages than this floor.
        # Live attribute (not a frozen-config read): the autopilot's
        # set_resident_floor actuation must land mid-run.
        self._resident_low = (
            config.host_kv_resident_pages or config.num_pages // 8
        )
        # Per-iteration restore budget. Mirrors the frozen config field
        # into a live attribute so _issue_restores reads THIS every
        # iteration — a mid-run set_kv_restore_slots actuation takes
        # effect on the next loop pass instead of being silently
        # ignored (the knob-application audit, ISSUE 18).
        # Clamped like set_kv_restore_slots: the restore frontier's
        # progress floor (schedlint SL001) assumes a budget of at least
        # one scatter per iteration.
        self._restore_slots = max(1, config.host_kv_restore_slots)
        # Restore-frontier round-robin cursor (the shared starved-first
        # discipline for page faults).
        self._restore_rr = _RRCursor()
        # Durable-store gc cadence: gc() lists and parses the whole
        # state dir — amortize it over batches instead of paying a
        # directory scan per spill on the engine thread.
        self._kv_gc_countdown = 0
        self._prefix = None
        if config.prefix_cache:
            from .prefix_cache import PrefixCache

            self._prefix = PrefixCache(
                self.allocator, config.page_size,
                config.prefix_cache_pages or config.num_pages // 2,
                host_pool=self._host_kv,
            )
        if self._host_kv is not None and config.kv_state_dir:
            # Restart-durable prefix cache: reload spilled pages
            # persisted by a previous incarnation (same weights — the
            # params_key gate) into the host tier, so the first sticky
            # turn after a supervisor restart faults its prefix back in
            # instead of recomputing it cold.
            from .prefix_cache import PrefixStateStore

            self._kv_state = PrefixStateStore(
                config.kv_state_dir, self.model_cfg.name, config.page_size,
                params_key=self._params_fingerprint(seed),
                quantized=self._kv_quantized, logger=logger,
            )
            self._kv_reloaded_pages = self._kv_state.load_into(
                self._prefix, self._host_kv,
                expect_shape=(
                    self.model_cfg.num_layers, 0, config.page_size,
                    self.model_cfg.num_kv_heads, self.model_cfg.head_dim,
                ),
            )

        self._chunk = config.prefill_chunk or max(config.prefill_buckets)
        # Interleaved-prefill budget (config.prefill_budget; 0 → auto):
        # prefill tokens allowed per loop iteration while decode lanes
        # are live. Floored at one chunk so a budget below the dispatch
        # granularity still makes progress (the knob bounds stall length,
        # it must never deadlock a long prompt).
        self._prefill_budget = max(
            config.prefill_budget or 2 * self._chunk, self._chunk
        )
        # Round-robin cursor over slots with pending chunked prefill —
        # budgeted chunk advancement must not starve the highest-index
        # pending slot when the budget covers fewer chunks than slots.
        self._chunk_rr = _RRCursor()
        self._block_steps = config.decode_block_steps
        # Load-adaptive block size (config.adaptive_block): the solo block
        # is a distinct static `steps` value, so it gets its own compile —
        # warmup covers it alongside the full block.
        self._solo_steps = (
            max(1, config.decode_block_steps // 8)
            if config.adaptive_block else config.decode_block_steps
        )
        self._last_dispatch_steps = 0    # observability (bench step_costs)

        # --- Ragged dispatch (ISSUE 12): admissions + chunk advancement
        # become token-range appends into ONE flat mixed prefill+decode
        # dispatch (_ragged_fn) whenever prefill work exists; pure-decode
        # iterations keep the K-step block path. POLYKEY_DISABLE_RAGGED
        # is the operational kill-switch (wins over config/env
        # enablement — the POLYKEY_DISABLE_PAGED_KERNEL pattern): a
        # ragged regression must be containable by falling back to the
        # bucketed executables without a config rollout.
        self._ragged = config.ragged_dispatch and os.environ.get(
            "POLYKEY_DISABLE_RAGGED", ""
        ).lower() not in ("1", "true")
        self._jit_ragged = None
        if self._ragged:
            # Static prefill-stream width: the per-iteration token
            # budget, floored at one chunk and padded so the full flat
            # stream (B + W) tiles the ragged kernel's token_tile. ONE
            # width ⇒ one resident executable per greedy variant — the
            # census collapse GL001 asserts.
            from ..ops.ragged_paged_attention_kernel import TOKEN_TILE

            W = max(self._prefill_budget, self._chunk)
            W += (-(B + W)) % TOKEN_TILE
            self._ragged_width = W
            self._jit_ragged = jax.jit(
                _ragged_fn,
                static_argnames=(
                    "cfg", "greedy", "eos_id", "candidates", "mesh",
                ),
                donate_argnames=(
                    "paged", "last_tokens", "seq_lens", "active",
                ),
                out_shardings=(
                    self._dp_steps, self._dp_vec, self._dp_vec,
                    self._dp_vec, self._repl, self._pool_sharding,
                ),
            )

        # --- Speculative decoding: draft model + its own page pool, same
        # page tables (position → (page, offset) is model-independent).
        self._spec = config.draft_model is not None
        # Adaptive gamma (VERDICT r2 #8, per-lane since ISSUE 19): each
        # LANE carries its own dial on a two-level ladder {max(1, γ/2), γ}
        # driven by a per-lane acceptance EWMA with hysteresis, updated
        # INSIDE the jitted round (spec_decode._accept_merge) — the dial
        # rides the donated slot state, so it costs no crossings. The
        # host-side `self._gamma` is now only the DISPATCH WIDTH: the
        # ladder rung covering the widest active lane dial (recomputed
        # from the packed round stats in _process_spec), clamped by the
        # autopilot's `_gamma_cap` (set_spec_gamma). Page/position SLACK
        # always reserves for _gamma_max, so a mid-stream dial increase
        # can never overflow a slot's pages. Each ladder rung is its own
        # compile; warmup covers both.
        self._gamma_max = config.spec_gamma if self._spec else 0
        self._gamma = self._gamma_max
        self._gamma_low = (
            max(1, config.spec_gamma // 2)
            if (self._spec and config.adaptive_gamma) else self._gamma_max
        )
        self._gamma_cap = self._gamma_max   # autopilot bound (rung-snapped)
        # Batch-aggregate acceptance EWMA, kept for observability/back-
        # compat (stats()["spec_accept_ewma"]); the per-lane EWMAs below
        # are what drive the dial.
        self._accept_ewma = 1.0          # optimistic start: full gamma
        if self._spec:
            from .spec_decode import (
                ragged_spec_fn,
                spec_decode_fn,
                spec_prefill_fn,
            )

            self.draft_cfg = get_config(config.draft_model)
            if self.draft_cfg.vocab_size != self.model_cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {self.draft_cfg.vocab_size} != target "
                    f"vocab {self.model_cfg.vocab_size}"
                )
            if self.draft_cfg.num_kv_heads % config.tp != 0:
                raise ValueError(
                    f"tp={config.tp} must divide draft num_kv_heads="
                    f"{self.draft_cfg.num_kv_heads}"
                )
            if self.draft_cfg.num_layers % config.pp != 0:
                raise ValueError(
                    f"pp={config.pp} must divide draft num_layers="
                    f"{self.draft_cfg.num_layers} (the draft's params and "
                    f"page pool shard the same pp axis)"
                )
            if draft_params is not None:
                # Caller-provided draft weights (benchmarks pass the target
                # tree itself to measure the acceptance-1.0 ceiling).
                d_params = draft_params
            elif config.draft_checkpoint_path:
                from ..models.loader import load_checkpoint

                d_params = load_checkpoint(
                    config.draft_checkpoint_path, self.draft_cfg, self._dtype
                )
            else:
                d_params = init_params(
                    jax.random.PRNGKey(seed + 2), self.draft_cfg, self._dtype
                )
            if config.quantize:
                # The engine-wide int8 knob covers the draft too — the
                # draft exists to save bandwidth, and an unquantized draft
                # could push the HBM budget the flag exists to protect.
                from ..models.quant import quantize_params

                d_params = quantize_params(
                    d_params, self.draft_cfg, bits=config.quantize_bits
                )
            self.draft_params = shard_params(d_params, self.draft_cfg, self.mesh)
            self.d_paged = jax.device_put(
                init_paged_kv(
                    self.draft_cfg, config.num_pages, config.page_size,
                    pool_fp_dtype, kv_dtype=kv_q,
                ),
                self._pool_sharding,
            )
            self._jit_spec_prefill = jax.jit(
                spec_prefill_fn,
                static_argnames=("t_cfg", "d_cfg", "greedy", "candidates",
                                 "mesh"),
                donate_argnames=("t_paged", "d_paged"),
                out_shardings=(
                    self._repl, self._pool_sharding, self._pool_sharding,
                ),
            )
            self._jit_spec_decode = jax.jit(
                spec_decode_fn,
                static_argnames=(
                    "t_cfg", "d_cfg", "gamma", "eos_id", "gamma_low",
                    "gamma_max", "candidates", "mesh",
                ),
                # Same double-buffered slot-state donation as the plain
                # decode block — spec rounds ride the identical pipeline.
                # The per-lane gamma dial (accept_ewma / gamma_lane,
                # ISSUE 19) donates alongside: it advances on device
                # every round like the rest of the slot state.
                donate_argnames=(
                    "t_paged", "d_paged",
                    "last_tokens", "seq_lens", "active",
                    "accept_ewma", "gamma_lane",
                ),
                out_shardings=(
                    self._dp_mat, self._dp_vec, self._dp_vec, self._dp_vec,
                    self._dp_vec, self._dp_vec,
                    self._pool_sharding, self._pool_sharding,
                ),
            )
            self._jit_ragged_spec = None
            if self._ragged:
                # Spec×ragged unification (ISSUE 19 tentpole b): gamma-
                # token verify windows ride the flat ragged stream as
                # ordinary per-sequence ranges, so ONE mixed dispatch
                # serves prefill chunks AND spec verify lanes. The flat
                # stream is B·(γ+1)+W tokens, so the tile-aligned prefill
                # width W is per-gamma (each ladder rung is its own
                # compile anyway).
                from ..ops.ragged_paged_attention_kernel import TOKEN_TILE

                W0 = max(self._prefill_budget, self._chunk)
                self._ragged_spec_width = {
                    g: W0 + (-(B * (g + 1) + W0)) % TOKEN_TILE
                    for g in sorted({self._gamma_low, self._gamma_max})
                }
                self._jit_ragged_spec = jax.jit(
                    ragged_spec_fn,
                    static_argnames=(
                        "t_cfg", "d_cfg", "gamma", "eos_id", "gamma_low",
                        "gamma_max", "greedy", "candidates", "mesh",
                    ),
                    donate_argnames=(
                        "t_paged", "d_paged",
                        "last_tokens", "seq_lens", "active",
                        "accept_ewma", "gamma_lane",
                    ),
                    out_shardings=(
                        self._dp_mat, self._dp_vec, self._dp_vec,
                        self._dp_vec, self._dp_vec, self._dp_vec,
                        self._repl,
                        self._pool_sharding, self._pool_sharding,
                    ),
                )

        # Host mirrors of per-slot device state (engine thread only). They
        # are the source of truth at slot transitions (admit/finish mark
        # `_dev_dirty` → re-upload); between transitions the decode state —
        # RNG key included — stays device-resident (`_dev`) and advances
        # on-device, so steady decode uploads nothing per block.
        self._page_tables = np.zeros((B, P), dtype=np.int32)
        self._seq_lens = np.zeros((B,), dtype=np.int32)
        self._last_tokens = np.zeros((B,), dtype=np.int32)
        self._active = np.zeros((B,), dtype=bool)
        self._caps = np.zeros((B,), dtype=np.int32)
        self._temperature = np.zeros((B,), dtype=np.float32)
        self._top_p = np.ones((B,), dtype=np.float32)
        self._top_k = np.zeros((B,), dtype=np.int32)
        self._seeds = np.zeros((B, 2), dtype=np.int32)
        # Per-lane gamma dial mirrors (spec engines, ISSUE 19): refreshed
        # from each processed round's packed stat columns — the DEVICE
        # copy is authoritative between slot transitions, exactly like
        # the other mirrors.
        self._lane_ewma = np.ones((B,), dtype=np.float32)
        self._lane_gamma = np.full(
            (B,), max(self._gamma_max, 1), dtype=np.int32
        )
        self._slots: list[Optional[_Slot]] = [None] * B
        self._dev: dict = {}
        self._dev_dirty = True

        self._submit: queue.Queue[GenRequest] = queue.Queue()
        # Lookahead pipeline: dispatched-but-unprocessed decode blocks,
        # oldest first (_InflightBlock records). While dispatching, up to
        # _depth_target - 1 blocks stay queued ACROSS iterations — depth
        # counts device-resident slot-state generations including the
        # block just dispatched, so depth 2 = double-buffered overlap
        # (dispatch N+1 before reading N) and depth 1 = synchronous
        # dispatch-then-read, exactly. POLYKEY_DISPATCH_LOOKAHEAD
        # overrides the config depth regardless of how the config was
        # built (serving env, bench, tests) — the operator knob for
        # host-bound decode (DEPLOY.md runbook).
        from collections import deque

        self._inflight_q: deque = deque()
        try:
            # polylint: disable=ML004(documented operator override: env beats any programmatic config, see comment above)
            self._depth = max(1, int(os.environ.get(
                "POLYKEY_DISPATCH_LOOKAHEAD", config.lookahead_blocks
            )))
        except ValueError:
            self._depth = config.lookahead_blocks
        # Flight-deck timeline (ISSUE 10): the promoted pipeline ring —
        # typed, bounded, always-on events for both frontiers plus slot
        # lifecycle, exported as Perfetto JSON (/debug/timeline). The
        # loop-trace regression test asserts dispatch-N+1-before-
        # process-N on it. timeline_capacity=0 disables it entirely:
        # no ring allocated, every emission site one `is None` branch —
        # obs-off engines pay nothing (the memory-discipline contract
        # tests/test_timeline.py pins).
        self.timeline: Optional[TimelineRecorder] = (
            TimelineRecorder(config.timeline_capacity)
            if config.timeline_capacity > 0 else None
        )
        # SLO signal plane (ISSUE 11): windowed rates/delta-quantiles
        # over a ring of metrics snapshots, plus burn-rate evaluation of
        # the declarative POLYKEY_SLO objectives. Attached to the
        # METRICS object so the supervisor's adoption path carries the
        # windows and budget state across restarts; the supervisor
        # rebinds `timeline` to the fresh ring. signals_interval_s=0
        # allocates nothing (`metrics.signals is None`) and the loop
        # emission site below is one `is None` branch.
        if config.signals_interval_s > 0 and self.metrics.signals is None:
            from ..obs.signals import (
                ENV_POLICY,
                ENV_WINDOWS,
                SignalPlane,
                SloPolicy,
                windows_from_spec,
            )

            # Config-first, env-fallback: an EngineConfig.from_env
            # carries the boot-time specs (restart-stable); a
            # programmatic config controls them without touching
            # os.environ; the empty defaults read the env here.
            self.metrics.signals = SignalPlane(
                self.metrics,
                windows=windows_from_spec(
                    config.signals_windows
                    or os.environ.get(ENV_WINDOWS, "")
                ),
                interval_s=config.signals_interval_s,
                policy=SloPolicy.from_spec(
                    config.slo_policy or os.environ.get(ENV_POLICY, "")
                ),
                timeline=self.timeline,
            )
        self._dispatch_seq = 0
        # In-flight target for the CURRENT block size: when the adaptive
        # dispatcher shrinks K, the LOOKAHEAD portion deepens by the
        # same factor (1 + (depth-1) x (K/steps) — constant queued-ahead
        # steps), because roundtrip hiding needs lookahead × block_time
        # ≥ the tunnel latency — a K/8 block at the configured depth
        # would leave the host stalled on un-landed copies. Only the
        # lookahead portion scales, so depth 1 stays exactly
        # synchronous at every block size (the escape-hatch contract).
        # The 64-block cap binds only for large lookahead_blocks (the
        # scale factor itself tops out at block_steps // solo_steps).
        self._depth_target = self._depth
        if config.compile_warmup:
            self._compile_warmup()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self.dead: Optional[str] = None
        self.last_progress = time.monotonic()

        self._trace_acc = (
            {"iters": 0}
            if os.environ.get("POLYKEY_LOOP_TRACE", "") == "1"
            else None
        )

        self._thread = threading.Thread(
            target=self._run, name="polykey-engine", daemon=True
        )
        self._thread.start()

    # -- public API (any thread) -------------------------------------------

    def submit(self, request: GenRequest) -> None:
        if self.dead is not None:
            raise EngineDeadError(self.dead)
        if self._stop.is_set():
            raise EngineDeadError("engine is shut down")
        # Bounded admission with load shedding: over-limit submissions
        # fail in O(1) with a retry-after hint instead of queueing into
        # unbounded latency — overload degrades to fast rejections.
        limit = self.config.max_queue_depth
        if limit > 0 and self._submit.qsize() >= limit:
            self.metrics.on_shed()
            raise EngineOverloadedError(
                f"submit queue full ({limit} waiting)",
                retry_after_ms=self._retry_after_ms(),
            )
        if request.deadline is not None:
            # Deadline-aware admission: if the estimated queue delay
            # already blows the request's budget, shedding now is
            # strictly better than burning a slot on work the client
            # will throw away. Estimate is qsize × EWMA(service time) /
            # slots — zero until the first completed request, so cold
            # engines never false-positive.
            est = self._estimated_queue_delay_s()
            if est > 0.0 and time.monotonic() + est >= request.deadline:
                self.metrics.on_shed()
                raise EngineOverloadedError(
                    f"estimated queue delay {est:.2f}s exceeds request "
                    "deadline",
                    retry_after_ms=self._retry_after_ms(),
                )
        self.metrics.on_admit()
        self._submit.put(request)
        self._wake.set()
        # Close the submit/shutdown race: if the engine died or stopped
        # between the check above and the put, nothing will ever drain the
        # queue — fail it from here (queue ops are thread-safe; a duplicate
        # terminal event is harmless, readers stop at the first one).
        if self.dead is not None or self._stop.is_set():
            self._fail_pending(self.dead or "engine is shut down")

    def _estimated_queue_delay_s(self) -> float:
        """Expected wait before a newly queued request is admitted: with
        S slots draining in parallel and an EWMA per-request service
        time, the queue drains at roughly S requests per EWMA."""
        ewma = self.metrics.service_time_ewma_s()
        if ewma <= 0.0:
            return 0.0
        slots = max(1, self.config.max_decode_slots)
        return self._submit.qsize() * ewma / slots

    def _retry_after_ms(self) -> int:
        """Shed hint: about one slot-drain interval, floored at 50 ms so
        clients never busy-spin, defaulting to 100 ms on a cold engine."""
        ewma = self.metrics.service_time_ewma_s()
        if ewma <= 0.0:
            return 100
        slots = max(1, self.config.max_decode_slots)
        return max(50, int(1000.0 * ewma / slots))

    # -- router signals (replica_pool; any thread) ---------------------------

    def queue_delay_estimate_s(self) -> float:
        """Public routing signal: the same estimated queue delay the
        deadline-aware admission check uses (qsize × service EWMA /
        slots) — the replica pool ranks candidates by it."""
        return self._estimated_queue_delay_s()

    def load_fraction(self) -> float:
        """Instantaneous load for routing: (busy slots + queued) over
        slots. The EWMA-based delay estimate is 0 until a first request
        completes, so a cold pool would tie every score and pile work on
        replica 0 — this term spreads concurrent cold traffic."""
        slots = max(1, self.config.max_decode_slots)
        busy = sum(s is not None for s in self._slots)
        return (busy + self._submit.qsize()) / slots

    def prefix_warmth(self, ids) -> float:
        """Fraction [0, 1] of `ids` (token id sequence) whose KV this
        engine could serve from its prefix cache — the NetKV-style
        warmth signal the replica/disagg routers score on. Read-only:
        no page retains, no LRU refresh, no hit accounting
        (prefix_cache.probe_tiered). TIER-AWARE (ISSUE 15): host-
        resident pages count as warm — a spilled-but-warm sticky
        session must not route as cold — but weighted below device-
        resident ones (a restore scatter stands between them and a
        dispatch). 0.0 with prefix caching off or an empty prompt."""
        if self._prefix is None or len(ids) == 0:
            return 0.0
        ids = np.asarray(ids, dtype=np.int32)
        dev, host = self._prefix.probe_tiered(ids)
        return (dev + _HOST_WARMTH_WEIGHT * host) / len(ids)

    # -- live-knob actuation (autopilot; any thread) -------------------------
    #
    # The scheduling knobs below were once read from the frozen config
    # (or captured at construction) exactly once — a mid-run change was
    # silently ignored. Each setter mutates the ONE attribute the engine
    # loop reads per iteration, so an actuation lands within one loop
    # pass. Plain int/float attribute swaps: GIL-atomic against the loop
    # thread, no lock needed (racelint: no blocking under any lock).
    # Every setter clamps to the engine's own hard bounds and returns
    # the value actually applied — the autopilot records old→new from
    # the return, never from its request.

    def set_lookahead(self, depth: int) -> int:
        """Dispatch pipeline depth (POLYKEY_DISPATCH_LOOKAHEAD). The
        adaptive _depth_target recomputes from _depth on every dispatch,
        so the new depth governs the very next block."""
        self._depth = max(1, min(64, int(depth)))
        return self._depth

    def set_prefill_budget(self, tokens: int) -> int:
        """Interleaved-prefill token budget per loop iteration. Floored
        at one chunk (the knob bounds stall length, it must never
        deadlock a long prompt); in ragged mode capped at the
        compile-static prefill-stream width — the executable cannot
        carry more prefill tokens than it was built for."""
        tokens = max(int(tokens), self._chunk)
        if self._ragged:
            tokens = min(tokens, self._ragged_width)
        self._prefill_budget = tokens
        return tokens

    def set_kv_restore_slots(self, slots: int) -> int:
        """Per-iteration restore-frontier budget (POLYKEY_KV_RESTORE_
        SLOTS): host→device page-fault scatters issued ahead of each
        iteration's dispatches."""
        self._restore_slots = max(1, min(
            int(slots), self.config.max_decode_slots
        ))
        return self._restore_slots

    def set_resident_floor(self, pages: int) -> int:
        """Host-KV resident floor (POLYKEY_KV_RESIDENT_PAGES): _finish
        spills cold pages whenever a retirement leaves fewer free
        device pages than this."""
        self._resident_low = max(0, min(
            int(pages), self.config.num_pages
        ))
        return self._resident_low

    def set_spec_gamma(self, gamma: int) -> int:
        """Upper bound on the speculative dispatch width (autopilot's
        `decide_gamma`). Snapped to the nearest ladder rung — the per-
        lane dial (device-resident) only ever takes rung values, and
        each rung is its own compiled executable, so an off-rung cap
        would either mask the dial or force a fresh compile. The cap
        clamps the dispatch-width recompute in _process_spec; lane dials
        keep adapting underneath it, so lifting the cap restores full
        gamma within one round."""
        if not self._spec:
            return 0
        g = int(gamma)
        # Snap down to the low rung unless the cap clears the high one.
        self._gamma_cap = (
            self._gamma_max if g >= self._gamma_max else self._gamma_low
        )
        self._gamma = min(self._gamma, self._gamma_cap)
        return self._gamma_cap

    def knob_setpoints(self) -> dict:
        """The live values of every actuated knob — what the loop will
        read on its next iteration, not what any config said at boot."""
        out = {
            "lookahead": self._depth,
            "prefill_budget": self._prefill_budget,
        }
        if self._host_kv is not None:
            out["restore_slots"] = self._restore_slots
            out["resident_floor"] = self._resident_low
        if self._spec:
            out["spec_gamma"] = self._gamma_cap
        return out

    @staticmethod
    def _deadline_expired(request: GenRequest) -> bool:
        return (
            request.deadline is not None
            and time.monotonic() >= request.deadline
        )

    @staticmethod
    def _trace_id_of(request: Optional[GenRequest]) -> Optional[str]:
        if request is None or request.trace is None:
            return None
        return request.trace.trace_id

    def _expire(self, request: GenRequest, phase: str) -> None:
        """Fail an expired request that never held (or no longer holds)
        a slot. Slot-holding expiries go through _finish instead."""
        self.metrics.on_deadline_expired(phase)
        if self.timeline is not None:
            self.timeline.expire(phase, self._trace_id_of(request))
        request.out.put(("error", f"{DEADLINE_MSG} while {phase}"))
        self.metrics.on_finish(request.timings, failed=True,
                               trace_id=self._trace_id_of(request))

    def stats(self) -> dict:
        snap = self.metrics.snapshot()
        snap.update(
            {
                "model": self.model_cfg.name,
                "replica": self.replica_id,
                "slots_busy": sum(s is not None for s in self._slots),
                "slots_total": self.config.max_decode_slots,
                "pages_free": self.allocator.num_free,
                "pages_total": self.config.num_pages,
                "queued": self._submit.qsize(),
                "inflight_blocks": len(self._inflight_q),
                "prefill_budget": self._prefill_budget,
                # Lookahead pipeline (ISSUE 6): configured depth (env
                # override included), the live adaptive target, and the
                # host-stall/overlap numbers ride the metrics snapshot
                # (host_stall_ms_p50, lookahead_observed_*).
                "lookahead_depth": self._depth,
                "lookahead_target": self._depth_target,
                # Ragged dispatch (ISSUE 12): whether the single-
                # executable mixed prefill+decode path is live, and its
                # static prefill-stream width.
                "ragged": self._ragged,
            }
        )
        if self._ragged:
            snap["ragged_width"] = self._ragged_width
        if snap.get("avg_lanes") is not None:
            # Measured occupancy fraction: step-weighted mean live lanes
            # over the slot count (the ≥0.8 target ISSUE 4 soaks against).
            snap["occupancy"] = round(
                snap["avg_lanes"] / max(1, self.config.max_decode_slots), 4
            )
        signals = self.metrics.signals
        if signals is not None:
            # Windowed quantiles alongside the lifetime ones (ISSUE 11
            # satellite): ttft_ms_p95_5m etc. reflect the last minutes,
            # not the whole uptime — the staleness fix operators read.
            snap.update(signals.stats_fields())
        if self._spec:
            # Dispatch width (the rung covering the widest active lane
            # dial, under the autopilot cap) plus the per-lane dial/EWMA
            # aggregates (ISSUE 19 satellite: the engine-global value is
            # meaningless per-lane — mean/min/max over occupied lanes is
            # what operators and the autopilot read).
            snap["spec_gamma"] = self._gamma
            snap["spec_gamma_cap"] = self._gamma_cap
            occ = [
                i for i, s in enumerate(self._slots) if s is not None
            ]
            if occ:
                dials = self._lane_gamma[occ]
                ewmas = self._lane_ewma[occ]
                snap["spec_gamma_mean"] = round(float(dials.mean()), 4)
                snap["spec_gamma_min"] = int(dials.min())
                snap["spec_gamma_max"] = int(dials.max())
                snap["spec_accept_ewma_mean"] = round(
                    float(ewmas.mean()), 4
                )
                snap["spec_accept_ewma_min"] = round(
                    float(ewmas.min()), 4
                )
                snap["spec_accept_ewma_max"] = round(
                    float(ewmas.max()), 4
                )
            else:
                snap["spec_gamma_mean"] = float(self._gamma)
                snap["spec_gamma_min"] = self._gamma
                snap["spec_gamma_max"] = self._gamma
                snap["spec_accept_ewma_mean"] = 1.0
                snap["spec_accept_ewma_min"] = 1.0
                snap["spec_accept_ewma_max"] = 1.0
        if self._prefix is not None:
            snap.update(self._prefix.stats())
        # Host-KV tier (ISSUE 15): always present — collectors index
        # these unconditionally, and 0s on a tier-less engine are the
        # honest reading (no host pool exists).
        snap["host_kv"] = self._host_kv is not None
        snap["kv_host_pages"] = (
            self._host_kv.used if self._host_kv is not None else 0
        )
        snap["kv_host_capacity"] = (
            self._host_kv.capacity if self._host_kv is not None else 0
        )
        # Device pages in use by slots/cache (reserved page 0 excluded).
        snap["kv_device_pages"] = (
            self.config.num_pages - 1 - self.allocator.num_free
        )
        snap["kv_reloaded_pages"] = self._kv_reloaded_pages
        return snap

    @property
    def busy(self) -> bool:
        return (
            bool(self._active.any())
            or not self._submit.empty()
            or any(s is not None for s in self._slots)
        )

    def shutdown(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=timeout)

    # -- engine thread ------------------------------------------------------

    def _run(self) -> None:
        # POLYKEY_LOOP_TRACE=1 (read once at CONSTRUCTION — engine
        # __init__ sets _trace_acc, so a caller toggling the env after
        # the constructor returns cannot race this thread): accumulate
        # wall time per loop phase and print a summary to stderr every
        # 100 iterations — the tool that found the r03 host-side
        # serialization (PERF.md). Near-zero cost when off.
        trace = self._trace_acc is not None
        tacc: dict = self._trace_acc if trace else {"iters": 0}

        def _t() -> float:
            return time.monotonic() if trace else 0.0

        def _acc(key: str, t0: float) -> None:
            if trace:
                tacc[key] = tacc.get(key, 0.0) + (time.monotonic() - t0)

        # Heap-witness heartbeat (memlint ML006): bound once outside the
        # loop; heartbeat() self-throttles to ~1 Hz and is a no-op
        # unless POLYKEY_HEAP_WITNESS armed the witness at import.
        from ..analysis.heapwitness import heartbeat as _heap_heartbeat

        try:
            while not self._stop.is_set():
                _heap_heartbeat()
                if trace:
                    tacc["iters"] += 1
                    if tacc["iters"] % 100 == 0:
                        import sys as _sys

                        print(f"[loop-trace] {tacc}", file=_sys.stderr,
                              flush=True)
                if self.dead is not None:  # watchdog tripped while we were out
                    self._fail_all(self.dead)
                    return
                # Admit every waiting request a free slot can take, every
                # iteration — under the interleaved-prefill TOKEN BUDGET
                # (config.prefill_budget) whenever decode lanes are live.
                # Burst admissions cost one batched prefill dispatch per
                # bucket group (_dispatch_prefill_group) and long prompts
                # advance in chunks, all scheduled BETWEEN decode-block
                # dispatches; the budget bounds how many prefill tokens
                # ride any one gap, so a prompt burst can no longer stall
                # in-flight decode beyond ~budget tokens of prefill work
                # (Sarathi-style chunked interleaving; ISSUE 4). With no
                # live lanes the budget is waived — there is no ITL to
                # protect and cold bursts should fill every slot at once.
                # (History: the old `limit=1 if active` admission policy
                # equilibrated occupancy at ~max_new/K lanes — measured
                # 5/32 live lanes and 230 tok/s where full slots give
                # ~2,000; r03 loop-trace, PERF.md.)
                decode_live = bool(self._active.any())
                budget = self._prefill_budget if decode_live else None
                t0 = _t()
                worked, spent = self._admit(budget=budget)
                _acc("admit", t0)
                if self._host_kv is not None:
                    # Restore frontier (ISSUE 15): issue host→device
                    # page scatters for faulting slots BEFORE this
                    # iteration's prefill/decode dispatches — restores
                    # ride ahead of need on the donation chain, budgeted
                    # like interleaved prefill so they cannot stall live
                    # decode beyond host_kv_restore_slots uploads.
                    t0 = _t()
                    if self._issue_restores():
                        worked = True
                    _acc("restore", t0)
                if self._ragged:
                    # Ragged mode: admissions only REGISTER (token-range
                    # appends happen in _dispatch_step's batch builder,
                    # which owns the budget and the interleave
                    # accounting) — no separate chunk dispatch exists.
                    chunked = 0
                else:
                    t0 = _t()
                    remaining = (
                        None if budget is None else max(0, budget - spent)
                    )
                    chunked = self._advance_chunked_prefills(remaining)
                    if chunked:
                        _acc("chunk", t0)
                        worked = True
                    self.metrics.on_prefill_interleave(
                        spent + chunked, decode_live
                    )
                if self._dev_dirty and self._inflight_q:
                    # Rare full transition (init/recovery): a mirror upload
                    # may never rewind live device state, so the whole
                    # pipeline drains first.
                    self._drain_inflight()
                # Dispatch frontier: keep up to `_depth_target` slot-state
                # generations resident — the dispatch in hand plus
                # `_depth_target - 1` queued blocks (constant
                # steps-in-flight across block sizes).
                # Device-side stopping makes stale blocks safe (a stream the
                # host finished was stopped on device by the same EOS/cap
                # condition, so its lookahead emit lanes read -1);
                # cancellations are the one host-only transition, guarded
                # per-block by the request-identity snapshot in
                # _process_step. Spec rounds carry the same device-side
                # stop, so both block kinds pipeline alike.
                dispatched = False
                if self._active.any() or (
                    self._ragged and self._has_pending_prefill()
                ):
                    t0 = _t()
                    block = self._dispatch_step()
                    _acc("dispatch", t0)
                    if block is not None:
                        self._inflight_q.append(block)
                        if trace:
                            tacc["blocks"] = tacc.get("blocks", 0) + 1
                            tacc["max_depth"] = max(
                                tacc.get("max_depth", 0), self._depth_target
                            )
                            tacc["disp_steps"] = (
                                tacc.get("disp_steps", 0)
                                + self._last_dispatch_steps
                            )
                            tacc["disp_lanes"] = (
                                tacc.get("disp_lanes", 0)
                                + int(self._active.sum())
                            )
                        dispatched = True
                        worked = True
                if _schedwitness.installed() and self._active.any():
                    # Decode boundary: a dispatched block serves every
                    # active lane (flat batch); active lanes with no
                    # block this iteration are waiting on the frontier.
                    lanes = np.flatnonzero(self._active).tolist()
                    _schedwitness.note(
                        "decode", lanes if dispatched else [], lanes
                    )
                t0 = _t()
                self._resolve_prefills()
                _acc("resolve", t0)
                # Processed frontier: drain down to depth-1 queued blocks
                # (depth counts the dispatch in hand, so depth 1 reads the
                # block it just dispatched — synchronous — and depth 2
                # keeps one block in flight while dispatching the next).
                # Behind the forced drain, any OLDER block whose packed
                # copy already LANDED is processed too — a free batched
                # readback that never blocks the host. The freshest block
                # stays in flight across the iteration boundary (floor)
                # even when a fast device finishes it instantly: reading
                # it now would re-serialize dispatch-then-read, and the
                # whole point of the pipeline is that block N's readback
                # happens AFTER block N+1's dispatch (the happens-before
                # the loop-trace test pins). Idle iterations (floor 0)
                # collapse the pipeline completely.
                target = max(0, self._depth_target - 1) if dispatched else 0
                floor = 1 if (dispatched and self._depth > 1) else 0
                t0 = _t()
                while self._inflight_q and (
                    len(self._inflight_q) > target
                    or (len(self._inflight_q) > floor
                        and self._block_ready(self._inflight_q[0]))
                ):
                    self._process_step(self._inflight_q.popleft())
                    worked = True
                _acc("process", t0)
                # SLO signal plane (ISSUE 11): ring sample at block
                # boundaries — idle iterations reach here too at ~20 Hz
                # (the low-rate fallback timer). Time-gated inside to
                # signals_interval_s; one `is None` branch when off.
                signals = self.metrics.signals
                if signals is not None:
                    signals.maybe_sample()
                if worked:
                    self.last_progress = time.monotonic()
                else:
                    # Idle iteration ⇒ no live lanes and an empty
                    # pipeline: the idle wait must not be charged to the
                    # next request as device time (attribution reads the
                    # inter-dispatch gap as device-busy, which only
                    # holds while dispatches tile the device schedule).
                    self.metrics.on_dispatch_idle()
                    self._resolve_prefills(block=True)
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                    # Idle time is not a stall: only the engine thread itself
                    # may refresh the stall clock (a submit() reset would let
                    # steady client traffic suppress the watchdog during a
                    # genuine device hang mid-_step).
                    self.last_progress = time.monotonic()
            self._fail_all("engine is shut down")
        except Exception as e:  # engine thread must never die silently
            self.dead = f"engine loop crashed: {e}"
            if self.logger is not None:
                self.logger.error(
                    "engine loop crashed",
                    error=str(e),
                    traceback=traceback.format_exc(),
                )
            self._fail_all(self.dead)
            if self.health is not None:
                self.health.shutdown()

    def _bucket_for(self, length: int) -> Optional[int]:
        for b in self.config.prefill_buckets:
            if length <= b:
                return b
        return None

    def _admit(self, budget: Optional[int] = None) -> tuple[bool, int]:
        """Admit waiting requests into free slots. Short prompts are
        gathered into per-bucket groups and prefilled in ONE batched
        dispatch per group (burst admissions — e.g. cold start — pay one
        device call instead of one per request; spec engines batch the
        same way, prefilling both pools per dispatch); long prompts
        register for chunked prefill.

        `budget` (tokens, None → unbounded) is the interleaved-prefill
        discipline: each short admission charges its padded bucket width
        (the prefill tokens its group dispatch will compute); once spent
        reaches the budget, the rest of the queue WAITS for the next
        loop iteration — i.e. for the next decode block to dispatch
        first. Long-prompt registrations charge nothing here; their
        chunks are budgeted as they dispatch
        (_advance_chunked_prefills). Returns (admitted_any, spent)."""
        admitted = False
        spent = 0
        trace = getattr(self, "_trace_acc", None)
        groups: dict[int, list] = {}    # bucket → [(slot_idx, slot, ids)]
        try:
            while budget is None or spent < budget:
                free_slots = [
                    i for i, s in enumerate(self._slots) if s is None
                ]
                if not free_slots:
                    if trace is not None:
                        trace["adm_noslot"] = trace.get("adm_noslot", 0) + 1
                    return admitted, spent
                try:
                    request = self._submit.get_nowait()
                except queue.Empty:
                    if trace is not None:
                        trace["adm_empty"] = trace.get("adm_empty", 0) + 1
                    return admitted, spent
                if request.cancelled.is_set():
                    continue
                if self._deadline_expired(request):
                    # Dropped at dequeue: the request never tokenizes,
                    # never allocates pages, never reaches the device.
                    self._expire(request, "queued")
                    continue
                try:
                    prep = self._prepare_request(free_slots[0], request)
                    admitted = True
                    if self.timeline is not None:
                        self.timeline.admit(
                            free_slots[0], self._trace_id_of(request),
                            request.timings.prompt_tokens,
                        )
                    if trace is not None:
                        trace["adm_ok"] = trace.get("adm_ok", 0) + 1
                    if prep is not None:
                        bucket = prep[0]
                        # Budget charge = the bucket width (known only
                        # after tokenize), so the LAST admission may
                        # overshoot by one bucket — the budget is a soft
                        # bound at dispatch granularity (config).
                        spent += bucket
                        groups.setdefault(bucket, []).append(prep[1:])
                        if len(groups[bucket]) >= _MAX_PREFILL_GROUP:
                            self._dispatch_prefill_group(
                                bucket, groups.pop(bucket)
                            )
                except AllocationError:
                    # Pool exhausted: put it back and let running requests
                    # finish. FIFO fairness over throughput.
                    if trace is not None:
                        trace["adm_alloc"] = trace.get("adm_alloc", 0) + 1
                    self._requeue_front(request)
                    return admitted, spent
                except Exception as e:
                    request.out.put(("error", f"admission failed: {e}"))
                    self.metrics.on_finish(request.timings, failed=True,
                                           trace_id=self._trace_id_of(request))
            return admitted, spent
        finally:
            for bucket, group in groups.items():
                self._dispatch_prefill_group(bucket, group)

    def _requeue_front(self, request: GenRequest) -> None:
        # queue.Queue has no push-front; rebuild (small queues, rare path).
        items = [request]
        try:
            while True:
                items.append(self._submit.get_nowait())
        except queue.Empty:
            pass
        for item in items:
            self._submit.put(item)

    def _prepare_request(self, slot_idx: int, request: GenRequest):
        """Tokenize, budget, allocate pages, and register the slot.
        Returns (bucket, slot_idx, slot, prompt_ids, start) for short
        prompts (the caller batches their prefill dispatches — plain and
        spec engines alike) or None for long prompts (registered for
        chunked prefill)."""
        cfg = self.config
        if request.resume_state is not None:
            # Decode-tier resume (ISSUE 13): the prompt's KV arrives
            # with the request; nothing tokenizes or prefills here.
            return self._admit_resume(slot_idx, request)
        request.timings.prefill_start = time.monotonic()

        if self._faults is not None:
            self._faults.maybe_raise("tokenizer-error", replica=self.replica_id, tier=self._tier)
        prompt_ids = self.tokenizer.encode(request.prompt)
        max_new = max(
            1,
            min(request.max_new_tokens, cfg.max_new_tokens_cap,
                cfg.max_seq_len - 1 - self._gamma_max),
        )
        # Leave room for generation within the per-request position cap
        # (max_new ≤ max_seq_len-1-gamma guarantees max_prompt ≥ 1, so the
        # tail-truncation slice below can never be [-0:]). The gamma slack
        # keeps the final speculative verify window's overdraft inside the
        # request's own pages (spec_decode.py module docstring). Prompts
        # beyond the largest bucket go through chunked prefill, so the cap
        # is the position budget, not the bucket table.
        max_prompt = cfg.max_seq_len - max_new - self._gamma_max
        if len(prompt_ids) > max_prompt:
            prompt_ids = prompt_ids[-max_prompt:]  # keep the prompt tail
        prompt_len = len(prompt_ids)
        request.timings.prompt_tokens = prompt_len

        total_len = prompt_len + max_new
        ids = np.asarray(prompt_ids, dtype=np.int32)

        # Prefix cache: reuse pages covering a cached page-aligned prefix
        # (lookup retains device pages for this slot); only the suffix
        # prefills. With the host tier on (ISSUE 15) the lookup walks
        # BOTH tiers: host-resident hits are PAGE FAULTS — each gets a
        # fresh device page here, the host contents scatter in via the
        # restore frontier (_issue_restores), and the slot joins no
        # dispatch until that restore has issued.
        matched: list[int] = []
        chain: list = []
        fault_idx: list[int] = []
        if self._prefix is not None:
            if self._host_kv is not None:
                chain, fault_idx = self._prefix.lookup_chain(ids)
                if not fault_idx:
                    # All-device chain: identical to the classic lookup.
                    matched = [page for _, _, page in chain]
                    chain = []
            else:
                matched = self._prefix.lookup(ids)
        restore_items: list = []
        if chain:
            # Detach the chain's host pages BEFORE allocating: the
            # pressure path below may spill into a full host tier,
            # whose LRU drop (`pop_lru_host`) must never free a page
            # this admission's pending restore depends on. Ownership
            # moves to this request now and returns (re-adopt) on the
            # allocation-failure path.
            for ci, (key, tier, _page) in enumerate(chain):
                if tier == TIER_HOST:
                    restore_items.append(
                        (key, self._prefix.detach_host(key), ci)
                    )
        n_dev_matched = (
            (len(chain) - len(fault_idx)) if chain else len(matched)
        )
        need = (
            -(-(total_len + self._gamma_max) // cfg.page_size)
            - n_dev_matched
        )
        try:
            if self._faults is not None:
                # Inside the try: the AllocationError path below must
                # still release the prefix-cache lookup's page refs.
                self._faults.maybe_raise(
                    "alloc-fail", AllocationError, replica=self.replica_id,
                    tier=self._tier,
                )
            try:
                fresh = self.allocator.alloc(need)
            except AllocationError:
                if self._prefix is None:
                    raise
                # Allocation pressure: offload cold cache pages to the
                # host tier when it exists (warmth preserved), drop them
                # when it doesn't (or it couldn't free enough), retry.
                if self._host_kv is not None:
                    self._spill_for(need)
                if self.allocator.num_free < need:
                    self._prefix.evict_for(need)
                fresh = self.allocator.alloc(need)
        except AllocationError:
            if chain:
                self._prefix.release_chain(chain)   # drop lookup's refs
                for key, host_page, _ci in restore_items:
                    # Hand the detached host pages back to the cache
                    # (warmth survives the requeue); a key re-cached
                    # meanwhile keeps its copy and ours frees.
                    if not self._prefix.adopt_host(key, host_page):
                        self._host_kv.release(host_page)
            else:
                self.allocator.release_all(matched)
            raise
        if chain:
            # Assemble the table in chain order: device hits keep their
            # shared pages; fault positions take fresh pages whose
            # contents arrive via the restore frontier (the host pages
            # detached to this slot above).
            pages = []
            fi = 0
            for _key, tier, page in chain:
                if tier == TIER_DEVICE:
                    pages.append(page)
                else:
                    pages.append(fresh[fi])
                    fi += 1
            pages += fresh[fi:]
        else:
            pages = matched + fresh
        if request.trace is not None:
            # Recorded only after allocation succeeds: an AllocationError
            # requeues the request and re-enters this method, and the
            # span tree must hold ONE queue_wait covering the whole wait
            # (enqueue through the attempt that actually admitted).
            request.trace.child(
                "queue_wait",
                start=request.timings.enqueued,
                end=request.timings.prefill_start,
            )

        page_table = np.zeros((1, cfg.pages_per_seq), dtype=np.int32)
        page_table[0, : len(pages)] = pages
        seed = request.seed
        if seed is None:
            seed = int(self._seed_rng.integers(0, 1 << 63))
        # Injective packing of the seed's low 64 bits into two int32
        # halves (uint32 wraparound, not masking to 31 bits — distinct
        # 64-bit seeds must never collide to the same stream; seeds are
        # taken mod 2**64).
        s = seed & 0xFFFFFFFFFFFFFFFF
        seed_row = np.array(
            [(s >> 32) & 0xFFFFFFFF, s & 0xFFFFFFFF], np.uint32
        ).view(np.int32)
        slot = _Slot(request=request, pages=pages, position_cap=total_len)
        slot.seed_row = seed_row
        bucket = self._bucket_for(prompt_len)

        slot.table = page_table
        slot.prompt_len = prompt_len
        slot.prompt_ids = ids

        if restore_items:
            # Faulting admission: the slot registers with its whole
            # prompt pending from the post-chain offset and WAITS for
            # the restore frontier — it joins no prefill dispatch until
            # its pages are in flight on the donation chain, so resident
            # lanes admitted this same iteration dispatch ahead of it
            # (the page-aware no-stall property).
            slot.restore_pages = restore_items
            kind = (
                "ctx" if prompt_len > max(cfg.prefill_buckets) else "prefix"
            )
            self.metrics.on_kv_fault(kind, len(restore_items))
            slot.pending = ids
            slot.filled = len(chain) * cfg.page_size
            self._slots[slot_idx] = slot
            return None

        if self._ragged:
            # Ragged mode: EVERY prompt registers as a pending token
            # range — admissions and chunk advancement are the same
            # operation (token-range appends into the next ragged
            # dispatch's flat stream; _build_ragged_batch). A prefix-
            # cache hit just starts the range at the cached offset.
            slot.pending = ids
            slot.filled = len(matched) * cfg.page_size
            self._slots[slot_idx] = slot
            return None

        if matched:
            # Prefill only the suffix. A bucket-sized suffix rides the
            # batched bucket path at its own width (a hit must not cost
            # more than a miss); longer suffixes chunk from the offset.
            # On spec engines the group dispatch prefills BOTH pools, and
            # cached pages already hold both models' prefix KV.
            filled = len(matched) * cfg.page_size
            suffix = ids[filled:]
            suffix_bucket = self._bucket_for(len(suffix))
            self._slots[slot_idx] = slot
            if suffix_bucket is None:
                slot.pending = ids
                slot.filled = filled
                return None
            return suffix_bucket, slot_idx, slot, suffix, filled

        if bucket is None:
            # Long prompt: register the slot in prefilling state; the
            # engine loop runs one chunk per iteration (interleaved with
            # decode steps) until the prompt is in cache. Its page table
            # stays slot-local (NOT in the device mirrors) so concurrent
            # decode blocks keep writing this lane's garbage through the
            # reserved page 0 instead of over the chunks already prefilled.
            slot.pending = np.asarray(prompt_ids, dtype=np.int32)
            self._slots[slot_idx] = slot
            return None

        # Registered but inactive until _resolve_prefills reads the token —
        # after the next decode block is dispatched, so prefill overlaps it.
        self._slots[slot_idx] = slot
        return bucket, slot_idx, slot, ids, 0

    def _dispatch_prefill_group(self, bucket: int, group: list) -> None:
        """One batched prefill dispatch for up to _MAX_PREFILL_GROUP
        same-bucket admissions, padded to a power of two so the compiled
        shape set stays small ({1,2,4,8} × buckets). Padded rows point their
        page tables at the reserved garbage page and are never resolved."""
        n = len(group)
        n_pad = 1 if n == 1 else 2 if n == 2 else 4 if n <= 4 else 8
        cfg = self.config
        tokens = np.zeros((n_pad, bucket), dtype=np.int32)
        starts = np.zeros((n_pad,), dtype=np.int32)
        last_rel = np.zeros((n_pad,), dtype=np.int32)
        tables = np.zeros((n_pad, cfg.pages_per_seq), dtype=np.int32)
        temp = np.zeros((n_pad,), dtype=np.float32)
        top_p = np.ones((n_pad,), dtype=np.float32)
        top_k = np.zeros((n_pad,), dtype=np.int32)
        seeds = np.zeros((n_pad, 2), dtype=np.int32)
        for r, (slot_idx, slot, ids, start) in enumerate(group):
            tokens[r, : len(ids)] = ids
            starts[r] = start                   # >0: prefix-cache suffix
            last_rel[r] = len(ids) - 1
            tables[r] = slot.table[0]
            temp[r] = slot.request.temperature
            top_p[r] = slot.request.top_p
            top_k[r] = self._eff_top_k(slot.request)
            seeds[r] = slot.seed_row
        greedy = bool(np.all(temp == 0.0))

        put = partial(jax.device_put, device=self._repl)
        common = (
            jax.device_put(tokens, self._prefill_tok),
            put(starts), put(last_rel), put(tables), put(seeds),
            put(temp), put(top_p), put(top_k),
        )
        try:
            if self._faults is not None:
                self._faults.maybe_raise("prefill-error", replica=self.replica_id, tier=self._tier)
            with jax.profiler.TraceAnnotation("polykey/prefill"):
                if self._spec:
                    # Spec burst admissions batch exactly like plain ones
                    # (spec_prefill_fn is N-row); both pools prefill in
                    # the one dispatch.
                    toks_dev, self.paged, self.d_paged = self._jit_spec_prefill(
                        self.params, self.draft_params,
                        self.model_cfg, self.draft_cfg,
                        self.paged, self.d_paged,
                        *common,
                        greedy=greedy,
                        candidates=self.config.top_p_candidates,
                        mesh=self.mesh,
                    )
                else:
                    toks_dev, self.paged = self._jit_prefill(
                        self.params, self.model_cfg, self.paged,
                        *common,
                        greedy=greedy,
                        candidates=self.config.top_p_candidates,
                        mesh=self.mesh,
                    )
        except Exception as e:
            # Contain the failure to this group: every member slot is
            # already registered, so each must be finished (pages released,
            # client errored) or they leak and their clients hang forever.
            for slot_idx, slot, _, _ in group:
                if self._slots[slot_idx] is slot:
                    self._finish(slot_idx, error=f"prefill failed: {e}")
            return
        # Padding-waste accounting: the group computed n_pad × bucket
        # token rows for Σ len(ids) real prompt tokens.
        self.metrics.on_padding_tokens(
            n_pad * bucket, sum(len(ids) for _, _, ids, _ in group)
        )
        for r, (slot_idx, slot, _, _) in enumerate(group):
            if self.timeline is not None:
                self.timeline.prefill(slot_idx, bucket, True)
            self._merge_slot(slot_idx, slot, toks_dev, r)

    def _has_pending_prefill(self) -> bool:
        return any(
            s is not None and s.pending is not None for s in self._slots
        )

    def _build_ragged_batch(self) -> list:
        """Collect the next ragged dispatch's token ranges: round-robin
        from the `_chunk_rr` cursor over slots with pending prompt
        tokens, one range of up to a chunk per slot, until the prefill
        budget (while decode lanes are live) or the stream width W is
        spent — the same fairness + progress-floor semantics as
        _advance_chunked_prefills (the first range always proceeds; the
        budget is a soft bound at range granularity). Returns
        [(slot_idx, slot, take)]; empty means no prefill work this
        iteration (steady-state decode keeps the K-step block path)."""
        W = self._ragged_width
        if self._spec and self._jit_ragged_spec is not None:
            # Spec engines may route these ranges through the per-gamma
            # tile-aligned spec stream, whose prefill width can sit up to
            # a tile short of the plain one — build to the tightest so a
            # batch fits whichever executable the spec gate picks.
            W = min(W, min(self._ragged_spec_width.values()))
        decode_live = bool(self._active.any())
        budget = min(self._prefill_budget, W) if decode_live else W
        ranges: list = []
        spent = 0
        B = len(self._slots)
        starved = None
        for i in self._chunk_rr.scan(B):
            s = self._slots[i]
            if s is None or s.pending is None:
                continue
            if s.restore_pages is not None:
                continue   # faulting: waits for the restore frontier
            if s.request.cancelled.is_set():
                self._finish(i, error="cancelled")
                continue
            if self._deadline_expired(s.request):
                # Expired mid-prefill: remaining ranges never dispatch.
                self.metrics.on_deadline_expired("prefill")
                self._finish(i, error=f"{DEADLINE_MSG} during prefill")
                continue
            if spent >= budget and ranges:
                starved = i     # goes first next iteration
                break
            take = min(self._chunk, len(s.pending) - s.filled, W - spent)
            if take <= 0:
                if ranges:
                    starved = i
                break
            ranges.append((i, s, take))
            spent += take
        if starved is not None:
            self._chunk_rr.reanchor(starved)
        else:
            self._chunk_rr.advance(B)
        self._note_sched_frontier("prefill", [i for i, _s, _t in ranges])
        return ranges

    def _ragged_prefill_operands(self, ranges: list, W: int):
        """Build the 14 `pre_*` numpy operands of a ragged dispatch
        (stream width W) from the batch builder's token ranges — shared
        by the plain ragged dispatch and the spec×ragged one (ISSUE 19)
        so the operand layout cannot drift between them. Returns
        (operands, useful, smp_temp): the positional operand tuple, the
        real-token count (padding-waste accounting), and the sampled-
        this-dispatch temperature vector (feeds the batch-greedy key)."""
        cfg = self.config
        B = cfg.max_decode_slots
        P = cfg.pages_per_seq
        pre_tokens = np.zeros((W,), np.int32)
        pre_pos = np.zeros((W,), np.int32)
        pre_tidx = np.full((W,), B, np.int32)     # B → garbage table row
        pre_tables = np.zeros((B, P), np.int32)
        rng_start = np.full((B,), W, np.int32)    # unused → past the end
        rng_len = np.zeros((B,), np.int32)
        rng_kv = np.zeros((B,), np.int32)
        rng_tidx = np.full((B,), B, np.int32)
        smp_idx = np.zeros((B,), np.int32)
        smp_pos = np.zeros((B,), np.int32)
        smp_seeds = np.zeros((B, 2), np.int32)
        smp_temp = np.zeros((B,), np.float32)
        smp_top_p = np.ones((B,), np.float32)
        smp_top_k = np.zeros((B,), np.int32)
        off = 0
        useful = 0
        for r, (i, s, take) in enumerate(ranges):
            pre_tokens[off:off + take] = s.pending[s.filled:s.filled + take]
            pre_pos[off:off + take] = np.arange(s.filled, s.filled + take)
            pre_tidx[off:off + take] = i
            pre_tables[i] = s.table[0]
            rng_start[r] = off
            rng_len[r] = take
            rng_kv[r] = s.filled + take
            rng_tidx[r] = i
            if s.filled + take >= len(s.pending):
                # Final range: sample this slot's first token from its
                # last prefill row at position key prompt_len — exactly
                # _prefill_fn's start + last_rel + 1.
                smp_idx[i] = off + take - 1
                smp_pos[i] = s.filled + take
                smp_seeds[i] = s.seed_row
                smp_temp[i] = s.request.temperature
                smp_top_p[i] = s.request.top_p
                smp_top_k[i] = self._eff_top_k(s.request)
            off += take
            useful += take
        operands = (
            pre_tokens, pre_pos, pre_tidx, pre_tables,
            rng_start, rng_len, rng_kv, rng_tidx,
            smp_idx, smp_pos, smp_seeds, smp_temp, smp_top_p, smp_top_k,
        )
        return operands, useful, smp_temp

    def _dispatch_ragged(self, ranges: list):
        """ONE flat mixed prefill+decode dispatch (ISSUE 12): the token
        ranges from _build_ragged_batch plus every decode lane's single
        token, through the resident ragged executable. Returns an
        _InflightBlock whose packed [1, B] decode emissions ride the
        lookahead pipeline's _process_step unchanged (None on a
        contained prefill failure — the caller falls through to the
        plain paths)."""
        cfg = self.config
        W = self._ragged_width
        B = cfg.max_decode_slots
        (pre_tokens, pre_pos, pre_tidx, pre_tables, rng_start, rng_len,
         rng_kv, rng_tidx, smp_idx, smp_pos, smp_seeds, smp_temp,
         smp_top_p, smp_top_k), useful, _ = (
            self._ragged_prefill_operands(ranges, W)
        )

        dev = self._dev
        act = self._active
        lanes = int(act.sum())
        # Static greedy variant, batch-keyed like the other dispatch
        # paths: all live decode lanes AND all sampled-this-dispatch
        # prefill rows greedy (non-final rows default 0.0 → neutral).
        greedy = bool(np.all(self._temperature[act] == 0.0)) and bool(
            np.all(smp_temp == 0.0)
        )
        self._depth_target = self._depth
        self._last_dispatch_steps = 1
        gap_ms = self.metrics.on_dispatch(lanes, 1, slots=B)
        # Padding-waste accounting: the device computes W prefill rows
        # of which `useful` carry real prompt tokens (decode rows are
        # charged by on_dispatch's slots/lanes split).
        self.metrics.on_padding_tokens(W, useful)
        self.metrics.on_prefill_interleave(useful, lanes > 0)
        live = tuple(int(i) for i in np.flatnonzero(act))
        put = partial(jax.device_put, device=self._repl)
        try:
            if self._faults is not None:
                self._faults.maybe_raise(
                    "prefill-error", replica=self.replica_id,
                    tier=self._tier,
                )
            with jax.profiler.TraceAnnotation("polykey/ragged"):
                (packed_dev, last_dev, seq_dev, act_dev, first_dev,
                 self.paged) = self._jit_ragged(
                    self.params, self.model_cfg, self.paged,
                    dev["last_tokens"], dev["seq_lens"],
                    dev["page_tables"], dev["active"], dev["caps"],
                    dev["seeds"], dev["temperature"], dev["top_p"],
                    dev["top_k"],
                    put(pre_tokens), put(pre_pos), put(pre_tidx),
                    put(pre_tables),
                    put(rng_start), put(rng_len), put(rng_kv),
                    put(rng_tidx),
                    put(smp_idx), put(smp_pos), put(smp_seeds),
                    put(smp_temp), put(smp_top_p), put(smp_top_k),
                    greedy=greedy, eos_id=self.tokenizer.eos_id,
                    candidates=self.config.top_p_candidates,
                    mesh=self.mesh,
                )
                dev["last_tokens"] = last_dev
                dev["seq_lens"] = seq_dev
                dev["active"] = act_dev
        except Exception as e:
            # Contain to the ranged slots (each must be finished or its
            # client hangs — the prefill-group containment contract);
            # the conservative dirty flag re-folds mirrors next
            # iteration. Decode lanes keep their state: the failure
            # (fault injection raises before dispatch) never advanced
            # them.
            for i, s, _take in ranges:
                if self._slots[i] is s:
                    self._finish(i, error=f"prefill failed: {e}")
            self._dev_dirty = True
            return None
        try:
            packed_dev.copy_to_host_async()
        except Exception:
            # Best-effort copy hint only (same as the block dispatch).
            pass
        self._dispatch_seq += 1
        if self.timeline is not None:
            self.timeline.dispatch(
                self._dispatch_seq, "ragged", lanes, 1, gap_ms
            )
        for i, s, take in ranges:
            final = s.filled + take >= len(s.pending)
            if self.timeline is not None:
                self.timeline.prefill(i, take, final)
            if final:
                # The sampled first token (row i of the ragged call's
                # first-token vector, still device-resident) activates
                # the lane via the usual merge — it joins the NEXT
                # dispatch, exactly like a bucketed admission.
                self._merge_slot(i, s, first_dev, i)
            else:
                s.filled += take
        return _InflightBlock(
            "plain", packed_dev, self._snapshot_requests(),
            self._dispatch_seq, gap_ms, live,
        )

    def _dispatch_ragged_spec(self, ranges: list):
        """ONE flat mixed dispatch serving prefill chunks AND spec verify
        lanes (ISSUE 19 tentpole b): each live decode lane contributes a
        gamma+1 verify window to the flat stream as an ordinary per-
        sequence range, alongside the prompt-chunk ranges — the spec
        formulation of _dispatch_ragged. Returns an
        _InflightBlock("spec", …) whose packed matrix rides the same
        once-per-block D2H as a bucketed spec round (None on a contained
        prefill failure)."""
        cfg = self.config
        gamma = self._gamma
        W = self._ragged_spec_width[gamma]
        B = cfg.max_decode_slots
        (pre_tokens, pre_pos, pre_tidx, pre_tables, rng_start, rng_len,
         rng_kv, rng_tidx, smp_idx, smp_pos, smp_seeds, smp_temp,
         smp_top_p, smp_top_k), useful, _ = (
            self._ragged_prefill_operands(ranges, W)
        )

        dev = self._dev
        act = self._active
        lanes = int(act.sum())
        # Static greedy variant, batch-keyed like the plain ragged path:
        # all live decode lanes AND all sampled-this-dispatch prefill
        # rows greedy. The candidates variant follows the caller's spec
        # gate: all-untruncated batches skip truncation work entirely
        # (greedy=True implies all-untruncated, so (True, C>0) never
        # compiles — mirrored in warmup's reachable-variant list).
        greedy = bool(np.all(self._temperature[act] == 0.0)) and bool(
            np.all(smp_temp == 0.0)
        )
        all_untruncated = bool(np.all(
            ((self._top_p[act] >= 1.0) & (self._top_k[act] <= 0))
            | (self._temperature[act] == 0.0)
        ))
        spec_candidates = (
            0 if all_untruncated else self.config.top_p_candidates
        )
        # Spec rounds land >= 1 token per round, so `remaining` rounds
        # always suffice (same tail-work cap as the bucketed spec path).
        self._depth_target = min(
            self._depth, max(1, self._remaining_budget(act))
        )
        self._last_dispatch_steps = 1
        # A spec round's scan length is gamma draft steps + one verify —
        # the step weight that makes its lane-seconds comparable.
        gap_ms = self.metrics.on_dispatch(lanes, gamma + 1, slots=B)
        # Padding-waste accounting covers the PREFILL region only: the
        # B·(gamma+1) verify rows are charged by on_dispatch's
        # steps-weighted lane accounting, same as a bucketed spec round.
        self.metrics.on_padding_tokens(W, useful)
        self.metrics.on_prefill_interleave(useful, lanes > 0)
        live = tuple(int(i) for i in np.flatnonzero(act))
        put = partial(jax.device_put, device=self._repl)
        try:
            if self._faults is not None:
                self._faults.maybe_raise(
                    "prefill-error", replica=self.replica_id,
                    tier=self._tier,
                )
            with jax.profiler.TraceAnnotation("polykey/ragged_spec"):
                (packed_dev, last_dev, seq_dev, act_dev, ewma_dev,
                 dial_dev, first_dev, self.paged,
                 self.d_paged) = self._jit_ragged_spec(
                    self.params, self.draft_params,
                    self.model_cfg, self.draft_cfg,
                    self.paged, self.d_paged,
                    dev["last_tokens"], dev["seq_lens"],
                    dev["page_tables"], dev["active"], dev["caps"],
                    dev["seeds"], dev["temperature"], dev["top_p"],
                    dev["top_k"],
                    dev["accept_ewma"], dev["gamma_lane"],
                    put(pre_tokens), put(pre_pos), put(pre_tidx),
                    put(pre_tables),
                    put(rng_start), put(rng_len), put(rng_kv),
                    put(rng_tidx),
                    put(smp_idx), put(smp_pos), put(smp_seeds),
                    put(smp_temp), put(smp_top_p), put(smp_top_k),
                    gamma=gamma, eos_id=self.tokenizer.eos_id,
                    gamma_low=self._gamma_low, gamma_max=self._gamma_max,
                    greedy=greedy, candidates=spec_candidates,
                    mesh=self.mesh,
                )
                dev["last_tokens"] = last_dev
                dev["seq_lens"] = seq_dev
                dev["active"] = act_dev
                dev["accept_ewma"] = ewma_dev
                dev["gamma_lane"] = dial_dev
        except Exception as e:
            # Same containment contract as _dispatch_ragged: finish the
            # ranged slots, mark mirrors dirty, let the caller fall
            # through. Decode lanes keep their state.
            for i, s, _take in ranges:
                if self._slots[i] is s:
                    self._finish(i, error=f"prefill failed: {e}")
            self._dev_dirty = True
            return None
        try:
            packed_dev.copy_to_host_async()
        except Exception:
            # Best-effort copy hint only (same as the block dispatch).
            pass
        if self.config.spec_host_sync:
            # A/B instrumentation (scripts/occupancy_soak.py --ab-spec):
            # emulate the pre-ISSUE-19 host-loop spec round — three
            # synchronous readbacks per round on the device-resident
            # math, so the A/B isolates the crossing schedule, not the
            # arithmetic. Each timed read lands in the host-stall
            # accounting (metrics.on_spec_host_sync). Never enabled in
            # production.
            for _ in range(3):
                t_sync = time.monotonic()
                with _host_crossing("spec-host-sync"):
                    # polylint: disable=PL001(spec_host_sync A/B emulation of the pre-ISSUE-19 host-loop round; off in production), PL008(the blocking dispatch-side read IS the measured subject here)
                    np.asarray(packed_dev)
                self.metrics.on_spec_host_sync(
                    (time.monotonic() - t_sync) * 1e3
                )
        self._dispatch_seq += 1
        if self.timeline is not None:
            self.timeline.dispatch(
                self._dispatch_seq, "spec", lanes, gamma + 1, gap_ms
            )
        for i, s, take in ranges:
            final = s.filled + take >= len(s.pending)
            if self.timeline is not None:
                self.timeline.prefill(i, take, final)
            if final:
                # Same merge-activation as the plain ragged path; the
                # spec merge additionally resets the lane's gamma dial.
                self._merge_slot(i, s, first_dev, i)
            else:
                s.filled += take
        return _InflightBlock(
            "spec", packed_dev, self._snapshot_requests(),
            self._dispatch_seq, gap_ms, live,
        )

    def _compile_warmup(self) -> None:
        """Pre-compile the greedy prefill group shapes and the greedy
        decode block (or spec round) against the reserved garbage page.
        Runs in __init__ before the engine thread starts, so there is no
        concurrent owner of the donated pools; first real requests then
        never pay compile time."""
        cfg = self.config
        B = cfg.max_decode_slots
        warm_sampled = cfg.warm_sampled_variants
        greedy_variants = (True, False) if warm_sampled else (True,)
        put = partial(jax.device_put, device=self._repl)
        # Possible padded group sizes given the slot count (groups are
        # bounded by free slots; n=3 pads to 4, n=5 pads to 8). A
        # full-rate admission burst of 32 then costs 4 weight-read
        # passes instead of 8 — prefill is weight-bandwidth-bound
        # exactly like decode, so group width amortizes it.
        pads = ([1] + ([2] if B >= 2 else []) + ([4] if B >= 3 else [])
                + ([8] if B >= 5 else []))
        self._upload_slot_state()
        dev = self._dev
        zrow = np.zeros((cfg.pages_per_seq,), np.int32)
        if self._ragged:
            # Ragged mode: the per-bucket prefill executables never
            # compile — ONE ragged executable per greedy variant serves
            # every admission and chunk shape (the census collapse GL001
            # asserts). The lane merge warms against the ragged call's
            # own first-token output (committedness is part of the jit
            # key, same rule as the bucketed warmup below).
            W = self._ragged_width
            put = partial(jax.device_put, device=self._repl)
            pre = tuple(
                put(a) for a in
                ragged_zero_operands(B, W, cfg.pages_per_seq)
            )
            first_dev = None
            if self._spec:
                # Unified spec×ragged path (ISSUE 19): one executable per
                # (gamma rung, greedy/candidates variant). Reachable
                # variants only — greedy=True implies an all-greedy batch,
                # which is all-untruncated, which dispatches candidates=0.
                spec_variants = [(True, 0)]
                if warm_sampled:
                    spec_variants.append((False, 0))
                    if cfg.top_p_candidates > 0:
                        spec_variants.append((False, cfg.top_p_candidates))
                for greedy, cand in spec_variants:
                    for gamma in sorted({self._gamma_low, self._gamma_max}):
                        pre_g = tuple(
                            put(a) for a in ragged_zero_operands(
                                B, self._ragged_spec_width[gamma],
                                cfg.pages_per_seq,
                            )
                        )
                        (_, dev["last_tokens"], dev["seq_lens"],
                         dev["active"], dev["accept_ewma"],
                         dev["gamma_lane"], first_dev, self.paged,
                         self.d_paged) = self._jit_ragged_spec(
                            self.params, self.draft_params,
                            self.model_cfg, self.draft_cfg,
                            self.paged, self.d_paged,
                            dev["last_tokens"], dev["seq_lens"],
                            dev["page_tables"], dev["active"], dev["caps"],
                            dev["seeds"], dev["temperature"], dev["top_p"],
                            dev["top_k"], dev["accept_ewma"],
                            dev["gamma_lane"], *pre_g,
                            gamma=gamma, eos_id=self.tokenizer.eos_id,
                            gamma_low=self._gamma_low,
                            gamma_max=self._gamma_max,
                            greedy=greedy, candidates=cand, mesh=self.mesh,
                        )
                if warm_sampled and cfg.top_p_candidates == 0:
                    # Gate-fail fallback with prefill ranges in hand: a
                    # truncated sampled row (only possible variant:
                    # greedy=False, candidates=0) rides the PLAIN ragged
                    # dispatch. With the prefilter on, the gate never
                    # fails and _jit_ragged is unreachable entirely.
                    (_, dev["last_tokens"], dev["seq_lens"], dev["active"],
                     first_dev, self.paged) = self._jit_ragged(
                        self.params, self.model_cfg, self.paged,
                        dev["last_tokens"], dev["seq_lens"],
                        dev["page_tables"], dev["active"], dev["caps"],
                        dev["seeds"], dev["temperature"], dev["top_p"],
                        dev["top_k"], *pre,
                        greedy=False, eos_id=self.tokenizer.eos_id,
                        candidates=0, mesh=self.mesh,
                    )
            else:
                for greedy in greedy_variants:
                    (_, dev["last_tokens"], dev["seq_lens"], dev["active"],
                     first_dev, self.paged) = self._jit_ragged(
                        self.params, self.model_cfg, self.paged,
                        dev["last_tokens"], dev["seq_lens"],
                        dev["page_tables"], dev["active"], dev["caps"],
                        dev["seeds"], dev["temperature"], dev["top_p"],
                        dev["top_k"], *pre,
                        greedy=greedy, eos_id=self.tokenizer.eos_id,
                        candidates=self.config.top_p_candidates,
                        mesh=self.mesh,
                    )
            merge_args = (
                dev["last_tokens"], dev["seq_lens"],
                dev["page_tables"], dev["active"], dev["caps"],
                dev["temperature"], dev["top_p"], dev["top_k"],
                dev["seeds"],
                first_dev, np.int32(0), np.int32(0),
                np.int32(1), np.int32(2), np.float32(0.0),
                np.float32(1.0), np.int32(0), zrow,
                np.zeros((2,), np.int32),
            )
            if self._spec:
                self._jit_merge(
                    *merge_args, dev["accept_ewma"], dev["gamma_lane"],
                    np.int32(self._gamma_max),
                    eos_id=self.tokenizer.eos_id, spec=True,
                )
            else:
                self._jit_merge(*merge_args, eos_id=self.tokenizer.eos_id)
        bucket_list = () if self._ragged else cfg.prefill_buckets
        for bucket in bucket_list:
            for n in pads:
                window = (
                    jax.device_put(
                        np.zeros((n, bucket), np.int32), self._prefill_tok
                    ),
                    put(np.zeros((n,), np.int32)),
                    put(np.zeros((n,), np.int32)),
                    put(np.zeros((n, cfg.pages_per_seq), np.int32)),
                    put(np.zeros((n, 2), np.int32)),
                    put(np.zeros((n,), np.float32)),
                    put(np.ones((n,), np.float32)),
                    put(np.zeros((n,), np.int32)),
                )
                # greedy is a static argname keyed on the BATCH (all-greedy
                # vs any-sampled), so both variants occur at serving time —
                # warm both or the first sampled admission pays a compile.
                # (warm_sampled_variants=False: greedy-only runs skip the
                # sampled compiles entirely.)
                for greedy in greedy_variants:
                    if self._spec:
                        toks_dev, self.paged, self.d_paged = self._jit_spec_prefill(
                            self.params, self.draft_params,
                            self.model_cfg, self.draft_cfg,
                            self.paged, self.d_paged,
                            *window,
                            greedy=greedy,
                            candidates=self.config.top_p_candidates,
                            mesh=self.mesh,
                        )
                    else:
                        toks_dev, self.paged = self._jit_prefill(
                            self.params, self.model_cfg, self.paged,
                            *window,
                            greedy=greedy,
                            candidates=self.config.top_p_candidates,
                            mesh=self.mesh,
                        )
                if bucket == cfg.prefill_buckets[0]:
                    # Warm the lane merge with the prefill's OWN device
                    # output — a numpy stand-in would compile a different
                    # cache entry (committedness is part of the key) and
                    # the real first admission would still pay the compile.
                    merge_args = (
                        dev["last_tokens"], dev["seq_lens"],
                        dev["page_tables"], dev["active"], dev["caps"],
                        dev["temperature"], dev["top_p"], dev["top_k"],
                        dev["seeds"],
                        toks_dev, np.int32(0), np.int32(0),
                        np.int32(1), np.int32(2), np.float32(0.0),
                        np.float32(1.0), np.int32(0), zrow,
                        np.zeros((2,), np.int32),
                    )
                    if self._spec:
                        self._jit_merge(
                            *merge_args, dev["accept_ewma"],
                            dev["gamma_lane"], np.int32(self._gamma_max),
                            eos_id=self.tokenizer.eos_id, spec=True,
                        )
                    else:
                        self._jit_merge(
                            *merge_args, eos_id=self.tokenizer.eos_id,
                        )
        if self._spec:
            # The spec round is the steady-state step; its compile is the
            # heavy one (draft scan + verify + draft-sync forwards).
            # _dispatch_spec alternates between candidates=0 (all rows
            # greedy/untruncated) and candidates=top_p_candidates, and
            # each value is a distinct compile — warm both so the first
            # truncated-top-p batch at serving time doesn't stall.
            warm_candidates = [0]
            if warm_sampled and self.config.top_p_candidates > 0:
                warm_candidates.append(self.config.top_p_candidates)
            # The adaptive gamma dial alternates between both ladder
            # levels at dispatch time; each is a distinct compile.
            for cand in warm_candidates:
                for gamma in sorted({self._gamma_low, self._gamma_max}):
                    outs = self._jit_spec_decode(
                        self.params, self.draft_params,
                        self.model_cfg, self.draft_cfg,
                        self.paged, self.d_paged,
                        dev["last_tokens"], dev["seq_lens"], dev["page_tables"],
                        dev["active"], dev["caps"], dev["seeds"],
                        dev["temperature"], dev["top_p"], dev["top_k"],
                        dev["accept_ewma"], dev["gamma_lane"],
                        gamma=gamma,
                        eos_id=self.tokenizer.eos_id,
                        gamma_low=self._gamma_low,
                        gamma_max=self._gamma_max,
                        candidates=cand, mesh=self.mesh,
                    )
                    # Donated slot state: rebind the warmed dev entries
                    # from the outputs or the next warmup call would feed
                    # deleted buffers.
                    (_, dev["last_tokens"], dev["seq_lens"], dev["active"],
                     dev["accept_ewma"], dev["gamma_lane"],
                     self.paged, self.d_paged) = outs
            if warm_sampled and self.config.top_p_candidates == 0:
                # Without the top-k prefilter, a batch containing any
                # sampled top_p<1 row leaves the spec path entirely and
                # takes the PLAIN decode block (see _dispatch_step's
                # all_untruncated gate) — warm that fallback too. Only
                # greedy=False is reachable there: all_untruncated can
                # only be False via a temp>0 row, which makes the batch
                # non-greedy.
                for steps in sorted({self._solo_steps, self._block_steps}):
                    outs = self._jit_decode(
                        self.params, self.model_cfg, self.paged,
                        dev["last_tokens"], dev["seq_lens"], dev["page_tables"],
                        dev["active"], dev["caps"], dev["seeds"],
                        dev["temperature"], dev["top_p"], dev["top_k"],
                        greedy=False, steps=steps,
                        eos_id=self.tokenizer.eos_id,
                        candidates=0, mesh=self.mesh,
                    )
                    (_, dev["last_tokens"], dev["seq_lens"], dev["active"],
                     self.paged) = outs
        else:
            # greedy is batch-keyed at dispatch (all-greedy vs any-sampled)
            # and the adaptive dispatcher alternates between the solo and
            # full block sizes — warm every reachable (greedy, steps) pair.
            for greedy in greedy_variants:
                for steps in sorted({self._solo_steps, self._block_steps}):
                    outs = self._jit_decode(
                        self.params, self.model_cfg, self.paged,
                        dev["last_tokens"], dev["seq_lens"], dev["page_tables"],
                        dev["active"], dev["caps"], dev["seeds"],
                        dev["temperature"], dev["top_p"], dev["top_k"],
                        greedy=greedy, steps=steps,
                        eos_id=self.tokenizer.eos_id,
                        candidates=self.config.top_p_candidates, mesh=self.mesh,
                    )
                    # Donated slot state: rebind or the next warmup call
                    # would feed deleted buffers.
                    (_, dev["last_tokens"], dev["seq_lens"], dev["active"],
                     self.paged) = outs
        self._jit_retire(
            dev["last_tokens"], dev["seq_lens"], dev["page_tables"],
            dev["active"], dev["caps"], np.int32(0),
        )
        if self._host_kv is not None:
            # Host-tier gather/scatter pair (ISSUE 15): pre-compile both
            # fixed-width executables against the reserved garbage page
            # so the first spill or page fault at serving time never
            # pays XLA compile time (the GL001 discipline: one resident
            # executable each way, warmed here, never again).
            P = cfg.pages_per_seq
            idx0 = np.zeros((P,), np.int32)
            jax.block_until_ready(self._jit_kv_gather(self.paged, put(idx0)))
            zk = np.zeros(
                (self.model_cfg.num_layers, P, cfg.page_size,
                 self.model_cfg.num_kv_heads, self.model_cfg.head_dim),
                self.paged.k.dtype,
            )
            operands = [put(idx0), put(zk), put(np.zeros_like(zk))]
            if self._kv_quantized:
                zs = np.zeros(zk.shape[:-1], jnp.dtype(jnp.bfloat16))
                operands += [put(zs), put(np.zeros_like(zs))]
            self.paged = self._jit_kv_restore(self.paged, *operands)
        jax.block_until_ready(self.paged)
        # The dirty flag forces a fresh upload once real slots exist.
        self._dev_dirty = True

    def _run_prefill(
        self, tokens: np.ndarray, start: int, last_rel: int,
        page_table: np.ndarray, request: GenRequest,
        seed_row: np.ndarray,
    ) -> jax.Array:
        """One prefill window at absolute offset `start`, sampling from
        relative index `last_rel`. Returns the sampled token as a DEVICE
        scalar — callers either discard it (non-final chunks, no sync at
        all) or resolve it later (_resolve_prefills), so dispatching a
        prefill never blocks the engine loop on the device."""
        put = partial(jax.device_put, device=self._repl)
        common = (
            jax.device_put(tokens, self._prefill_tok),
            put(np.asarray([start], dtype=np.int32)),
            put(np.asarray([last_rel], dtype=np.int32)),
            put(np.ascontiguousarray(page_table)),
            put(seed_row.reshape(1, 2)),
        )
        sampling = (
            put(np.asarray([request.temperature], dtype=np.float32)),
            put(np.asarray([request.top_p], dtype=np.float32)),
            put(np.asarray([self._eff_top_k(request)], dtype=np.int32)),
        )
        if self._faults is not None:
            self._faults.maybe_raise("prefill-error", replica=self.replica_id, tier=self._tier)
        with jax.profiler.TraceAnnotation("polykey/prefill"):
            if self._spec:
                first_token, self.paged, self.d_paged = self._jit_spec_prefill(
                    self.params, self.draft_params,
                    self.model_cfg, self.draft_cfg,
                    self.paged, self.d_paged,
                    *common, *sampling,
                    greedy=request.temperature == 0.0,
                    candidates=self.config.top_p_candidates,
                    mesh=self.mesh,
                )
            else:
                first_token, self.paged = self._jit_prefill(
                    self.params, self.model_cfg, self.paged,
                    *common, *sampling,
                    greedy=request.temperature == 0.0,
                    candidates=self.config.top_p_candidates,
                    mesh=self.mesh,
                )
            return first_token

    def _merge_slot(
        self, slot_idx: int, slot: _Slot, toks_dev: jax.Array, row: int
    ) -> None:
        """Activate a prefilled slot's decode lane ON DEVICE: the merge
        dispatch splices the sampled token (still a device array) and the
        slot's geometry into the device-resident state, so the lane joins
        the next decode block with zero host↔device syncs and no pipeline
        flush. The host keeps a handle to the token purely for client
        delivery (_resolve_prefills)."""
        request = slot.request
        if request.prefill_only:
            # Prefill-tier mode (ISSUE 13): the lane never activates —
            # the sampled first token and the written KV pages ARE this
            # request's product; decode happens on the decode tier after
            # the handoff. The token handle still resolves through
            # _resolve_prefills, which routes to the handoff export.
            slot.merged = False
            slot.pending = None
            slot.token_dev = toks_dev
            slot.token_row = row
            try:
                toks_dev.copy_to_host_async()
            except Exception:
                pass  # harmless: np.asarray at resolve time starts the copy
            return
        if self._dev_dirty:
            # Cold start / post-recovery: fold mirrors in before merging.
            self._drain_inflight()
            self._upload_slot_state()
        dev = self._dev
        try:
            # _host_crossing: the merge's geometry rides as tiny numpy
            # scalars (an implicit upload that piggybacks the dispatch).
            with _host_crossing("merge-upload"):
                args = (
                    dev["last_tokens"], dev["seq_lens"], dev["page_tables"],
                    dev["active"], dev["caps"], dev["temperature"], dev["top_p"],
                    dev["top_k"], dev["seeds"],
                    toks_dev, np.int32(row), np.int32(slot_idx),
                    np.int32(slot.prompt_len + 1), np.int32(slot.position_cap),
                    np.float32(request.temperature), np.float32(request.top_p),
                    np.int32(self._eff_top_k(request)),
                    slot.table[0], slot.seed_row,
                )
                if self._spec:
                    # The per-lane gamma dial resets with its occupant
                    # (fresh EWMA, dial at gamma_max) — see _merge_lane_fn.
                    outs = self._jit_merge(
                        *args, dev["accept_ewma"], dev["gamma_lane"],
                        np.int32(self._gamma_max),
                        eos_id=self.tokenizer.eos_id, spec=True,
                    )
                    dev["accept_ewma"], dev["gamma_lane"] = outs[9:]
                else:
                    outs = self._jit_merge(
                        *args, eos_id=self.tokenizer.eos_id,
                    )
                (
                    dev["last_tokens"], dev["seq_lens"], dev["page_tables"],
                    dev["active"], dev["caps"], dev["temperature"], dev["top_p"],
                    dev["top_k"], dev["seeds"],
                ) = outs[:9]
        except Exception as e:
            self._finish(slot_idx, error=f"activation failed: {e}")
            return
        try:
            toks_dev.copy_to_host_async()
        except Exception:
            pass  # harmless: np.asarray at resolve time starts the copy
        slot.merged = True
        slot.pending = None
        slot.token_dev = toks_dev
        slot.token_row = row
        # Host mirrors (flush-upload source of truth; _last_tokens follows
        # at resolve time, and any flush first drains + resolves).
        self._page_tables[slot_idx] = slot.table[0]
        slot.table = None
        self._seq_lens[slot_idx] = slot.prompt_len + 1
        self._active[slot_idx] = True
        self._caps[slot_idx] = slot.position_cap
        self._temperature[slot_idx] = request.temperature
        self._top_p[slot_idx] = request.top_p
        self._top_k[slot_idx] = self._eff_top_k(request)
        self._seeds[slot_idx] = slot.seed_row
        self._lane_ewma[slot_idx] = 1.0
        self._lane_gamma[slot_idx] = max(self._gamma_max, 1)

    def _resolve_prefills(self, block: bool = False) -> None:
        """Deliver first tokens whose async D2H copies have landed (all of
        them when `block=True`). Activation already happened at merge time;
        this is purely client-facing delivery + host bookkeeping."""
        for i, slot in enumerate(self._slots):
            if slot is None or slot.token_dev is None:
                continue
            if block or slot.token_dev.is_ready():
                self._resolve_slot(i, slot)

    def _resolve_slot(self, slot_idx: int, slot: _Slot) -> None:
        try:
            # Deliberate resolve point: the copy was started async at merge
            # time (copy_to_host_async), so this sync is local by now.
            with _host_crossing("first-token-resolve"):
                # polylint: disable=PL001(first-token resolve point; async copy landed), PL008(reached from dispatch only on the dev-dirty cold path, behind a full pipeline drain)
                token = int(np.asarray(slot.token_dev).reshape(-1)[slot.token_row])
        except Exception as e:
            slot.token_dev = None
            self._finish(slot_idx, error=f"prefill failed: {e}")
            return
        slot.token_dev = None
        slot.generated = 1
        request = slot.request
        if self._prefix is not None and slot.prompt_ids is not None:
            # Publish the prompt's page-aligned pages only now: the token
            # read above proves the prefill computation succeeded, so the
            # cached pages hold real KV (an async prefill failure above
            # would otherwise poison the cache with unwritten pages). Any
            # consumer's own prefill dispatches after this point, so
            # device-order still guarantees the pages are written first.
            self._prefix.insert(slot.prompt_ids, slot.pages)
        if request.prefill_only:
            # Prefill-tier product (ISSUE 13): instead of activating
            # decode, gather the prompt's KV pages and hand the state to
            # the worker harness (which serializes + retains it until
            # the coordinator acks — the two-phase hand-over).
            self._export_handoff(slot_idx, slot, token)
            return
        self._last_tokens[slot_idx] = token
        request.timings.first_token = time.monotonic()
        slot.last_emit = request.timings.first_token
        if self.timeline is not None:
            self.timeline.slot_start(slot_idx, self._trace_id_of(request))
        if request.trace is not None:
            # Prefill phase: admission tokenize through first-token
            # delivery (covers bucketed, batched, and chunked prefill —
            # all funnel through this resolve).
            request.trace.child(
                "prefill",
                start=request.timings.prefill_start,
                end=request.timings.first_token,
                prompt_tokens=slot.prompt_len,
            )
            slot.decode_span = request.trace.child(
                "decode", start=request.timings.first_token
            )
        request.out.put(("token", token))
        self._maybe_finish(slot_idx, token)

    def _export_handoff(self, slot_idx: int, slot: _Slot,
                        token: int) -> None:
        """Prefill-tier export (ISSUE 13): gather the slot's prompt KV
        pages to host, emit ("handoff", KVHandoffState) then the usual
        ("done", timings), and release the slot. The gathered host copy
        is the retained artifact of the two-phase hand-over (the worker
        harness keeps its serialized form until the coordinator acks);
        the device pages themselves release with the slot — block-table
        order is preserved by the gather, so the target re-maps pages to
        its own ids without any index translation."""
        request = slot.request
        cfg = self.config
        n_kv = -(-slot.prompt_len // cfg.page_size)
        try:
            # polylint: disable=PL008(tiny page-index upload, not a readback; prefill_only cold path)
            idx = jnp.asarray(np.asarray(slot.pages[:n_kv], np.int32))
            with _host_crossing("handoff-export"):
                # polylint: disable=PL008(handoff export: deliberate one-shot gather; prefill_only cold path never taken by in-process serving)
                k = np.asarray(jnp.take(self.paged.k, idx, axis=1))
                # polylint: disable=PL008(handoff export gather; prefill_only cold path)
                v = np.asarray(jnp.take(self.paged.v, idx, axis=1))
                ks = vs = None
                if self.paged.quantized:
                    # polylint: disable=PL008(handoff export gather; prefill_only cold path)
                    ks = np.asarray(jnp.take(self.paged.ks, idx, axis=1))
                    # polylint: disable=PL008(handoff export gather; prefill_only cold path)
                    vs = np.asarray(jnp.take(self.paged.vs, idx, axis=1))
        except Exception as e:
            self._finish(slot_idx, error=f"handoff export failed: {e}")
            return
        halves = slot.seed_row.view(np.uint32).astype(np.uint64)
        seed = int((halves[0] << np.uint64(32)) | halves[1])
        state = KVHandoffState(
            model=self.model_cfg.name, page_size=cfg.page_size,
            prompt_len=slot.prompt_len, first_token=int(token), seed=seed,
            prompt_ids=slot.prompt_ids, k=k, v=v, ks=ks, vs=vs,
        )
        request.timings.first_token = time.monotonic()
        if self.timeline is not None:
            self.timeline.note(
                "handoff_export", slot=slot_idx,
                prompt_tokens=slot.prompt_len, pages=n_kv,
            )
        request.out.put(("handoff", state))
        self._finish(slot_idx)

    def _admit_resume(self, slot_idx: int, request: GenRequest) -> None:
        """Decode-tier admission (ISSUE 13): map a handed-off KV state
        into this pool and splice the slot state a single-process run
        would hold at seq_len = prompt_len + 1 — no tokenize, no
        prefill dispatch. Greedy continuation is then bit-identical to
        an uninterrupted run (same params, same seed, same position
        keys). Geometry/dtype mismatches reject as typed 'kv-handoff
        rejected' failures BEFORE any pool write; AllocationError takes
        the usual requeue backpressure path (the resume_state rides the
        request, so a retry re-admits cleanly)."""
        cfg = self.config
        state: KVHandoffState = request.resume_state
        request.timings.prefill_start = time.monotonic()
        try:
            state.validate_for(
                self.model_cfg, cfg.page_size, self._kv_quantized
            )
            if jnp.dtype(state.k.dtype) != self.paged.k.dtype:
                raise KVWireError(
                    f"kv-handoff pool dtype mismatch: blob "
                    f"{state.k.dtype}, target {self.paged.k.dtype}"
                )
        except KVWireError as e:
            # _admit wraps as "admission failed: kv-handoff ..." — the
            # coordinator matches the marker and re-routes cleanly.
            raise RuntimeError(f"kv-handoff rejected: {e}") from e
        prompt_len = state.prompt_len
        request.timings.prompt_tokens = prompt_len
        max_new = max(
            1,
            min(request.max_new_tokens, cfg.max_new_tokens_cap,
                cfg.max_seq_len - 1 - self._gamma_max),
        )
        total_len = prompt_len + max_new
        if total_len + self._gamma_max > cfg.max_seq_len:
            raise RuntimeError(
                f"kv-handoff rejected: prompt_len {prompt_len} + max_new "
                f"{max_new} exceeds this worker's position budget "
                f"({cfg.max_seq_len})"
            )
        need = -(-(total_len + self._gamma_max) // cfg.page_size)
        if self._faults is not None:
            self._faults.maybe_raise(
                "alloc-fail", AllocationError, replica=self.replica_id,
                tier=self._tier,
            )
        pages = self.allocator.alloc(need)
        P = cfg.pages_per_seq
        n_kv = state.num_pages
        idx = np.zeros((P,), np.int32)     # pad rows → garbage page 0
        idx[:n_kv] = pages[:n_kv]

        def _pad(arr: np.ndarray) -> np.ndarray:
            out = np.zeros((arr.shape[0], P) + arr.shape[2:], arr.dtype)
            out[:, :n_kv] = arr
            return out

        try:
            put = partial(jax.device_put, device=self._repl)
            operands = [put(idx), put(_pad(state.k)), put(_pad(state.v))]
            if self._kv_quantized:
                operands += [put(_pad(state.ks)), put(_pad(state.vs))]
            # _host_crossing: the padded page payload rides up as one
            # deliberate upload (the handoff's whole point).
            with _host_crossing("handoff-restore"):
                self.paged = self._jit_kv_restore(self.paged, *operands)
        except Exception as e:
            self.allocator.release_all(pages)
            raise RuntimeError(f"kv-handoff restore failed: {e}") from e
        if request.trace is not None:
            request.trace.child(
                "queue_wait",
                start=request.timings.enqueued,
                end=request.timings.prefill_start,
            )
        seed = state.seed & 0xFFFFFFFFFFFFFFFF
        seed_row = np.array(
            [(seed >> 32) & 0xFFFFFFFF, seed & 0xFFFFFFFF], np.uint32
        ).view(np.int32)
        slot = _Slot(request=request, pages=pages, position_cap=total_len)
        slot.generated = 1
        slot.seed_row = seed_row
        slot.prompt_len = prompt_len
        slot.prompt_ids = np.asarray(state.prompt_ids, np.int32)
        self._slots[slot_idx] = slot
        token = int(state.first_token)
        seq_len = prompt_len + 1
        live = token != self.tokenizer.eos_id and seq_len < total_len
        # Host mirrors become the source of truth; the dirty flag folds
        # them (and the restored pool) in before the next dispatch —
        # the same full-transition discipline as recovery.
        table = np.zeros((P,), np.int32)
        table[:len(pages)] = pages
        self._page_tables[slot_idx] = table
        self._seq_lens[slot_idx] = seq_len
        self._last_tokens[slot_idx] = token
        self._caps[slot_idx] = total_len
        self._temperature[slot_idx] = request.temperature
        self._top_p[slot_idx] = request.top_p
        self._top_k[slot_idx] = self._eff_top_k(request)
        self._seeds[slot_idx] = seed_row
        self._active[slot_idx] = live
        slot.merged = live
        self._dev_dirty = True
        if self.timeline is not None:
            self.timeline.admit(
                slot_idx, self._trace_id_of(request), prompt_len
            )
            self.timeline.note(
                "handoff_restore", slot=slot_idx, pages=n_kv,
                seq_len=seq_len,
            )
        request.timings.first_token = time.monotonic()
        slot.last_emit = request.timings.first_token
        if self.timeline is not None:
            self.timeline.slot_start(slot_idx, self._trace_id_of(request))
        if request.trace is not None:
            request.trace.child(
                "prefill",
                start=request.timings.prefill_start,
                end=request.timings.first_token,
                prompt_tokens=prompt_len, handoff=True,
            )
            slot.decode_span = request.trace.child(
                "decode", start=request.timings.first_token
            )
        request.out.put(("token", token))
        self._maybe_finish(slot_idx, token)
        return None

    def _drain_inflight(self) -> None:
        """Process every in-flight block and deliver every pending first
        token — the full pipeline flush that must precede any mirror
        upload (rare: cold start and failure recovery)."""
        while self._inflight_q:
            self._process_step(self._inflight_q.popleft())
        self._resolve_prefills(block=True)

    # -- host-memory KV tier (ISSUE 15) --------------------------------------

    def _params_fingerprint(self, seed: int) -> str:
        """Fingerprint of everything that determines KV content, gating
        durable prefix reloads: a state dir written under one set of
        weights must never warm an engine serving another. Explicit
        caller-provided params hash as a flag only — the supervisor's
        restart factory replays the same object, which is the contract
        that makes the flag sufficient there; callers mixing state dirs
        across different explicit weights are on their own (DEPLOY.md)."""
        import hashlib as _hashlib

        basis = (
            self.config.model, self.config.dtype, self.config.kv_dtype,
            self.config.quantize, self.config.quantize_bits,
            self.config.checkpoint_path or "",
            -1 if (self._params_explicit or self.config.checkpoint_path)
            else seed,
            self._params_explicit,
            self.config.page_size,
        )
        return _hashlib.blake2b(
            repr(basis).encode(), digest_size=8
        ).hexdigest()

    def _note_sched_frontier(self, frontier: str, served: list) -> None:
        """Starvation-witness hook (schedlint SL006): record one
        dispatch boundary — the slots this frontier served and the
        slots that were ELIGIBLE for it but got nothing (faulting slots
        at the restore frontier, pending-prefill resident slots at the
        prefill frontier). One predicate call when the witness is not
        armed (POLYKEY_SCHED_WITNESS=1)."""
        if not _schedwitness.installed():
            return
        if frontier == "restore":
            waiting = [
                i for i, s in enumerate(self._slots)
                if s is not None and s.restore_pages is not None
            ]
        else:
            waiting = [
                i for i, s in enumerate(self._slots)
                if s is not None and s.pending is not None
                and s.restore_pages is None
            ]
        _schedwitness.note(frontier, served, waiting)

    def _issue_restores(self) -> int:
        """The restore frontier: issue host→device page scatters for up
        to `host_kv_restore_slots` FAULTING slots, round-robin ahead of
        this iteration's prefill/decode dispatches. A faulting lane
        joins no dispatch until its restore has issued; once it has, the
        pool donation chain orders the restored contents ahead of every
        dispatch that could read them — so a resident lane never waits
        on a faulting one, and a faulting lane never needs a host sync
        to know its pages landed (page-aware scheduling, PersistentKV
        shape). Returns the number of slots restored."""
        if self._host_kv is None:
            return 0
        issued = 0
        served: list = []
        B = len(self._slots)
        # Round-robin from the cursor (the shared _RRCursor
        # discipline): admissions always fill the lowest free index, so
        # a 0-based scan would let fresh low-index faults starve a
        # high-index faulting slot of the per-iteration budget.
        for i in self._restore_rr.scan(B):
            slot = self._slots[i]
            if slot is None or slot.restore_pages is None:
                continue
            if issued >= self._restore_slots and issued > 0:
                # Progress floor (schedlint SL001): the `issued > 0`
                # conjunct proves at least one scatter rode this
                # iteration before the budget can wedge the frontier —
                # previously implicit in the >=1 clamp on the knob,
                # which a mis-tuned live actuation could have violated.
                self._restore_rr.reanchor(i)    # starved goes first next
                self._note_sched_frontier("restore", served)
                return issued
            if slot.request.cancelled.is_set():
                self._finish(i, error="cancelled")
                continue
            self._restore_slot_pages(i, slot)
            issued += 1
            served.append(i)
        self._restore_rr.advance(B)
        self._note_sched_frontier("restore", served)
        return issued

    def _restore_slot_pages(self, slot_idx: int, slot: _Slot) -> None:
        """One faulting slot's restore: copy its host pages into the
        fixed-width upload buffers, scatter them into the slot's own
        device pages (`_jit_kv_restore`, pool donated — ONE executable,
        shared with the ISSUE 13 handoff restore), then promote the
        prefix-cache entries so later lookups hit device tier."""
        items = slot.restore_pages
        assert items
        cfg = self.config
        t0 = time.monotonic()
        P = cfg.pages_per_seq
        pool = self._host_kv
        idx = np.zeros((P,), np.int32)        # pad rows → garbage page 0
        k = np.zeros((self.model_cfg.num_layers, P, cfg.page_size,
                      self.model_cfg.num_kv_heads,
                      self.model_cfg.head_dim), self.paged.k.dtype)
        v = np.zeros_like(k)
        ks = vs = None
        if self._kv_quantized:
            ks = np.zeros(k.shape[:-1], jnp.dtype(jnp.bfloat16))
            vs = np.zeros_like(ks)
        for r, (key, host_page, chain_idx) in enumerate(items):
            idx[r] = slot.pages[chain_idx]
            hk, hv, hks, hvs = pool.read(host_page)
            k[:, r] = hk
            v[:, r] = hv
            if self._kv_quantized:
                ks[:, r] = hks
                vs[:, r] = hvs
        try:
            put = partial(jax.device_put, device=self._repl)
            operands = [put(idx), put(k), put(v)]
            if self._kv_quantized:
                operands += [put(ks), put(vs)]
            # _host_crossing: the page payload rides up as one
            # deliberate upload — the page fault's whole point.
            with _host_crossing("kv-fault-restore"):
                self.paged = self._jit_kv_restore(self.paged, *operands)
        except Exception as e:
            # Host copies are untouched on failure; _finish re-adopts
            # them into the cache so the warmth survives this slot.
            self._finish(slot_idx, error=f"kv restore failed: {e}")
            return
        for key, host_page, chain_idx in items:
            pool.release(host_page)
            # Re-register under the slot's device page (detached at
            # admission); a racing re-insert of the same prefix wins
            # harmlessly — our copy still serves this slot.
            self._prefix.reinsert_device(key, slot.pages[chain_idx])
        slot.restore_pages = None
        ms = (time.monotonic() - t0) * 1e3
        trace_id = self._trace_id_of(slot.request)
        self.metrics.on_kv_restore(len(items), ms, trace_id=trace_id)
        if self.timeline is not None:
            self.timeline.note(
                "kv_restore", slot=slot_idx, pages=len(items),
                ms=round(ms, 3), trace=trace_id,
            )

    def _spill_for(self, target_free: int) -> int:
        """Cold-page offload: spill LRU device-tier prefix entries into
        the host pool until the allocator has `target_free` free pages
        or no spillable entries remain. A spilled page whose content is
        also shared by a live slot frees only when that slot retires —
        the loop re-reads num_free rather than counting. Returns pages
        spilled."""
        if self._host_kv is None or self._prefix is None:
            return 0
        spilled = 0
        P = self.config.pages_per_seq
        while self.allocator.num_free < target_free:
            cands = self._prefix.spill_candidates(P)
            if not cands:
                break
            spilled += self._spill_batch(cands)
        return spilled

    def _spill_batch(self, cands: list) -> int:
        """Gather one batch of cold pages (≤ pages_per_seq — the fixed
        gather width) to host in ONE dispatch + one packed D2H read,
        move each into the host pool (LRU-dropping host entries under
        cap pressure), and write the batch through to the durable state
        dir when configured."""
        cfg = self.config
        P = cfg.pages_per_seq
        idx = np.zeros((P,), np.int32)
        idx[:len(cands)] = [page for _, page in cands]
        outs = self._jit_kv_gather(self.paged, jax.device_put(idx, self._repl))
        with _host_crossing("kv-evict-gather"):
            # polylint: disable=PL008(eviction gather resolve: one packed D2H read per spill batch; cold path, reached from dispatch only via _finish under the resident-floor check)
            k = np.asarray(outs[0])
            # polylint: disable=PL008(spill gather read, same cold path)
            v = np.asarray(outs[1])
            ks = vs = None
            if self._kv_quantized:
                # polylint: disable=PL008(spill gather read, same cold path)
                ks = np.asarray(outs[2])
                # polylint: disable=PL008(spill gather read, same cold path)
                vs = np.asarray(outs[3])
        moved: list[tuple[bytes, int]] = []   # (key, gather row)
        for r, (key, _page) in enumerate(cands):
            try:
                host_page = self._host_kv.alloc()
            except AllocationError:
                # Host tier full: LRU pressure — drop the coldest host
                # entry to make room; an empty host LRU means the tier
                # is smaller than this batch, so the entry is dropped
                # outright (forgotten, recomputed on next use).
                if self._prefix.pop_lru_host() is None:
                    self._prefix.drop(key)
                    continue
                host_page = self._host_kv.alloc()
            self._host_kv.write(
                host_page, k[:, r], v[:, r],
                ks[:, r] if ks is not None else None,
                vs[:, r] if vs is not None else None,
            )
            self._prefix.mark_host(key, host_page)
            moved.append((key, r))
        if moved:
            self.metrics.on_kv_evict(len(moved))
            if self.timeline is not None:
                self.timeline.note("kv_evict", pages=len(moved))
            if self._kv_state is not None:
                rows = [r for _, r in moved]
                self._kv_state.save_batch(
                    [key for key, _ in moved],
                    k[:, rows], v[:, rows],
                    ks[:, rows] if ks is not None else None,
                    vs[:, rows] if vs is not None else None,
                )
                # Amortized gc: the cap is approximate anyway (oldest
                # batches beyond ~capacity), so a dir scan every 16
                # batches bounds the overshoot without paying listdir +
                # sidecar parses on every retire-pressure spill.
                self._kv_gc_countdown -= 1
                if self._kv_gc_countdown <= 0:
                    self._kv_state.gc(self._host_kv.capacity)
                    self._kv_gc_countdown = 16
        return len(moved)

    def _advance_chunked_prefills(self, budget: Optional[int]) -> int:
        """Advance slots mid-chunked-prefill, round-robin from the
        `_chunk_rr` cursor, one chunk per slot per call, until the token
        budget is spent (None → every pending slot advances one chunk —
        the no-live-decode fast path). The FIRST chunk always dispatches
        regardless of budget (progress floor: the budget bounds decode
        stalls, it must never wedge a long prompt). Returns prefill
        tokens dispatched."""
        spent = 0
        served: list = []
        B = len(self._slots)
        for i in self._chunk_rr.scan(B):
            s = self._slots[i]
            if s is None or s.pending is None:
                continue
            if s.restore_pages is not None:
                # Faulting slot: its prefix pages are not in flight yet
                # — it joins no dispatch until the restore frontier
                # issues its scatter (_issue_restores).
                continue
            if budget is not None and spent > 0 and spent >= budget:
                # Leave the cursor ON the starved slot so it goes first
                # next iteration.
                self._chunk_rr.reanchor(i)
                self._note_sched_frontier("prefill", served)
                return spent
            charged = self._prefill_one_chunk(i)
            if charged:
                served.append(i)
            spent += charged
        self._chunk_rr.advance(B)
        self._note_sched_frontier("prefill", served)
        return spent

    def _prefill_one_chunk(self, slot_idx: int) -> int:
        """Advance a long-prompt slot by one fixed-size chunk; the final
        chunk samples the first token and activates the slot. Returns
        the charged prefill width — one full chunk window when a
        dispatch issued (the budget charges at chunk granularity even
        for a partial final chunk), 0 when the slot exited without
        dispatching (cancelled / deadline-expired / prefill failure),
        so quota accounting (schedlint SL005) never bills tokens that
        never rode a dispatch."""
        slot = self._slots[slot_idx]
        assert slot is not None and slot.pending is not None
        request = slot.request
        if request.cancelled.is_set():
            self._finish(slot_idx, error="cancelled")
            return 0
        if self._deadline_expired(request):
            # Expired mid-prefill: remaining chunks never dispatch.
            self.metrics.on_deadline_expired("prefill")
            self._finish(slot_idx, error=f"{DEADLINE_MSG} during prefill")
            return 0
        C = self._chunk
        prompt_len = len(slot.pending)
        take = min(C, prompt_len - slot.filled)
        tokens = np.zeros((1, C), dtype=np.int32)
        tokens[0, :take] = slot.pending[slot.filled:slot.filled + take]
        final = slot.filled + take >= prompt_len
        try:
            token_dev = self._run_prefill(
                tokens, slot.filled, take - 1, slot.table, request,
                slot.seed_row,
            )
        except Exception as e:
            self._finish(slot_idx, error=f"prefill failed: {e}")
            return 0
        if self.timeline is not None:
            self.timeline.prefill(slot_idx, take, final)
        # The chunk window is C tokens wide; `take` carried real ones.
        self.metrics.on_padding_tokens(C, take)
        if final:
            # The final chunk's sampled token activates the lane (on-device
            # merge; the host delivers it to the client once its async copy
            # lands). Non-final chunks never sync at all — the device token
            # is discarded.
            self._merge_slot(slot_idx, slot, token_dev, 0)
        else:
            slot.filled += take
        return C

    def _upload_slot_state(self) -> None:
        self._dev = {
            "last_tokens": jax.device_put(self._last_tokens, self._dp_vec),
            "seq_lens": jax.device_put(self._seq_lens, self._dp_vec),
            "page_tables": jax.device_put(self._page_tables, self._dp_mat),
            "active": jax.device_put(self._active, self._dp_vec),
            "caps": jax.device_put(self._caps, self._dp_vec),
            "temperature": jax.device_put(self._temperature, self._dp_vec),
            "top_p": jax.device_put(self._top_p, self._dp_vec),
            "top_k": jax.device_put(self._top_k, self._dp_vec),
            "seeds": jax.device_put(self._seeds, self._dp_mat),
        }
        if self._spec:
            # Per-lane gamma dial (ISSUE 19): device-resident like the
            # rest of the slot state; the mirrors were refreshed from the
            # last processed round's packed stat columns.
            self._dev["accept_ewma"] = jax.device_put(
                self._lane_ewma, self._dp_vec
            )
            self._dev["gamma_lane"] = jax.device_put(
                self._lane_gamma, self._dp_vec
            )
        self._dev_dirty = False

    def _dispatch_step(self):
        """Dispatch one decode block (or spec round) without waiting for it;
        returns an opaque record for _process_step. Between dispatch and
        process the engine resolves pending prefills, overlapping their
        device time with the block's."""
        if self._faults is not None:
            # Stand-ins for a wedged (step-stall) or degraded (slow-step)
            # device call: they block the engine thread exactly where the
            # real dispatch would, so the watchdog's no-progress clock
            # sees the genuine failure shape.
            self._faults.maybe_sleep("step-stall", replica=self.replica_id, tier=self._tier)
            self._faults.maybe_sleep("slow-step", replica=self.replica_id, tier=self._tier)
        if self._dev_dirty:
            # Rare (init / retire-failure recovery): mirrors must be
            # complete before they become the device state — deliver any
            # pending first tokens so _last_tokens is exact (the loop has
            # already drained in-flight blocks).
            self._resolve_prefills(block=True)
            self._upload_slot_state()
        # top_p composes with speculation via truncated rejection sampling
        # (sampling.truncated_dist), which needs the top-k prefilter
        # (top_p_candidates > 0) to avoid full-vocab sorts. Without the
        # prefilter, a batch containing any top_p<1 row takes the plain
        # step; note that blast radius is batch-wide — speculation is off
        # for every slot while such a row is active, and the plain steps
        # leave draft-cache holes, so acceptance stays collapsed for
        # surviving streams afterwards. Correctness never degrades.
        # Greedy rows neutralize top_p inside the round (eff_top_p), so
        # only SAMPLED rows with top_p<1 require the truncated variant.
        act = self._active
        all_untruncated = bool(np.all(
            ((self._top_p[act] >= 1.0) & (self._top_k[act] <= 0))
            | (self._temperature[act] == 0.0)
        ))
        spec_on = self._spec and (
            self.config.top_p_candidates > 0 or all_untruncated
        )
        if self._ragged:
            # Ragged mode (ISSUE 12): any pending prefill work rides ONE
            # mixed dispatch with the decode lanes' single tokens; pure-
            # decode iterations fall through to the K-step block (or spec
            # round) below (the PR 6 amortization is untouched at steady
            # state). Spec engines (ISSUE 19): the same mixed dispatch
            # carries the verify windows — prefill chunks, plain decode
            # lanes, and gamma-token spec lanes in ONE ragged call; the
            # gate-fail fallback (no prefilter + truncated sampled row)
            # keeps the plain ragged dispatch, trading acceptance, never
            # correctness.
            ranges = self._build_ragged_batch()
            if ranges:
                block = (
                    self._dispatch_ragged_spec(ranges)
                    if spec_on else self._dispatch_ragged(ranges)
                )
                if block is not None:
                    return block
            if not self._active.any():
                # Prefill-only iteration that dispatched nothing (e.g.
                # contained failure): no decode block to fall through to.
                return None
            # A contained failure may have retired lanes; refresh the
            # active view for the lane counts below (the spec gate only
            # ever loses truncated rows to a retirement, so `spec_on`
            # stays valid).
            act = self._active
        dev = self._dev
        if spec_on:
            spec_candidates = (
                0 if all_untruncated else self.config.top_p_candidates
            )
            # Spec rounds: full-size blocks; >= 1 token lands per round,
            # so `remaining` rounds always suffice (same tail-work cap
            # as the plain path).
            self._depth_target = min(
                self._depth, max(1, self._remaining_budget(act))
            )
            # Occupancy tracker: a spec round's scan length is gamma
            # draft steps + one verify — the step weight that makes its
            # lane-seconds comparable to a plain K-step block's.
            lanes = int(act.sum())
            gap_ms = self.metrics.on_dispatch(
                lanes, self._gamma + 1, slots=len(self._slots)
            )
            live = tuple(int(i) for i in np.flatnonzero(act))
            data = self._dispatch_spec(dev, spec_candidates)
            self._dispatch_seq += 1
            if self.timeline is not None:
                self.timeline.dispatch(
                    self._dispatch_seq, "spec", lanes, self._gamma + 1,
                    gap_ms,
                )
            return _InflightBlock(
                "spec", data, self._snapshot_requests(), self._dispatch_seq,
                gap_ms, live,
            )
        # Static variant: an all-greedy batch (the benchmark mode) skips
        # sample_dynamic's [B, vocab] sort and all RNG work. At most two
        # compiled variants exist; the mix flips only at slot transitions.
        greedy = bool(np.all(self._temperature[self._active] == 0.0))
        # Load-adaptive K: one active stream → small blocks (per-token
        # delivery at the device's step rate); more → the full block.
        steps = (
            self._solo_steps if int(act.sum()) == 1 else self._block_steps
        )
        self._last_dispatch_steps = steps
        # Constant steps-in-flight across block sizes — but never more
        # than the active streams still NEED: every in-flight step costs
        # a full weight-read on device even when its lanes have stopped,
        # so lookahead past the longest remaining budget burns device
        # time at stream tails and queues real latency in front of the
        # next arrival's prefill (a solo stream at K=2 used to keep 64
        # steps ≈ 0.9 s of dead work in flight).
        remaining = self._remaining_budget(act)
        blocks_needed = max(1, -(-remaining // max(1, steps)))
        # Scale only the LOOKAHEAD portion (depth - 1 queued blocks);
        # the +1 is the dispatch in hand. Deepening the whole depth
        # would let depth 1 — the documented synchronous escape hatch —
        # run ahead whenever adaptive blocking shrinks K (target 8 on a
        # solo stream), breaking the bit-identical-rollback contract on
        # any backend where readback isn't instant.
        self._depth_target = min(
            64,
            1 + (self._depth - 1) * (self._block_steps // max(1, steps)),
            blocks_needed,
        )
        lanes = int(act.sum())
        gap_ms = self.metrics.on_dispatch(lanes, steps, slots=len(self._slots))
        live = tuple(int(i) for i in np.flatnonzero(act))
        with jax.profiler.TraceAnnotation("polykey/decode"):
            (packed_dev, last_dev, seq_dev, act_dev,
             self.paged) = self._jit_decode(
                self.params,
                self.model_cfg,
                self.paged,
                dev["last_tokens"],
                dev["seq_lens"],
                dev["page_tables"],
                dev["active"],
                dev["caps"],
                dev["seeds"],
                dev["temperature"],
                dev["top_p"],
                dev["top_k"],
                greedy=greedy,
                steps=steps,
                eos_id=self.tokenizer.eos_id,
                candidates=self.config.top_p_candidates,
                mesh=self.mesh,
            )
            # Feed final state straight back as the next block's inputs;
            # host mirrors update in _process_step for bookkeeping.
            dev["last_tokens"] = last_dev
            dev["seq_lens"] = seq_dev
            dev["active"] = act_dev
        try:
            # Ship the block's packed tokens host-ward as soon as the
            # device finishes them; by processing time (lookahead_blocks
            # later) the read is then local.
            packed_dev.copy_to_host_async()
        except Exception:
            # Best-effort copy hint only: np.asarray at process time syncs
            # regardless, so a backend without async copies loses overlap,
            # not correctness.
            pass
        self._dispatch_seq += 1
        if self.timeline is not None:
            self.timeline.dispatch(
                self._dispatch_seq, "plain", lanes, steps, gap_ms
            )
        return _InflightBlock(
            "plain", packed_dev, self._snapshot_requests(), self._dispatch_seq,
            gap_ms, live,
        )

    def _eff_top_k(self, request: GenRequest) -> int:
        """Effective per-request top_k: with the top-k prefilter enabled
        (top_p_candidates = C > 0) every sampled path sees only the top-C
        logits, so a wider top_k clamps to C — applied at admission so
        the narrowing is a visible, documented contract
        (engine/config.py top_p_candidates) rather than a silent property
        of the sampler."""
        k = request.top_k
        C = self.config.top_p_candidates
        return min(k, C) if (C > 0 and k > 0) else k

    def _remaining_budget(self, act) -> int:
        """Longest remaining token budget over active lanes (host
        mirrors) — the tail-work cap both dispatch paths share."""
        return int(np.max(np.where(act, self._caps - self._seq_lens, 0)))

    def _note_block_token(self, slot: _Slot, block_span, before: int,
                          t_sync: float, **attrs):
        """Per-token block-span upkeep shared by the plain and spec
        process paths: lazily open the slot's decode_block child (only
        traced slots get one) and keep its token count and end time
        current. Called BEFORE the token (and any terminal event
        _maybe_finish enqueues) reaches the client — the gateway may
        snapshot the tree the moment the stream ends, and a child added
        after that snapshot would be lost."""
        if slot.decode_span is None:
            return None
        if block_span is None:
            # Clamp to the parent's start: when the slot's first token
            # resolved within THIS sync, t_sync predates the decode span
            # opened at first_token, and a child must not begin before
            # its parent in the rendered tree.
            block_span = slot.decode_span.child(
                "decode_block",
                start=max(t_sync, slot.decode_span.start),
                **attrs,
            )
        block_span.set(tokens=slot.generated - before)
        block_span.end = time.monotonic()
        return block_span

    def _note_block_done(self, slot: _Slot, before: int) -> None:
        """Post-block ITL accounting shared by both process paths: the
        window since the slot's previous emit, amortized per token."""
        n = slot.generated - before
        if n > 0:
            now = time.monotonic()
            if slot.last_emit > 0:
                self.metrics.on_itl(
                    (now - slot.last_emit) * 1e3 / n, n,
                    trace_id=self._trace_id_of(slot.request),
                )
            slot.last_emit = now

    def _snapshot_requests(self):
        """Per-slot request identities at dispatch time: with cross-block
        lookahead a slot can be finished (cancel) and re-admitted while its
        block is in flight, and the stale lane's tokens must never reach
        the new occupant."""
        return [s.request if s is not None else None for s in self._slots]

    def _block_ready(self, block) -> bool:
        """True when a dispatched block's result buffers have landed —
        its readback will not block the host. Conservative: a backend
        without is_ready() reports landed (the read then syncs, which is
        the pre-pipeline behavior — correctness over overlap)."""
        data = block[1]
        try:
            return data.is_ready()
        except Exception:
            # Justified: is_ready() is an optional backend capability —
            # "landed" is the safe answer (process path syncs regardless),
            # and an error here must never take the engine loop down.
            return True

    def _process_step(self, block) -> None:
        """Sync a dispatched block's results and emit/finish on the host.
        Slots activated between dispatch and process were not in the block:
        their device lanes were inactive, so their columns read -1.

        `block` is an _InflightBlock (legacy bare (kind, data, reqs)
        tuples still unpack — seq then defaults to the current dispatch
        frontier, i.e. observed lookahead 0)."""
        kind, data, reqs = block[0], block[1], block[2]
        seq = block[3] if len(block) > 3 else self._dispatch_seq
        gap_ms = block[4] if len(block) > 4 else 0.0
        live = block[5] if len(block) > 5 else ()
        # Observed lookahead: blocks dispatched after this one, before its
        # readback — ≥1 is the overlap the pipeline exists for; 0 is the
        # synchronous depth-1 shape. Recorded for every processed block
        # (the loop-trace test and engine_stats read it).
        lookahead = self._dispatch_seq - seq
        queued_after = len(self._inflight_q)
        if kind == "spec":
            # Spec rounds always sync: their device-computed acceptance
            # stats feed the gamma-tuning dial even when every occupant is
            # gone by processing time.
            self._process_spec(data, reqs, lookahead, seq=seq,
                               gap_ms=gap_ms, live=live,
                               queued_after=queued_after)
            return
        if not any(
            s is not None and s.request is reqs[i]
            for i, s in enumerate(self._slots)
        ):
            # Dead block: every dispatch-time occupant is gone (batch
            # drained / all cancelled). Nothing to emit — skip the sync
            # entirely so the drain costs no host↔device roundtrip (no
            # stall is recorded: nothing was read; no device time is
            # attributed: every lane's request already finished).
            self.metrics.on_process_block(lookahead, None)
            if self.timeline is not None:
                now = time.monotonic()
                self.timeline.process(seq, now, now, None, lookahead,
                                      queued_after, 0.0)
            return
        t_sync = time.monotonic()
        with _host_crossing("block-packed"):
            # polylint: disable=PL001(block resolve point; one packed D2H read per block), PL008(process-side read; reachable from dispatch only via the ragged merge's dev-dirty cold path, behind a full pipeline drain)
            packed = np.asarray(data)     # [K, B]; blocks until block done
        # Host stall: how long the processed frontier blocked waiting for
        # this block's copy to land — ~0 when lookahead hid the roundtrip,
        # ~roundtrip_ms when the host is on the critical path (the r03
        # signature this pipeline exists to erase).
        stall_ms = (time.monotonic() - t_sync) * 1e3
        self.metrics.on_process_block(
            lookahead, stall_ms, trace_id=self._block_trace_id(reqs, live)
        )
        busy_ms = self._attribute_device_time(gap_ms, stall_ms, live, reqs)

        emitted = 0
        for i, slot in enumerate(self._slots):
            if slot is None or not self._active[i] or slot.request is not reqs[i]:
                continue
            if slot.request.cancelled.is_set():
                self._finish(i, error="cancelled")
                continue
            if self._deadline_expired(slot.request):
                # Block-boundary deadline drop: the lane retires now, so
                # no further block computes for a client that is gone.
                self.metrics.on_deadline_expired("decode")
                self._finish(i, error=f"{DEADLINE_MSG} mid-decode")
                continue
            if slot.token_dev is not None:
                # First token precedes block tokens in the client stream
                # (its copy landed with the prefill, before this block).
                self._resolve_slot(i, slot)
                if self._slots[i] is not slot:
                    continue
            # The block's own [K, B] shape, not the configured K — the
            # adaptive dispatcher varies K per block.
            before = slot.generated
            block_span = None
            for k in range(packed.shape[0]):
                token = int(packed[k, i])
                if token < 0:
                    break
                slot.generated += 1
                self._seq_lens[i] += 1
                self._last_tokens[i] = token
                block_span = self._note_block_token(
                    slot, block_span, before, t_sync,
                    steps=int(packed.shape[0]),
                )
                slot.request.out.put(("token", token))
                emitted += 1
                self._maybe_finish(i, token)
                if self._slots[i] is None:  # finished mid-block
                    break
            self._note_block_done(slot, before)
        self.metrics.on_step(emitted)
        if self.timeline is not None:
            self.timeline.process(seq, t_sync, time.monotonic(), stall_ms,
                                  lookahead, queued_after, busy_ms)

    def _block_trace_id(self, reqs, live) -> Optional[str]:
        """A trace id to exemplar block-level observations with: the
        first traced request live in the block (any live request is an
        honest witness for a shared stall)."""
        for i in live:
            trace_id = self._trace_id_of(reqs[i])
            if trace_id is not None:
                return trace_id
        return None

    def _attribute_device_time(self, gap_ms: float, stall_ms: float,
                               live, reqs) -> float:
        """Per-request device-time attribution (ISSUE 10): charge this
        block's device-busy window — the host gap that preceded its
        dispatch minus the host stall its readback cost — equally to the
        lanes live at dispatch, into each request's timings.device_ms.

        The dispatch gap approximates the block's device residency
        (dispatches serialize on the device through the pool donation
        chain, so at steady state consecutive dispatches tile the
        device's schedule); subtracting the measured stall removes the
        host's share. Conservation: Σ busy ≤ Σ counted gaps ≤ wall, so
        Σ per-request device_ms can never exceed wall × slots — and on
        a single-lane run the one request receives exactly
        device_busy_ms_total (both pinned by tests/test_timeline.py).
        Returns the busy ms charged (0.0 when nothing was)."""
        if not live or gap_ms <= 0.0:
            return 0.0
        busy = gap_ms - max(0.0, stall_ms)
        if busy <= 0.0:
            return 0.0
        self.metrics.on_device_busy(busy)
        share = busy / len(live)
        for i in live:
            request = reqs[i]
            if request is not None:
                request.timings.device_ms += share
        return busy

    def _dispatch_spec(self, dev: dict, candidates: int = 0):
        """Dispatch one draft/verify round (spec_decode.py). `candidates`
        is 0 when every active row has top_p >= 1 — the round then skips
        all truncation work (plain softmax dists). The round is fully
        device-resident (ISSUE 19): acceptance stats and the per-lane
        gamma dial ride the packed matrix's stat columns, so the block
        boundary costs ONE D2H read, same as a plain block."""
        with jax.profiler.TraceAnnotation("polykey/spec_decode"):
            (packed_dev, new_last, new_seq, new_active, new_ewma,
             new_gamma, self.paged, self.d_paged) = self._jit_spec_decode(
                self.params, self.draft_params,
                self.model_cfg, self.draft_cfg,
                self.paged, self.d_paged,
                dev["last_tokens"], dev["seq_lens"], dev["page_tables"],
                dev["active"], dev["caps"], dev["seeds"],
                dev["temperature"], dev["top_p"], dev["top_k"],
                dev["accept_ewma"], dev["gamma_lane"],
                gamma=self._gamma,
                eos_id=self.tokenizer.eos_id,
                gamma_low=self._gamma_low, gamma_max=self._gamma_max,
                candidates=candidates, mesh=self.mesh,
            )
            dev["last_tokens"] = new_last
            dev["seq_lens"] = new_seq
            dev["active"] = new_active
            dev["accept_ewma"] = new_ewma
            dev["gamma_lane"] = new_gamma
        try:
            packed_dev.copy_to_host_async()
        except Exception:
            # Best-effort copy hint only: _process_spec's np.asarray syncs
            # regardless; backends without async copies lose overlap only.
            pass
        if self.config.spec_host_sync:
            # A/B instrumentation (scripts/occupancy_soak.py --ab-spec):
            # emulate the pre-ISSUE-19 host-loop spec round — three
            # synchronous readbacks per round on the device-resident
            # math, so the A/B isolates the crossing schedule, not the
            # arithmetic. Each timed read lands in the host-stall
            # accounting (metrics.on_spec_host_sync). Never enabled in
            # production.
            for _ in range(3):
                t_sync = time.monotonic()
                with _host_crossing("spec-host-sync"):
                    # polylint: disable=PL001(spec_host_sync A/B emulation of the pre-ISSUE-19 host-loop round; off in production), PL008(the blocking dispatch-side read IS the measured subject here)
                    np.asarray(packed_dev)
                self.metrics.on_spec_host_sync(
                    (time.monotonic() - t_sync) * 1e3
                )
        return packed_dev

    def _process_spec(self, data, reqs, lookahead: int = 0, seq: int = 0,
                      gap_ms: float = 0.0, live: tuple = (),
                      queued_after: int = 0) -> None:
        """Sync a spec round; emits each row's packed prefix (-1 padded —
        device-truncated). Acceptance stats AND the per-lane gamma dial
        come FROM the device inside the same packed matrix (ISSUE 19:
        spec_decode._accept_merge owns truncation, the untruncated n_acc,
        and the dial update) — ONE D2H read per round, exactly like a
        plain block's packed readback."""
        packed_dev = data
        t_sync = time.monotonic()
        with _host_crossing("spec-packed"):
            # polylint: disable=PL001(spec-round resolve point; the ONE packed D2H read carries tokens, counts, and the gamma dial), PL008(process-side read; dispatch reaches it only via the merge drain cold path)
            packed = np.asarray(packed_dev)  # [B, gamma+1+SPEC_STAT_COLS]
        stall_ms = (time.monotonic() - t_sync) * 1e3
        # Stat columns (spec_decode.SPEC_STAT_COLS): per-lane accepted /
        # proposed counts, the acceptance EWMA in 1e-6 fixed point, and
        # the lane's next gamma dial.
        g1 = packed.shape[1] - 4
        acc_col, prop_col = packed[:, g1], packed[:, g1 + 1]
        ewma_col, dial_col = packed[:, g1 + 2], packed[:, g1 + 3]
        accepted, proposed = int(acc_col.sum()), int(prop_col.sum())
        for i, slot in enumerate(self._slots):
            # Mirror refresh gated on request identity: a stale lookahead
            # round must not overwrite a re-admitted lane's fresh dial
            # (the DEVICE copy is already correct — the merge reset
            # chained after this round's outputs).
            if slot is not None and slot.request is reqs[i]:
                self._lane_ewma[i] = ewma_col[i] / 1e6
                self._lane_gamma[i] = dial_col[i]
        self.metrics.on_process_block(
            lookahead, stall_ms, trace_id=self._block_trace_id(reqs, live)
        )
        busy_ms = self._attribute_device_time(gap_ms, stall_ms, live, reqs)

        emitted = 0
        for i, slot in enumerate(self._slots):
            if slot is None or not self._active[i] or slot.request is not reqs[i]:
                continue
            if slot.request.cancelled.is_set():
                self._finish(i, error="cancelled")
                continue
            if self._deadline_expired(slot.request):
                self.metrics.on_deadline_expired("decode")
                self._finish(i, error=f"{DEADLINE_MSG} mid-decode")
                continue
            if slot.token_dev is not None:
                self._resolve_slot(i, slot)
                if self._slots[i] is not slot:
                    continue
            before = slot.generated
            block_span = None
            for j in range(g1):
                token = int(packed[i, j])
                if token < 0:
                    break
                slot.generated += 1
                self._seq_lens[i] += 1
                self._last_tokens[i] = token
                block_span = self._note_block_token(
                    slot, block_span, before, t_sync, spec_round=True,
                )
                slot.request.out.put(("token", token))
                emitted += 1
                self._maybe_finish(i, token)
                if self._slots[i] is None:   # finished mid-window
                    break
            self._note_block_done(slot, before)
        self.metrics.on_step(emitted)
        if self.timeline is not None:
            self.timeline.process(seq, t_sync, time.monotonic(), stall_ms,
                                  lookahead, queued_after, busy_ms)
        self.metrics.on_spec(accepted, proposed)
        if proposed > 0:
            # Batch-aggregate EWMA, observability only (the per-lane dial
            # updated on DEVICE; see spec_decode._accept_merge). Same
            # blend as the per-lane one so operators can sanity-check the
            # lane spread against a familiar aggregate.
            from .spec_decode import GAMMA_EWMA_BETA

            rate = accepted / proposed
            self._accept_ewma = (
                GAMMA_EWMA_BETA * self._accept_ewma
                + (1.0 - GAMMA_EWMA_BETA) * rate
            )
        # Dispatch width: the ladder rung covering the widest ACTIVE lane
        # dial (a lane at gamma_low costs nothing extra when batchmates
        # need gamma_max — its surplus drafts are force-masked on
        # device), clamped by the autopilot's cap. Both rungs are
        # warmup-compiled; no new executables.
        if self._spec:
            act = [
                i for i, s in enumerate(self._slots)
                if s is not None and self._active[i]
            ]
            want = (
                int(self._lane_gamma[act].max()) if act else self._gamma_max
            )
            rung = (
                self._gamma_max if want > self._gamma_low
                else self._gamma_low
            )
            self._gamma = min(rung, self._gamma_cap)

    def _maybe_finish(self, slot_idx: int, token: int) -> None:
        slot = self._slots[slot_idx]
        assert slot is not None
        request = slot.request
        hit_eos = token == self.tokenizer.eos_id
        hit_cap = (
            slot.generated >= request.max_new_tokens
            or slot.generated >= self.config.max_new_tokens_cap
            or int(self._seq_lens[slot_idx]) >= slot.position_cap
        )
        if hit_eos or hit_cap:
            self._finish(slot_idx)

    def _finish(self, slot_idx: int, error: Optional[str] = None) -> None:
        slot = self._slots[slot_idx]
        if slot is None:
            return
        request = slot.request
        request.timings.finished = time.monotonic()
        request.timings.completion_tokens = slot.generated
        if self.timeline is not None:
            self.timeline.slot_end(
                slot_idx,
                "cancelled" if error == "cancelled"
                else ("error" if error is not None else "done"),
                slot.generated,
            )
        if slot.decode_span is not None:
            slot.decode_span.set(tokens=slot.generated)
            slot.decode_span.finish(end=request.timings.finished)
        if request.trace is not None and request.timings.device_ms > 0:
            # Attribution rides the span tree too: the root span carries
            # the request's accumulated device time so a flight-recorder
            # tree answers "device or host?" without cross-referencing.
            request.trace.set(device_ms=round(request.timings.device_ms, 3))
        if request.trace is not None and error is not None:
            # Cancellation is not a failure label: the gateway cancels on
            # stop-sequence matches and client disconnects, both of which
            # end the RPC cleanly (tpu_service._text_events calls the
            # engine's "cancelled" the EXPECTED outcome). A postmortem
            # reader must not chase phantom errors on stop-terminated
            # requests.
            if error == "cancelled":
                request.trace.set(cancelled=True)
            else:
                request.trace.set(error=error)
        if slot.restore_pages:
            # Died faulting (cancel/deadline/failure before its restore
            # issued): the slot owns these host pages — re-adopt them
            # into the cache so the warmth survives the slot; a key
            # re-cached meanwhile keeps its copy and ours frees.
            for key, host_page, _ci in slot.restore_pages:
                if self._prefix is None or \
                        not self._prefix.adopt_host(key, host_page):
                    self._host_kv.release(host_page)
            slot.restore_pages = None
        self.allocator.release_all(slot.pages)
        self._slots[slot_idx] = None
        self._active[slot_idx] = False
        self._caps[slot_idx] = 0
        self._seq_lens[slot_idx] = 0
        self._last_tokens[slot_idx] = 0
        self._page_tables[slot_idx] = 0
        self._seeds[slot_idx] = 0
        if slot.merged and self.dead is None and not self._stop.is_set():
            # Retire the device lane (stop stale-table writes) without
            # flushing the pipeline — a tiny chained dispatch, the mirror
            # of _merge_slot. EOS/cap retirements already stopped on
            # device; this also covers cancellations and failures.
            dev = self._dev
            try:
                # _host_crossing: the slot index rides as a numpy scalar.
                with _host_crossing("retire-upload"):
                    (
                        dev["last_tokens"], dev["seq_lens"], dev["page_tables"],
                        dev["active"], dev["caps"],
                    ) = self._jit_retire(
                        dev["last_tokens"], dev["seq_lens"], dev["page_tables"],
                        dev["active"], dev["caps"], np.int32(slot_idx),
                    )
            except Exception as e:
                # Retire is an optimization; the dirty flag's full mirror
                # re-upload is the correct fallback — but a recurring
                # failure here means every finish flushes the pipeline,
                # so leave a trace for the postmortem reader.
                if self.logger is not None:
                    self.logger.warn(
                        "lane retire failed; falling back to full "
                        "mirror re-upload", slot=slot_idx, error=str(e),
                    )
                self._dev_dirty = True
        if self._host_kv is not None and self.dead is None \
                and not self._stop.is_set() \
                and self.allocator.num_free < self._resident_low:
            # Eviction at retire (ISSUE 15): the request just released
            # its pages; if the free list is still below the resident
            # working-set floor, the pool is crowded with COLD pages —
            # spill LRU prefix entries to host now, off any admission's
            # critical path, so the next burst allocates without paying
            # the gather synchronously.
            self._spill_for(self._resident_low)
        if error is not None:
            request.out.put(("error", error))
            self.metrics.on_finish(request.timings, failed=True,
                                   trace_id=self._trace_id_of(request))
        else:
            request.out.put(("done", request.timings))
            self.metrics.on_finish(request.timings,
                                   trace_id=self._trace_id_of(request))

    def _fail_pending(self, message: str) -> None:
        try:
            while True:
                request = self._submit.get_nowait()
                request.out.put(("error", message))
        except queue.Empty:
            pass

    def _fail_all(self, message: str) -> None:
        self._inflight_q.clear()  # drop unprocessed lookahead results
        for i, slot in enumerate(self._slots):
            if slot is not None:
                self._finish(i, error=message)
        self._fail_pending(message)
