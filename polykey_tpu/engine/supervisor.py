"""Engine supervision: in-process restart instead of process recycling.

Before ISSUE 3 the failure story ended at the watchdog: one trip flipped
health to NOT_SERVING forever and the platform had to restart the whole
process — paying model load + warmup compiles and dropping every queued
request on the floor. The supervisor closes the loop in-process:

    watchdog trip / loop crash  →  engine.dead set
    supervisor notices          →  stop + drain the dead engine
                                   (in-flight requests failed cleanly)
                                →  build a fresh engine via the factory
                                →  re-arm the watchdog on it
                                →  health back to SERVING
                                →  flight-recorder "engine_restart" event
                                   + polykey_engine_restarts_total

Restarts are bounded: more than `max_restarts` inside `restart_window_s`
means the failure is not transient (bad checkpoint, broken device) — the
supervisor gives up, leaves health NOT_SERVING, and lets the platform
recycle the process per policy. That boundary is deliberate: in-process
restart handles transient faults cheaply; persistent faults still get
the full process restart the reference's compose healthcheck provides.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable


class EngineSupervisor:
    """Owns the live engine reference. `engine` is swapped atomically on
    restart; listeners (the TpuService) are told so their own reference
    follows."""

    def __init__(
        self,
        engine,
        factory: Callable[[], object],
        watchdog=None,
        health=None,
        logger=None,
        recorder=None,
        restart_counter=None,
        max_restarts: int = 3,
        restart_window_s: float = 600.0,
        check_interval_s: float = 0.5,
        join_timeout_s: float = 5.0,
    ):
        self.engine = engine
        self._factory = factory
        self.watchdog = watchdog
        self.health = health
        self.logger = logger
        self.recorder = recorder
        self.restart_counter = restart_counter
        self.max_restarts = max_restarts
        self.restart_window_s = restart_window_s
        self.check_interval_s = check_interval_s
        self.join_timeout_s = join_timeout_s
        self.restarts = 0
        self.gave_up = False
        self._restart_times: deque[float] = deque()
        self._listeners: list[Callable[[object], None]] = []
        self._giveup_listeners: list[Callable[[str], None]] = []
        self._trip_listeners: list[Callable[[object, str], None]] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="polykey-supervisor", daemon=True
        )

    def add_restart_listener(self, callback: Callable[[object], None]) -> None:
        """Called with the fresh engine after every successful restart
        (from the supervisor thread)."""
        self._listeners.append(callback)

    def add_giveup_listener(self, callback: Callable[[str], None]) -> None:
        """Called with the failure reason when the restart budget is
        exhausted and this supervisor stops trying (from the supervisor
        thread). The replica pool uses it to mark the replica DEAD while
        the rest of the pool keeps health SERVING — per-replica give-up
        instead of the single-engine whole-process NOT_SERVING."""
        self._giveup_listeners.append(callback)

    def add_trip_listener(
        self, callback: Callable[[object, str], None]
    ) -> None:
        """Called with (dead engine, reason) the moment the supervisor
        notices a trip — BEFORE the drain/restart/give-up path runs.
        Black boxes (ISSUE 16) hang a forced checkpoint here: the dying
        engine's timeline ring still exists at this point, and the
        moments before a trip are exactly what a postmortem needs."""
        self._trip_listeners.append(callback)

    def start(self) -> "EngineSupervisor":
        self._thread.start()
        return self

    def stop(self, join_timeout_s: float = 5.0) -> None:
        """Signal and (bounded) join: close() must not race a completing
        restart into swapping/reviving an engine on a terminating
        server. If the thread is mid-factory past the timeout, the
        in-restart `_stop` check shuts the fresh engine down itself."""
        self._stop.set()
        if self._thread.is_alive() and \
                threading.current_thread() is not self._thread:
            self._thread.join(timeout=join_timeout_s)

    # -- supervisor thread ---------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            engine = self.engine
            if engine.dead is None:
                continue
            for callback in self._trip_listeners:
                try:
                    callback(engine, engine.dead or "engine dead")
                except Exception:
                    pass  # a black-box flush must never break supervision
            if not self._budget_ok():
                self._give_up(engine.dead)
                return
            self._restart(engine)

    def _budget_ok(self) -> bool:
        now = time.monotonic()
        while self._restart_times and \
                now - self._restart_times[0] > self.restart_window_s:
            self._restart_times.popleft()
        return len(self._restart_times) < self.max_restarts

    def _give_up(self, reason: str) -> None:
        self.gave_up = True
        if self.logger is not None:
            self.logger.error(
                "supervisor giving up: restart budget exhausted",
                error=reason, restarts=self.restarts,
                window_s=self.restart_window_s,
            )
        if self.recorder is not None:
            self.recorder.event(
                "engine_restart_abandoned", reason=reason,
                restarts=self.restarts,
            )
        # Health stays NOT_SERVING (the watchdog/crash path already
        # flipped it); the platform's restart policy takes over.
        for callback in self._giveup_listeners:
            callback(reason)

    def _restart(self, old) -> None:
        reason = old.dead or "engine dead"
        if self.logger is not None:
            self.logger.warn(
                "supervisor restarting engine", error=reason,
                attempt=self.restarts + 1,
            )
        # Drain the corpse: reject racing submits, then give the engine
        # thread a grace window to unwind (a stall that clears — e.g. a
        # slow collective — lets the thread see `dead`, fail its own
        # in-flight work, and exit cleanly).
        old._stop.set()
        old._wake.set()
        old._thread.join(timeout=self.join_timeout_s)
        wedged = old._thread.is_alive()
        if wedged:
            # Genuinely wedged in a device call: the engine thread will
            # never fail its in-flight work, so do it from here. The old
            # engine object is discarded, so the slot/allocator races
            # this would normally risk are moot — only the requests'
            # thread-safe out-queues matter, and clients must not hang
            # to their timeouts.
            old._fail_all(f"engine restarting: {reason}")
        self._restart_times.append(time.monotonic())
        try:
            fresh = self._factory()
        except Exception as e:
            if self.logger is not None:
                self.logger.error(
                    "engine restart failed; will retry", error=str(e),
                )
            if self.recorder is not None:
                self.recorder.event(
                    "engine_restart_failed", reason=reason, error=str(e),
                )
            return  # budget was charged; next tick retries if any remains
        if self._stop.is_set():
            # Shutdown raced the restart (factory builds can take
            # minutes): a terminating server must not resurrect —
            # re-advertising SERVING and leaking a live engine thread.
            fresh.shutdown()
            return
        if not wedged:
            # Metric continuity: the fresh engine adopts the dead one's
            # EngineMetrics so shed/expired/latency counters survive the
            # swap (Prometheus counters must not reset on a supervised
            # restart — only on process restart). Skipped when the old
            # thread is still wedged: if its device call ever returns it
            # will run its own _fail_all concurrently with ours above,
            # and the double-counted failures must not pollute the live
            # engine's counters — a counter reset is the lesser evil.
            fresh.metrics = old.metrics
        # Signal-plane continuity (ISSUE 11): the plane rides the
        # adopted metrics object, so its window ring and SLO budget
        # state survive the swap — but its timeline binding points at
        # the DEAD engine's ring. Rebind to the fresh engine's so
        # breach/recovery notes land where to_perfetto exports from.
        # (On the wedged path fresh.metrics is a new object whose plane
        # was freshly built against the fresh timeline — nothing to do.)
        signals = getattr(fresh.metrics, "signals", None)
        if signals is not None:
            signals.timeline = getattr(fresh, "timeline", None)
            if signals.recorder is None:
                signals.recorder = self.recorder
        self.restarts += 1
        self.engine = fresh
        for callback in self._listeners:
            callback(fresh)
        if self.watchdog is not None:
            self.watchdog.rearm(fresh)   # also resumes health SERVING
        elif self.health is not None:
            self.health.resume_serving()
        if self.restart_counter is not None:
            self.restart_counter.inc()
        if self.recorder is not None:
            self.recorder.event(
                "engine_restart", reason=reason, restarts=self.restarts,
            )
        # The fresh engine's flight-deck timeline opens with the restart
        # marker, so a Perfetto export of the post-restart schedule shows
        # WHY the frontier counters reset (ISSUE 10).
        timeline = getattr(fresh, "timeline", None)
        if timeline is not None:
            # kv_reloaded: pages the fresh engine pulled back from the
            # durable prefix store (ISSUE 15) — the restart-handoff
            # evidence that warm TTFT survived the swap.
            timeline.note("engine_restart", reason=reason,
                          restarts=self.restarts,
                          kv_reloaded=getattr(
                              fresh, "_kv_reloaded_pages", 0))
        if self.logger is not None:
            self.logger.info(
                "engine restarted", restarts=self.restarts,
            )
