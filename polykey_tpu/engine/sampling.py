"""Token sampling: greedy, temperature, top-k, top-p.

jit-friendly by construction: the sampling configuration is static (baked at
trace time via SamplingParams), shapes never depend on data, and top-p uses a
sort + cumulative-sum mask rather than dynamic truncation.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingParams:
    """Static sampling configuration (hashable → usable as a jit static arg)."""

    temperature: float = 0.0   # 0 → greedy
    top_k: int = 0             # 0 → disabled
    top_p: float = 1.0         # 1.0 → disabled
    max_new_tokens: int = 128

    @property
    def is_greedy(self) -> bool:
        return self.temperature == 0.0


def _apply_top_k(logits: jax.Array, k: int) -> jax.Array:
    vals, _ = jax.lax.top_k(logits, k)
    threshold = vals[..., -1:]
    return jnp.where(logits < threshold, -jnp.inf, logits)


def _apply_top_p(logits: jax.Array, p: float) -> jax.Array:
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    # Keep the smallest prefix with cumulative mass >= p (always >= 1 token).
    cutoff_mask = cumulative - probs < p
    threshold = jnp.min(
        jnp.where(cutoff_mask, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < threshold, -jnp.inf, logits)


def sample(
    logits: jax.Array,            # [..., vocab] fp32
    key: jax.Array,
    params: SamplingParams,
) -> jax.Array:
    """Sample token ids [...] from logits under the static params."""
    if params.is_greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / params.temperature
    if params.top_k > 0:
        logits = _apply_top_k(logits, params.top_k)
    if params.top_p < 1.0:
        logits = _apply_top_p(logits, params.top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
