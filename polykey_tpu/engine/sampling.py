"""Token sampling: greedy, temperature, top-k, top-p.

jit-friendly by construction: the sampling configuration is static (baked at
trace time via SamplingParams), shapes never depend on data, and top-p uses a
sort + cumulative-sum mask rather than dynamic truncation.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingParams:
    """Static sampling configuration (hashable → usable as a jit static arg)."""

    temperature: float = 0.0   # 0 → greedy
    top_k: int = 0             # 0 → disabled
    top_p: float = 1.0         # 1.0 → disabled
    max_new_tokens: int = 128

    @property
    def is_greedy(self) -> bool:
        return self.temperature == 0.0


def _apply_top_k(logits: jax.Array, k: int) -> jax.Array:
    vals, _ = jax.lax.top_k(logits, k)
    threshold = vals[..., -1:]
    return jnp.where(logits < threshold, -jnp.inf, logits)


def _top_p_keep_mask(sorted_logits: jax.Array, p: jax.Array) -> jax.Array:
    """Keep-mask over descending-sorted logits: smallest prefix with
    cumulative mass >= p, and always at least the top-1 entry (so p <= 0
    degrades to greedy support instead of masking everything)."""
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    return _prefix_keep_mask(probs, p)


def _prefix_keep_mask(desc_probs: jax.Array, p) -> jax.Array:
    """THE top-p keep rule, shared by every path (exact sort, top-k
    prefilter, and the speculative truncated distributions — they must
    agree token-for-token): over descending-ordered probabilities, keep
    each entry whose exclusive cumulative mass is < p, always keeping
    the first."""
    keep = jnp.cumsum(desc_probs, axis=-1) - desc_probs < p
    return keep.at[..., 0].set(True)


def _rank_keep_mask(width: int, top_k) -> jax.Array:
    """[..., width] keep mask for per-row top-k over DESCENDING-ordered
    entries (rank < k); top_k <= 0 disables. THE top-k rule for every
    candidates-prefiltered path (exact paths use the k-th-value threshold
    instead — ties there keep all equal values, consistently between the
    plain sampler and the speculative truncated dists)."""
    r = jnp.arange(width)
    k = jnp.where(top_k > 0, top_k, width)
    return r < k[..., None]


def _trunc_thresholds(scaled: jax.Array, top_p, top_k):
    """THE exact-path truncation thresholds, from one descending sort:
    (thr_p, thr_k) such that keeping `scaled >= thr_p` realizes the
    shared top-p keep rule and `scaled >= thr_k` keeps the k largest
    (ties keep all equal values). One implementation for the plain
    sampler AND the speculative truncated dists — they must agree
    token-for-token, so the rule lives in exactly one place."""
    V = scaled.shape[-1]
    sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
    keep_p = _top_p_keep_mask(sorted_desc, top_p)
    thr_p = jnp.min(
        jnp.where(keep_p, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )
    kidx = jnp.clip(jnp.where(top_k > 0, top_k, V) - 1, 0, V - 1)
    thr_k = jnp.take_along_axis(sorted_desc, kidx[..., None], axis=-1)
    return thr_p, thr_k


def truncated_dist(
    logits: jax.Array,        # [..., V]
    temp: jax.Array,          # [...] (>0; callers handle greedy rows)
    top_p: jax.Array,         # [...]
    top_k: jax.Array,         # [...] int32; <= 0 → disabled
    candidates: int,          # static top-k prefilter width; 0 → exact
) -> jax.Array:
    """Per-row top-p-truncated, renormalized sampling distribution
    [..., V] — exactly the distribution sample_dynamic draws from for the
    same (candidates, top_p): the top-k-prefiltered rule when
    0 < candidates < V (keep rule on FULL-vocab probabilities via
    logsumexp, no sort), the exact full-vocab sort otherwise. Rows with
    top_p >= 1 get the untruncated softmax. The speculative draft/verify
    pair (engine/spec_decode.py) samples and accepts against this."""
    V = logits.shape[-1]
    scaled = logits / temp[..., None]
    probs = jax.nn.softmax(scaled, axis=-1)
    if candidates and candidates < V:
        vals, idx = jax.lax.top_k(scaled, candidates)      # desc [..., C]
        lse = jax.scipy.special.logsumexp(scaled, axis=-1, keepdims=True)
        p_c = jnp.exp(vals - lse)             # true full-vocab probabilities
        keep = _prefix_keep_mask(p_c, top_p[..., None])
        keep &= _rank_keep_mask(candidates, top_k)
        kept = jnp.where(keep, p_c, 0.0)
        trunc = jnp.put_along_axis(
            jnp.zeros_like(probs), idx, kept, axis=-1, inplace=False
        )
    else:
        # Exact full-vocab truncation (candidates disabled OR wider than
        # the vocabulary — never silently skip the requested nucleus).
        thr_p, thr_k = _trunc_thresholds(scaled, top_p[..., None], top_k)
        trunc = jnp.where(
            (scaled >= thr_p) & (scaled >= thr_k), probs, 0.0
        )
    trunc = trunc / jnp.maximum(
        jnp.sum(trunc, axis=-1, keepdims=True), 1e-20
    )
    no_trunc = (top_p >= 1.0) & (top_k <= 0)
    return jnp.where(no_trunc[..., None], probs, trunc)


def _top_p_threshold(scaled: jax.Array, p) -> jax.Array:
    """Exact full-vocab top-p cut: the smallest kept logit (descending
    sort + shared keep rule). ONE implementation — the exact sampler, the
    static top-p filter, and the speculative truncated dists all cut at
    this threshold, so tie handling cannot drift between paths."""
    sorted_logits = jnp.sort(scaled, axis=-1)[..., ::-1]
    keep = _top_p_keep_mask(sorted_logits, p)
    return jnp.min(
        jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )


def _apply_top_p(logits: jax.Array, p: float) -> jax.Array:
    threshold = _top_p_threshold(logits, jnp.float32(p))
    return jnp.where(logits < threshold, -jnp.inf, logits)


def _masked_rows(logits, temp, top_p, top_k, candidates: int):
    """Shared top-p/top-k masking for the dynamic samplers. Returns
    (greedy [B], masked [B, C or V], idx [B, C] | None, scaled_full):
    categorical over `masked` (mapped through idx when present) realizes
    the truncated distribution; `scaled_full` serves untruncated rows
    (top_p >= 1 and top_k disabled)."""
    if candidates and candidates < logits.shape[-1]:
        scaled_full = logits / temp                       # [B, V]
        lse = jax.scipy.special.logsumexp(
            scaled_full, axis=-1, keepdims=True
        )
        vals, idx = jax.lax.top_k(scaled_full, candidates)  # desc [B, C]
        greedy = idx[:, 0].astype(jnp.int32)
        probs = jnp.exp(vals - lse)       # true full-vocab probabilities
        keep = _prefix_keep_mask(probs, top_p[:, None])
        keep &= _rank_keep_mask(candidates, top_k)
        return greedy, jnp.where(keep, vals, -jnp.inf), idx, scaled_full
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / temp
    # Per-row top-p/top-k on the scaled logits (one sort; shared rules).
    thr_p, thr_k = _trunc_thresholds(scaled, top_p[:, None], top_k)
    masked = jnp.where(
        (scaled < thr_p) | (scaled < thr_k), -jnp.inf, scaled
    )
    return greedy, masked, None, scaled


def sample_dynamic(
    logits: jax.Array,            # [B, vocab] fp32
    key: jax.Array,
    temperature: jax.Array,       # [B] — 0 → greedy for that row
    top_p: jax.Array,             # [B] — 1.0 → disabled for that row
    top_k: jax.Array = None,      # [B] int32 — <= 0 → disabled
    candidates: int = 0,          # static: 0 → exact (full-vocab sort)
) -> jax.Array:
    """Per-row sampling with *data-dependent* temperature/top-p, one
    shared RNG key for the whole batch.

    The continuous-batching decode step serves many requests with different
    sampling settings in one jitted call, so the settings arrive as arrays
    rather than static config. Greedy rows are selected with jnp.where (no
    control flow → no recompilation as the batch mix changes).

    `candidates` > 0 prefilters each row to its top-`candidates` logits
    with lax.top_k (already descending — no separate [B, vocab] sort, the
    expensive op at 128k-256k vocab) and applies top-p within them:
    equivalent to composing top-k=candidates with top-p. Candidate
    probabilities are normalized by the FULL-vocab logsumexp (a sort-free
    reduction), so the keep rule matches the exact path token-for-token;
    the result is exact whenever the top-p support fits in the candidate
    set. Rows with top_p >= 1 asked for no truncation and bypass the
    prefilter entirely (untruncated categorical needs no sort either).
    Pass candidates=0 for the exact full-vocab path.
    """
    if top_k is None:
        top_k = jnp.zeros(logits.shape[0], jnp.int32)
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    greedy, masked, idx, scaled_full = _masked_rows(
        logits, temp, top_p, top_k, candidates
    )
    if idx is not None:
        k_pre, k_full = jax.random.split(key)
        local = jax.random.categorical(k_pre, masked, axis=-1)
        truncated = jnp.take_along_axis(
            idx, local[:, None], axis=-1
        )[:, 0].astype(jnp.int32)
        # Untruncated rows: unrestricted sampling over the whole vocab.
        full = jax.random.categorical(
            k_full, scaled_full, axis=-1
        ).astype(jnp.int32)
        sampled = jnp.where((top_p >= 1.0) & (top_k <= 0), full, truncated)
    else:
        sampled = jax.random.categorical(
            key, masked, axis=-1
        ).astype(jnp.int32)
    return jnp.where(temperature == 0.0, greedy, sampled)


def _row_categorical(keys: jax.Array, logits: jax.Array) -> jax.Array:
    """Independent per-row draws: keys [B, 2] uint32, logits [B, V] → [B]."""
    return jax.vmap(
        lambda k, l: jax.random.categorical(k, l)
    )(keys, logits).astype(jnp.int32)


def lane_keys(seed_hi: jax.Array, seed_lo: jax.Array) -> jax.Array:
    """Per-lane base PRNG keys [B, 2] from two int32 seed halves — the
    engine's per-request RNG roots (engine.py: every sampled draw for a
    request is keyed by fold_in(base, token position), so a request's
    stream depends only on (seed, prompt), never on batch composition or
    scheduling)."""
    def one(hi, lo):
        return jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(0), hi), lo
        )

    return jax.vmap(one)(seed_hi, seed_lo)


def fold_positions(base_keys: jax.Array, positions: jax.Array) -> jax.Array:
    """fold_in each lane's base key with its token position → [B, 2]."""
    return jax.vmap(jax.random.fold_in)(base_keys, positions)


def sample_dynamic_rows(
    logits: jax.Array,            # [B, vocab] fp32
    keys: jax.Array,              # [B, 2] uint32 — per-row keys
    temperature: jax.Array,       # [B]
    top_p: jax.Array,             # [B]
    top_k: jax.Array = None,      # [B] int32 — <= 0 → disabled
    candidates: int = 0,
) -> jax.Array:
    """sample_dynamic with an independent RNG key per row — the engine's
    seeded path. Identical masking (shared _masked_rows); only the draw
    granularity differs."""
    if top_k is None:
        top_k = jnp.zeros(logits.shape[0], jnp.int32)
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    greedy, masked, idx, scaled_full = _masked_rows(
        logits, temp, top_p, top_k, candidates
    )
    if idx is not None:
        keys2 = jax.vmap(lambda k: jax.random.fold_in(k, 1))(keys)
        local = _row_categorical(keys, masked)
        truncated = jnp.take_along_axis(
            idx, local[:, None], axis=-1
        )[:, 0].astype(jnp.int32)
        full = _row_categorical(keys2, scaled_full)
        sampled = jnp.where((top_p >= 1.0) & (top_k <= 0), full, truncated)
    else:
        sampled = _row_categorical(keys, masked)
    return jnp.where(temperature == 0.0, greedy, sampled)


def sample(
    logits: jax.Array,            # [..., vocab] fp32
    key: jax.Array,
    params: SamplingParams,
) -> jax.Array:
    """Sample token ids [...] from logits under the static params."""
    if params.is_greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / params.temperature
    if params.top_k > 0:
        logits = _apply_top_k(logits, params.top_k)
    if params.top_p < 1.0:
        logits = _apply_top_p(logits, params.top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def sample_tail(logits, seeds, positions, temperature, top_p, top_k,
                greedy: bool, candidates: int = 0):
    """THE shared sampling tail for prefill and decode (plain and
    speculative paths — one implementation so key derivation cannot
    drift): greedy takes pure argmax (no RNG); sampled rows draw
    independently, each keyed by fold_in(lane seed key, positions[row])."""
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    base = lane_keys(seeds[:, 0], seeds[:, 1])
    keys = fold_positions(base, positions)
    return sample_dynamic_rows(
        logits, keys, temperature, top_p, top_k, candidates
    )
