"""Engine configuration, layered on the gateway's precedence discipline.

Extends the reference's config model (internal/config/config.go: defaults <
flags < env) with the serving-engine settings the north star needs: model
selection, decode-batch geometry, KV page pool, prefill buckets, parallelism
axes. Env vars use the same POLYKEY_* prefix.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_bool(name: str, extra: tuple[str, ...] = ()) -> bool:
    return os.environ.get(name, "").lower() in ("1", "true", *extra)


_compile_cache_dir: Optional[str] = None


def enable_persistent_compile_cache() -> Optional[str]:
    """Point JAX's persistent compilation cache at a durable directory.

    TPU compiles of the serving step run 20-40 s each; a server restart,
    a benchmark retry after a tunnel flap, or the driver's end-of-round
    bench would otherwise pay them all again. The cache keys on program
    HLO + compiler flags + platform, so reuse is exact. Opt out with
    POLYKEY_COMPILE_CACHE=0; relocate with POLYKEY_COMPILE_CACHE_DIR.
    Returns the cache dir in use (None when disabled or unavailable).
    """
    global _compile_cache_dir
    if os.environ.get("POLYKEY_COMPILE_CACHE", "1") == "0":
        return None
    if _compile_cache_dir is not None:
        return _compile_cache_dir
    cache_dir = os.environ.get("POLYKEY_COMPILE_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "polykey_tpu_xla")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            _env_float("POLYKEY_COMPILE_CACHE_MIN_SECS", 1.0),
        )
        try:
            # JAX initializes its compilation cache lazily ONCE: if any
            # jit ran before this call (warmup, an embedder, a test
            # module), the dir update above is silently ignored until
            # the cache object is reset. Best-effort — the attribute is
            # jax-internal and the cache stays an optimization.
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass  # older/newer jax without reset_cache: dir may still apply
    except Exception:
        return None       # cache is an optimization, never a failure
    _compile_cache_dir = cache_dir
    return cache_dir


@dataclass(frozen=True)
class EngineConfig:
    model: str = "tiny-llama"
    tokenizer: str = "byte"              # 'byte' or a local HF tokenizer path
    dtype: str = "bfloat16"
    checkpoint_path: Optional[str] = None  # None → random init (dev/bench)
    quantize: bool = False               # weight-only quant (models/quant.py)
    # Quantization width: 8 (per-channel int8) or 4 (group-wise int4 —
    # halves weight HBM traffic again; embed/lm_head stay int8).
    # POLYKEY_QUANTIZE=int4 selects 4.
    quantize_bits: int = 8
    # KV-cache dtype: "bfloat16"/"float32" full precision, or "int8" —
    # per-(token, head) symmetric quantization at write time
    # (ops/paged_attention.quantize_kv_rows). Halves pool HBM, which is
    # the decode-slot budget on a 16 GiB chip; decode attention streams
    # the int8 pages through the DMA read kernel's in-kernel dequant
    # stage (half the bf16 bytes). POLYKEY_KV_DTYPE=int8 selects it.
    kv_dtype: str = ""                   # "" → follow `dtype`

    # Decode-batch geometry (static shapes; compile-time constants).
    # Defaults target real serving lengths (VERDICT r1 #5): 4k positions
    # per request, 32k pooled KV token-slots. Prompts longer than the
    # largest bucket prefill in `prefill_chunk`-sized chunks interleaved
    # with decode steps, so a long prompt never stalls running streams for
    # more than one chunk.
    max_decode_slots: int = 16
    page_size: int = 16
    num_pages: int = 2048                # includes reserved garbage page 0
    max_seq_len: int = 4096              # per-request position cap
    prefill_buckets: tuple[int, ...] = (128, 512)
    prefill_chunk: int = 0               # 0 → max(prefill_buckets)
    max_new_tokens_cap: int = 1024
    default_max_new_tokens: int = 64

    # Interleaved-prefill token budget (ISSUE 4, Sarathi-style): while any
    # decode lane is live, at most ~this many prefill tokens (burst
    # admission groups + long-prompt chunks) dispatch per engine-loop
    # iteration, i.e. between two decode blocks — so a prefill burst or a
    # long-prompt admission can no longer starve the decode lookahead
    # pipeline and blow ITL. 0 → auto (2 × the prefill chunk). The budget
    # is a soft bound at dispatch granularity: one admission group or one
    # chunk always proceeds per iteration (progress floor), and the last
    # unit may overshoot — worst case per iteration is
    # budget + largest_bucket + chunk. With NO live decode lanes the
    # budget is waived entirely (there is no ITL to protect; cold bursts
    # should fill all slots at once). POLYKEY_PREFILL_BUDGET.
    prefill_budget: int = 0

    # Ragged dispatch (ISSUE 12, PAPERS.md "Ragged Paged Attention"):
    # admissions and chunk advancement become token-range appends into
    # ONE flat mixed prefill+decode dispatch per engine-loop iteration
    # (all live decode lanes' single tokens + up to ~prefill_budget
    # prefill tokens), replacing the per-bucket prefill executables
    # ({1,2,4,8} pads × buckets × greedy variants) and the separate
    # chunk dispatch with a single resident ragged executable (≤2
    # greedy variants). Steady-state decode (no prefill work) keeps the
    # K-step block path, so the PR 6 lookahead pipeline and its
    # amortization are untouched. Attention rides the ragged Pallas
    # kernel on TPU (ops/ragged_paged_attention_kernel.py) and its
    # per-token gather fallback off-TPU — the bit-identity reference:
    # greedy streams match the bucketed path token-for-token.
    # POLYKEY_RAGGED=1 enables; POLYKEY_DISABLE_RAGGED=1 is the
    # operational kill-switch (wins over config/env enablement, the
    # POLYKEY_DISABLE_PAGED_KERNEL pattern). Requires dp=sp=pp=1.
    # Composes with speculative decoding (ISSUE 19): gamma-token verify
    # windows ride the flat stream as ordinary per-sequence ranges, so
    # one mixed dispatch serves prefill chunks, decode lanes, AND spec
    # verify lanes.
    ragged_dispatch: bool = False

    # Automatic prefix caching (engine/prefix_cache.py): requests sharing a
    # page-aligned prompt prefix reuse its KV pages and prefill only the
    # suffix. prefix_cache_pages caps the cache's own page references
    # (LRU); 0 → num_pages // 2. Composes with speculative decoding: the
    # draft pool shares page indices and spec prefill writes BOTH pools
    # for every window, so a cached page carries both models' prefix KV.
    prefix_cache: bool = False
    prefix_cache_pages: int = 0

    # -- Host-memory KV tier (ISSUE 15, ROADMAP item 3) ----------------------
    # POLYKEY_HOST_KV_BYTES > 0 adds a second KV tier in host RAM: cold
    # pages — prefix-cache entries whose sessions finished (sticky
    # multi-turn histories, long-context middles) — are evicted from the
    # device pool into pinned host buffers through a fixed-width jit'd
    # gather, and paged back on demand through the (equally fixed-width,
    # pool-donating) `_jit_kv_restore` scatter when a later request's
    # prefix-cache lookup hits them. Capacity then bounds on host RAM
    # instead of HBM. 0 (the default) allocates NO host pool and leaves
    # every existing path byte-identical. Requires prefix_cache (the
    # spill source); from_env auto-enables it.
    host_kv_bytes: int = 0
    # Resident working set: when a retiring request leaves fewer than
    # this many device pages free, LRU prefix-cache pages spill to the
    # host tier until the floor is restored (eviction at retire — the
    # proactive path that keeps admissions from ever paying the spill
    # synchronously). 0 → num_pages // 8. POLYKEY_KV_RESIDENT_PAGES.
    host_kv_resident_pages: int = 0
    # Page-aware restore scheduling: how many faulting slots may issue
    # their host→device restore dispatch per engine-loop iteration. A
    # lane whose pages are in flight never joins a prefill/decode
    # dispatch until its restore has issued, and this budget bounds how
    # much restore upload work rides any one gap between decode blocks
    # — the interleaved-prefill discipline applied to page faults.
    # POLYKEY_KV_RESTORE_SLOTS.
    host_kv_restore_slots: int = 2
    # Restart-durable prefix cache: a directory where spilled prefix
    # pages are ALSO serialized in the PR 13 KV wire format (CRC-framed
    # `serialize_kv_state` blobs + a JSON sidecar of page keys). A fresh
    # engine — in particular the supervisor's post-crash restart — scans
    # the dir at construction and reloads matching pages into the host
    # tier, so sticky sessions keep their warm TTFT across restarts.
    # Corrupt/CRC-failing files are skipped (warmth lost, never
    # liveness). "" disables persistence. POLYKEY_KV_STATE_DIR.
    kv_state_dir: str = ""

    # Pre-compile the prefill group shapes ({1,2,4,8} × buckets) and the
    # decode block (or spec round) at engine construction, before the loop
    # starts — first requests (and benchmark windows) then never pay XLA
    # compile time. Costs startup latency.
    compile_warmup: bool = False

    # With compile_warmup, also pre-compile the sampled-path variants
    # (greedy=False prefill/decode, truncated-top-p spec round, spec→plain
    # fallback). On for serving — the first sampled request must not stall
    # on a compile; off for greedy-only runs (the benchmark), where those
    # variants are never dispatched and roughly double warmup wall-clock.
    warm_sampled_variants: bool = True

    # Decode steps per dispatch: the jitted decode runs `decode_block_steps`
    # steps in one lax.scan call, with device-side EOS/budget stopping, so
    # per-dispatch host overhead (Python + transfer latency — dominant when
    # the accelerator sits behind a network tunnel) amortizes K-fold.
    # Tokens stream out in blocks of ≤K per request; prefills interleave at
    # block boundaries. 1 → token-at-a-time (lowest streaming latency).
    decode_block_steps: int = 8

    # Load-adaptive blocking: when only ONE stream is active, dispatch
    # small blocks (max(1, K // 8)) instead of the full K — a lone
    # stream's tokens then stream out one-at-a-time at the device's step
    # rate rather than arriving K at a time (the solo-latency cliff,
    # VERDICT r2 weak #6), while the lookahead pipeline keeps the device
    # busy. Under load the full K amortizes per-dispatch host overhead.
    # Output is unchanged either way (blocked decode is a pure batching
    # of the step loop); only dispatch granularity adapts.
    adaptive_block: bool = True

    # In-flight decode blocks (pipeline depth): the engine keeps up to
    # `lookahead_blocks` dispatched-but-unprocessed FULL-K blocks on the
    # device queue, so host-side processing and D2H latency hide behind
    # device compute. When adaptive blocking shrinks K the LOOKAHEAD
    # portion scales up by the same factor — 1 + (depth-1) x (K/steps),
    # capped at 64 blocks — keeping queued-ahead steps constant while
    # depth 1 stays exactly synchronous.
    # Device-side stopping + per-block request snapshots make
    # stale blocks safe (engine.py _run); the cost is up to
    # lookahead_blocks x decode_block_steps wasted device steps when a
    # stream finishes. 1 → classic dispatch-then-process.
    lookahead_blocks: int = 2

    # Flight-deck timeline (ISSUE 10): bounded ring of typed engine
    # events — dispatch/process frontiers, admissions, prefill chunks,
    # retirements, expiries, restarts, re-routes — exported as
    # Perfetto JSON (/debug/timeline, occupancy_soak --timeline).
    # Capacity bounds memory (events are small tuples; 4096 ≈ a few
    # hundred KB worst case). 0 DISABLES it: the engine allocates no
    # ring and every emission site is one `is None` branch, so an
    # obs-less deployment pays nothing. POLYKEY_TIMELINE_CAPACITY.
    timeline_capacity: int = 4096

    # Black-box checkpoint cadence (ISSUE 16, obs/postmortem.py): a
    # disagg member with a state dir flushes its timeline +
    # flight-recorder rings to `blackbox-<role>.json` every this many
    # timeline appends (plus forced flushes at control-plane op intake
    # and on the supervisor trip path). 0 DISABLES black boxes even
    # when a state dir exists. POLYKEY_BLACKBOX_EVERY.
    blackbox_every: int = 64

    # SLO signal plane (ISSUE 11, obs/signals.py): seconds between ring
    # samples of the metrics registry — monotone counters become
    # windowed rates, cumulative histograms become delta-quantiles over
    # 1m/5m/1h windows (POLYKEY_SIGNALS_WINDOWS), fixing the "p95 since
    # boot" staleness and feeding burn-rate SLO evaluation
    # (POLYKEY_SLO). Sampling rides engine-loop block boundaries with
    # the idle tick as the low-rate fallback; the read side also
    # samples, so windows advance even when the loop is wedged. 0
    # DISABLES the plane entirely: no ring allocated,
    # `metrics.signals is None`, one `is None` branch in the loop — the
    # timeline_capacity=0 discipline. POLYKEY_SIGNALS_INTERVAL.
    signals_interval_s: float = 5.0
    # Window widths (comma-separated seconds, "" → the env /
    # 60,300,3600 defaults) and the SLO policy spec (inline JSON,
    # "@/path.json", or "default"; "" → POLYKEY_SLO). Carried on the
    # config so programmatic constructions (perf_gate, tests, embedded
    # engines) control them without mutating os.environ, and so a
    # supervised restart rebuilds the plane from the SAME spec the
    # original engine ran — engines built with the empty defaults fall
    # back to the env at construction time.
    signals_windows: str = ""
    slo_policy: str = ""

    # Parallelism axes (parallel/mesh.py); 1 → axis unused. ep shards MoE
    # expert weights and rides token dispatch over the ep axis (Mixtral —
    # BASELINE.md measurement config 4); it requires an MoE model. sp
    # shards the PREFILL token axis (sequence-parallel prefill): long
    # prompts spread their attention/MLP compute over sp chips, with the
    # KV writes exchanged into the sp-replicated page pools by GSPMD —
    # the serving-path long-context story (SURVEY §5). Decode is
    # unaffected (T=1). Buckets and prefill_chunk must divide by sp.
    # pp shards the stacked-layer axis (memory distribution: a model
    # larger than one chip's HBM serves across pp stages; decode
    # activations hop stages via compiler-inserted transfers — capacity,
    # not throughput; the GPipe schedule in parallel/pipeline.py is the
    # training-side formulation).
    tp: int = 1
    dp: int = 1
    ep: int = 1
    sp: int = 1
    pp: int = 1

    # Multi-slice serving: >1 spans the mesh across `num_slices` ICI
    # domains connected by DCN (parallel/distributed.py:create_hybrid_mesh).
    # dp above is PER-SLICE — the mesh's dp axis extent becomes
    # num_slices × dp, with the slice dimension outermost so data-parallel
    # is the ONLY axis whose collectives cross DCN; tp/ep/sp/pp stay
    # inside a slice (the layout rule from parallel/distributed.py).
    num_slices: int = 1

    # Sampled-path top-p prefilter width: >0 restricts each row to its
    # top-K logits via lax.top_k (no full [B, vocab] sort — the expensive
    # op at 128k-256k vocab) and applies top-p within them; equivalent to
    # composing top-k=K with top-p, exact whenever the top-p support fits
    # in K. 0 → exact full-vocab sort. Greedy batches never sort either
    # way. Also enables top_p<1 requests on the SPECULATIVE path
    # (truncated rejection sampling — sampling.truncated_dist); with
    # 0, spec engines route top_p<1 batches through the plain step.
    # With the prefilter on, a request's top_k clamps to this width
    # (the sampled paths only ever see the top-C logits).
    top_p_candidates: int = 0

    # Speculative decoding (engine/spec_decode.py): a draft model name turns
    # it on; gamma = drafts per verify round. Draft must share the target's
    # vocab. top_p<1 requests ride the spec path when top_p_candidates > 0
    # (truncated rejection sampling); otherwise they fall back to the
    # plain decode step.
    draft_model: Optional[str] = None
    draft_checkpoint_path: Optional[str] = None  # None → random init
    spec_gamma: int = 4

    # Wire gamma to MEASURED acceptance: dispatch gamma moves on a
    # two-level ladder {max(1, spec_gamma//2), spec_gamma} driven by an
    # acceptance EWMA with hysteresis (engine._process_spec) — a draft
    # that keeps getting rejected stops wasting spec_gamma draft
    # forwards per round. Page/position slack always reserves for the
    # full spec_gamma, so adaptation never overflows a slot.
    adaptive_gamma: bool = True

    # A/B instrumentation ONLY (scripts/occupancy_soak.py --ab-spec):
    # emulate the pre-ISSUE-19 host-loop spec round by forcing three
    # synchronous packed readbacks at dispatch time — the crossing
    # schedule of the old path on the new path's math, so the A/B
    # isolates the host tax. Never set in production; programmatic only
    # (no env knob on purpose — it exists to measure a regression).
    spec_host_sync: bool = False

    # Liveness. The watchdog window must comfortably exceed worst-case XLA
    # compile time (each new prefill bucket compiles on first use).
    watchdog_timeout_s: float = 300.0
    request_timeout_s: float = 300.0

    # -- Overload safety (ISSUE 3) -------------------------------------------
    # Bound on the submit queue: requests beyond it are shed immediately
    # with RESOURCE_EXHAUSTED + a retry-after-ms hint (engine.submit)
    # instead of queueing into unbounded latency. 0 → unbounded (bench /
    # soak harnesses that deliberately flood the queue).
    max_queue_depth: int = 256
    # Supervised restarts (engine/supervisor.py): a watchdog trip or
    # engine-loop crash triggers an in-process restart — fresh engine,
    # re-armed watchdog, health back to SERVING — up to
    # `max_engine_restarts` times within `restart_window_s` before the
    # supervisor gives up and leaves the process NOT_SERVING for the
    # platform to recycle (compose healthcheck / k8s restart policy).
    supervise: bool = True
    max_engine_restarts: int = 3
    restart_window_s: float = 600.0

    # -- Replica tier (ISSUE 9) ----------------------------------------------
    # POLYKEY_REPLICAS > 1 serves through an in-process pool of
    # independently supervised engine replicas (engine/replica_pool.py)
    # behind a health/load-aware router. 1 (the default) keeps the
    # single-engine wiring byte-for-byte: no pool object, no routing, no
    # behavior change.
    replicas: int = 1
    # This engine's identity within a pool (fault targeting, metric
    # labels, stats). Set by the pool via dataclasses.replace — not an
    # env knob; a standalone engine is replica 0.
    replica: int = 0
    # Router score = prefix_weight × (cached-prefix fraction)
    #              − delay_weight × (estimated queue delay, s);
    # candidates whose estimated delay would blow the request deadline
    # are filtered first (headroom check). Ties break on the lowest
    # replica index, so routing is deterministic given equal state.
    route_prefix_weight: float = 1.0
    route_delay_weight: float = 1.0
    # How many times one request may be re-routed onto another replica
    # after an engine-lifecycle failure (queued requests move losslessly;
    # in-flight streams resume with already-emitted tokens suppressed).
    # 0 disables failover re-routing (failures surface as UNAVAILABLE,
    # exactly the single-engine contract).
    max_reroutes: int = 3

    # -- Disaggregated prefill/decode tiers (ISSUE 13) -----------------------
    # POLYKEY_DISAGG="PxD" (e.g. "2x2") or "prefill=P,decode=D" serves
    # through CROSS-PROCESS worker tiers (engine/disagg_pool.py): P
    # prefill-tier + D decode-tier worker processes on localhost, each a
    # supervised engine behind a socket control plane
    # (engine/worker.py), with finished prefill KV shipped to a
    # NetKV-scored decode worker in the versioned kv_cache wire format.
    # "" (the default) builds NO worker processes and NO pool — every
    # single-process path is byte-identical. Mutually exclusive with
    # POLYKEY_REPLICAS > 1 (the in-process stage-(a) pool).
    disagg: str = ""
    # This engine's tier identity inside a disaggregated worker
    # ("prefill" / "decode"; set by engine/worker.py via
    # dataclasses.replace, not an env knob). Scopes ":tier=" fault
    # targeting; "" for every non-disaggregated engine.
    disagg_tier: str = ""
    # Worker liveness: the coordinator heartbeats every worker's control
    # plane at this interval and declares death after `disagg_miss`
    # consecutive misses (process exit via poll() is detected
    # immediately either way). POLYKEY_DISAGG_HEARTBEAT /
    # POLYKEY_DISAGG_MISS.
    disagg_heartbeat_s: float = 0.5
    disagg_miss: int = 3
    # How long a re-route waits for a tier to regain a SERVING worker
    # (a supervised worker restart takes seconds on CPU; giving up
    # sooner would turn every restart window into failed RPCs).
    # POLYKEY_DISAGG_RECOVERY_WAIT.
    disagg_recovery_wait_s: float = 30.0

    @property
    def pages_per_seq(self) -> int:
        return self.max_seq_len // self.page_size

    @classmethod
    def from_env(cls) -> "EngineConfig":
        buckets = os.environ.get("POLYKEY_PREFILL_BUCKETS")
        return cls(
            model=os.environ.get("POLYKEY_MODEL", cls.model),
            tokenizer=os.environ.get("POLYKEY_TOKENIZER", cls.tokenizer),
            dtype=os.environ.get("POLYKEY_DTYPE", cls.dtype),
            checkpoint_path=os.environ.get("POLYKEY_CHECKPOINT") or None,
            quantize=_env_bool("POLYKEY_QUANTIZE", extra=("int8", "int4")),
            kv_dtype=os.environ.get("POLYKEY_KV_DTYPE", cls.kv_dtype),
            quantize_bits=(
                4 if os.environ.get("POLYKEY_QUANTIZE", "").lower() == "int4"
                else cls.quantize_bits
            ),
            max_decode_slots=_env_int("POLYKEY_MAX_DECODE_SLOTS", cls.max_decode_slots),
            page_size=_env_int("POLYKEY_PAGE_SIZE", cls.page_size),
            num_pages=_env_int("POLYKEY_NUM_PAGES", cls.num_pages),
            max_seq_len=_env_int("POLYKEY_MAX_SEQ_LEN", cls.max_seq_len),
            prefill_buckets=tuple(
                int(x) for x in buckets.split(",")
            ) if buckets else cls.prefill_buckets,
            prefill_chunk=_env_int("POLYKEY_PREFILL_CHUNK", cls.prefill_chunk),
            prefill_budget=_env_int(
                "POLYKEY_PREFILL_BUDGET", cls.prefill_budget
            ),
            max_new_tokens_cap=_env_int(
                "POLYKEY_MAX_NEW_TOKENS_CAP", cls.max_new_tokens_cap
            ),
            default_max_new_tokens=_env_int(
                "POLYKEY_DEFAULT_MAX_NEW_TOKENS", cls.default_max_new_tokens
            ),
            ragged_dispatch=_env_bool("POLYKEY_RAGGED"),
            # The host tier's spill source is the prefix cache, so
            # enabling the tier enables the cache (validate() enforces
            # the pairing for programmatic configs).
            prefix_cache=(
                _env_bool("POLYKEY_PREFIX_CACHE")
                or _env_int("POLYKEY_HOST_KV_BYTES", 0) > 0
            ),
            prefix_cache_pages=_env_int(
                "POLYKEY_PREFIX_CACHE_PAGES", cls.prefix_cache_pages
            ),
            host_kv_bytes=_env_int("POLYKEY_HOST_KV_BYTES", cls.host_kv_bytes),
            host_kv_resident_pages=_env_int(
                "POLYKEY_KV_RESIDENT_PAGES", cls.host_kv_resident_pages
            ),
            host_kv_restore_slots=_env_int(
                "POLYKEY_KV_RESTORE_SLOTS", cls.host_kv_restore_slots
            ),
            kv_state_dir=os.environ.get(
                "POLYKEY_KV_STATE_DIR", cls.kv_state_dir
            ),
            compile_warmup=_env_bool("POLYKEY_COMPILE_WARMUP"),
            decode_block_steps=_env_int(
                "POLYKEY_DECODE_BLOCK", cls.decode_block_steps
            ),
            # Default ON; POLYKEY_ADAPTIVE_BLOCK=0 pins the static block.
            adaptive_block=os.environ.get(
                "POLYKEY_ADAPTIVE_BLOCK", "1"
            ).lower() in ("1", "true"),
            # POLYKEY_DISPATCH_LOOKAHEAD is the documented knob (DEPLOY.md;
            # the engine also honors it as a construction-time override so
            # it works however the config was built); POLYKEY_LOOKAHEAD is
            # the legacy alias and loses when both are set.
            lookahead_blocks=_env_int(
                "POLYKEY_DISPATCH_LOOKAHEAD",
                _env_int("POLYKEY_LOOKAHEAD", cls.lookahead_blocks),
            ),
            timeline_capacity=_env_int(
                "POLYKEY_TIMELINE_CAPACITY", cls.timeline_capacity
            ),
            blackbox_every=_env_int(
                "POLYKEY_BLACKBOX_EVERY", cls.blackbox_every
            ),
            signals_interval_s=_env_float(
                "POLYKEY_SIGNALS_INTERVAL", cls.signals_interval_s
            ),
            # Captured as raw strings at from_env time so the config —
            # and therefore every supervised-restart factory replay —
            # pins the windows/policy the server booted with even if
            # the process env mutates later.
            signals_windows=os.environ.get(
                "POLYKEY_SIGNALS_WINDOWS", cls.signals_windows
            ),
            slo_policy=os.environ.get("POLYKEY_SLO", cls.slo_policy),
            tp=_env_int("POLYKEY_TP", cls.tp),
            dp=_env_int("POLYKEY_DP", cls.dp),
            ep=_env_int("POLYKEY_EP", cls.ep),
            sp=_env_int("POLYKEY_SP", cls.sp),
            pp=_env_int("POLYKEY_PP", cls.pp),
            num_slices=_env_int("POLYKEY_NUM_SLICES", cls.num_slices),
            top_p_candidates=_env_int(
                "POLYKEY_TOP_P_CANDIDATES", cls.top_p_candidates
            ),
            draft_model=os.environ.get("POLYKEY_DRAFT_MODEL") or None,
            draft_checkpoint_path=os.environ.get("POLYKEY_DRAFT_CHECKPOINT")
            or None,
            spec_gamma=_env_int("POLYKEY_SPEC_GAMMA", cls.spec_gamma),
            adaptive_gamma=os.environ.get(
                "POLYKEY_ADAPTIVE_GAMMA", "1"
            ).lower() in ("1", "true"),
            watchdog_timeout_s=_env_float(
                "POLYKEY_WATCHDOG_TIMEOUT", cls.watchdog_timeout_s
            ),
            request_timeout_s=_env_float(
                "POLYKEY_REQUEST_TIMEOUT", cls.request_timeout_s
            ),
            max_queue_depth=_env_int(
                "POLYKEY_MAX_QUEUE", cls.max_queue_depth
            ),
            # Default ON; POLYKEY_SUPERVISE=0 pins the one-shot behavior
            # (process restart is then the only recovery path).
            supervise=os.environ.get(
                "POLYKEY_SUPERVISE", "1"
            ).lower() in ("1", "true"),
            max_engine_restarts=_env_int(
                "POLYKEY_MAX_RESTARTS", cls.max_engine_restarts
            ),
            restart_window_s=_env_float(
                "POLYKEY_RESTART_WINDOW", cls.restart_window_s
            ),
            replicas=_env_int("POLYKEY_REPLICAS", cls.replicas),
            route_prefix_weight=_env_float(
                "POLYKEY_ROUTE_W_PREFIX", cls.route_prefix_weight
            ),
            route_delay_weight=_env_float(
                "POLYKEY_ROUTE_W_DELAY", cls.route_delay_weight
            ),
            max_reroutes=_env_int("POLYKEY_MAX_REROUTES", cls.max_reroutes),
            disagg=os.environ.get("POLYKEY_DISAGG", cls.disagg),
            disagg_heartbeat_s=_env_float(
                "POLYKEY_DISAGG_HEARTBEAT", cls.disagg_heartbeat_s
            ),
            disagg_miss=_env_int("POLYKEY_DISAGG_MISS", cls.disagg_miss),
            disagg_recovery_wait_s=_env_float(
                "POLYKEY_DISAGG_RECOVERY_WAIT", cls.disagg_recovery_wait_s
            ),
        )

    def disagg_tiers(self) -> Optional[tuple[int, int]]:
        """Parse the `disagg` spec into (prefill_workers, decode_workers),
        or None when unset. Accepts "PxD" ("2x2") and
        "prefill=P,decode=D" (any order). Raises ValueError on malformed
        specs — a typo must not silently serve single-process."""
        spec = self.disagg.strip().lower()
        if not spec:
            return None
        try:
            if "x" in spec and "=" not in spec:
                p_s, d_s = spec.split("x", 1)
                tiers = {"prefill": int(p_s), "decode": int(d_s)}
            else:
                tiers = {}
                for part in spec.split(","):
                    key, _, value = part.strip().partition("=")
                    tiers[key.strip()] = int(value)
                if set(tiers) != {"prefill", "decode"}:
                    raise ValueError(f"tiers {sorted(tiers)}")
        except (ValueError, TypeError) as e:
            raise ValueError(
                f"malformed POLYKEY_DISAGG spec {self.disagg!r}: expected "
                f"'PxD' or 'prefill=P,decode=D' ({e})"
            ) from None
        if tiers["prefill"] < 1 or tiers["decode"] < 1:
            raise ValueError(
                "POLYKEY_DISAGG needs >= 1 worker per tier, got "
                f"{self.disagg!r}"
            )
        return tiers["prefill"], tiers["decode"]

    def validate(self) -> None:
        if self.max_seq_len % self.page_size != 0:
            raise ValueError("max_seq_len must be a multiple of page_size")
        if self.num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        for b in self.prefill_buckets:
            if b > self.max_seq_len:
                raise ValueError(
                    f"prefill bucket {b} exceeds max_seq_len {self.max_seq_len}"
                )
        if not self.prefill_buckets:
            raise ValueError("need at least one prefill bucket")
        if self.draft_model is not None and self.spec_gamma < 1:
            raise ValueError("spec_gamma must be >= 1")
        if self.prefix_cache_pages < 0:
            raise ValueError(
                "prefix_cache_pages must be >= 0 (0 → num_pages // 2); "
                "negative would silently disable the LRU cap"
            )
        if self.host_kv_bytes < 0:
            raise ValueError(
                "host_kv_bytes must be >= 0 (0 disables the host KV tier)"
            )
        if self.host_kv_bytes > 0 and not self.prefix_cache:
            raise ValueError(
                "host_kv_bytes > 0 requires prefix_cache: the host tier's "
                "only spill source is the prefix cache (from_env pairs "
                "them automatically)"
            )
        if self.host_kv_resident_pages < 0:
            raise ValueError(
                "host_kv_resident_pages must be >= 0 (0 → num_pages // 8)"
            )
        if self.host_kv_bytes > 0 and \
                self.host_kv_resident_pages >= self.num_pages - 1:
            raise ValueError(
                f"host_kv_resident_pages={self.host_kv_resident_pages} "
                f"must stay below the usable device pool "
                f"({self.num_pages - 1} pages): a floor the pool can "
                "never satisfy turns every retire into a full cache "
                "spill and every turn into wall-to-wall page faults"
            )
        if self.host_kv_restore_slots < 1:
            raise ValueError(
                "host_kv_restore_slots must be >= 1 (a restore budget of "
                "0 would wedge every faulting lane forever)"
            )
        if self.prefill_chunk < 0:
            raise ValueError("prefill_chunk must be >= 0 (0 → max bucket)")
        if self.ragged_dispatch:
            # Speculative engines ride the same flat stream since
            # ISSUE 19: verify windows are ordinary per-sequence ranges,
            # so draft models compose with ragged_dispatch (the old
            # refusal is gone).
            if self.dp * self.num_slices > 1 or self.sp > 1 or self.pp > 1:
                raise ValueError(
                    "ragged_dispatch serves tp-at-most meshes: the flat "
                    "token stream does not shard over dp/sp/pp (got "
                    f"dp={self.dp}×slices={self.num_slices}, sp={self.sp}, "
                    f"pp={self.pp})"
                )
        if self.prefill_budget < 0:
            raise ValueError(
                "prefill_budget must be >= 0 (0 → 2 x prefill chunk)"
            )
        if self.decode_block_steps < 1:
            raise ValueError("decode_block_steps must be >= 1")
        if self.lookahead_blocks < 1:
            raise ValueError("lookahead_blocks must be >= 1")
        if self.timeline_capacity < 0:
            raise ValueError(
                "timeline_capacity must be >= 0 (0 disables the ring)"
            )
        if self.blackbox_every < 0:
            raise ValueError(
                "blackbox_every must be >= 0 (0 disables black boxes)"
            )
        if self.signals_interval_s < 0:
            raise ValueError(
                "signals_interval_s must be >= 0 (0 disables the plane)"
            )
        if self.quantize_bits not in (4, 8):
            raise ValueError("quantize_bits must be 4 or 8")
        if self.kv_dtype not in ("", "bfloat16", "float32", "int8"):
            raise ValueError(
                "kv_dtype must be '', bfloat16, float32, or int8; "
                f"got {self.kv_dtype!r}"
            )
        if self.top_p_candidates < 0:
            raise ValueError("top_p_candidates must be >= 0 (0 → exact)")
        if self.max_queue_depth < 0:
            raise ValueError("max_queue_depth must be >= 0 (0 → unbounded)")
        if self.max_engine_restarts < 0:
            raise ValueError("max_engine_restarts must be >= 0")
        if self.restart_window_s <= 0:
            raise ValueError("restart_window_s must be > 0")
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if self.replica < 0:
            raise ValueError("replica index must be >= 0")
        if self.max_reroutes < 0:
            raise ValueError("max_reroutes must be >= 0 (0 → no failover)")
        self.disagg_tiers()      # raises on a malformed spec
        if self.disagg and self.replicas > 1:
            raise ValueError(
                "POLYKEY_DISAGG and POLYKEY_REPLICAS>1 are mutually "
                "exclusive: the disaggregated tier replaces the "
                "in-process replica pool (each tier already scales by "
                "worker count)"
            )
        if self.disagg and self.draft_model is not None:
            raise ValueError(
                "disaggregated tiers have no speculative formulation yet "
                "(the KV handoff ships one pool; the draft pool would "
                "need its own) — unset POLYKEY_DISAGG or the draft model"
            )
        if self.disagg_tier not in ("", "prefill", "decode"):
            raise ValueError(
                f"disagg_tier must be '', 'prefill', or 'decode'; got "
                f"{self.disagg_tier!r}"
            )
        if self.disagg_heartbeat_s <= 0:
            raise ValueError("disagg_heartbeat_s must be > 0")
        if self.disagg_miss < 1:
            raise ValueError("disagg_miss must be >= 1")
        if self.disagg_recovery_wait_s < 0:
            raise ValueError("disagg_recovery_wait_s must be >= 0")
        if self.route_prefix_weight < 0 or self.route_delay_weight < 0:
            raise ValueError("routing weights must be >= 0")
        for name in ("tp", "dp", "ep", "sp", "pp", "num_slices"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1")
        if self.sp > 1:
            chunk = self.prefill_chunk or max(self.prefill_buckets)
            for b in (*self.prefill_buckets, chunk):
                if b % self.sp != 0:
                    raise ValueError(
                        f"sp={self.sp} must divide every prefill bucket "
                        f"and the prefill chunk (got {b})"
                    )
