"""Speculative decoding over the paged cache: the engine's draft/verify step.

models/speculative.py proves the draft/verify recurrence on the contiguous
cache; this module carries it into the serving path (measurement config 5 —
BASELINE.md: "server-streamed gRPC with speculative decode"). The cache-
rewind question the contiguous design dodges (VERDICT r1 weak #7) resolves
the same way for the paged layout: position p always maps to the same
physical slot (page_tables[p // page_size], p % page_size), so stale KV
written for rejected drafts at positions ≥ the accepted frontier is
overwritten by the next verify window's own writes *before* any query
attends it — the window starts exactly at the frontier and spans gamma+1
positions, which covers every stale slot (positions advance by ≤ gamma+1
per round). The engine allocates `gamma` extra positions of page slack per
request so the final window's overdraft lands in owned pages, never page 0.

Per-row sampling settings are data (temperature [B], top_p [B]): greedy
rows accept by exact argmax match; sampled rows use Leviathan-style
rejection sampling. top_p composes with speculation by truncating BOTH
distributions: the draft samples from its top-p-truncated dist q' and the
verify accepts against the top-p-truncated target p' — the rejection
identity (accept min(1, p'/q'), residual (p'-q')+) holds for any pair of
distributions, and p' is exactly what the plain sampled path draws from,
so outputs stay target-exact. Truncation uses the same top-k prefilter as
sampling.py (`candidates`; full-vocab probabilities via logsumexp, no
sort); candidates=0 disables the top-p path, and the engine then routes
top_p<1 batches through the plain decode step instead.

RNG: every draw keys on fold_in(lane seed key, token position) plus a
stream tag (draft sample / acceptance uniform / residual), so WITHIN the
spec path a seeded request's randomness is reproducible. Note the spec
path's sampled STREAM differs from the plain path's for the same seed
(drafts draw from the draft model's distribution before acceptance), and
which path a block takes can depend on batchmates (engine._dispatch_step
gates on the whole batch) — so spec-enabled engines guarantee greedy
exactness and distributional reproducibility, not draw-for-draw
batch-independence; plain engines guarantee the full contract.

Both functions are pure; the engine jits them with its mesh out_shardings.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..models.config import ModelConfig
from ..models.transformer import forward_paged, unembed
from .sampling import (
    _row_categorical,
    fold_positions,
    lane_keys,
    sample_tail,
    truncated_dist,
)


def spec_prefill_fn(
    t_params, d_params, t_cfg: ModelConfig, d_cfg: ModelConfig,
    t_paged, d_paged,
    tokens, start, last_rel, page_table, seeds, temperature, top_p, top_k,
    greedy: bool = False, candidates: int = 0, mesh=None,
):
    """Prefill BOTH caches for N windows; first tokens from the TARGET.

    Same contract as engine._prefill_fn (N windows at per-row start
    offsets + relative sampling indices → serves batched burst
    admissions, single admissions, and long-prompt chunks alike) plus
    the draft pool: the draft model must see the full prompt or its
    proposals start from a cold cache and acceptance collapses.
    """
    N, T = tokens.shape
    positions = start[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    hidden, t_paged = forward_paged(
        t_params, t_cfg, tokens, positions, t_paged, page_table, mesh=mesh
    )
    _, d_paged = forward_paged(
        d_params, d_cfg, tokens, positions, d_paged, page_table, mesh=mesh
    )
    last = hidden[jnp.arange(N), last_rel]                # [N, H]
    logits = unembed(t_params, t_cfg, last)               # [N, V]
    token = sample_tail(
        logits, seeds, start + last_rel + 1, temperature, top_p, top_k,
        greedy, candidates,
    )
    return token, t_paged, d_paged


def spec_decode_fn(
    t_params, d_params, t_cfg: ModelConfig, d_cfg: ModelConfig,
    t_paged, d_paged,
    last_tokens, seq_lens, page_tables, active, caps, seeds, temperature,
    top_p, top_k, gamma: int, eos_id: int, candidates: int = 0, mesh=None,
):
    """One draft/verify round for the whole slot batch.

    Returns (emit [B, gamma+1] packed — token id within each row's emitted
    prefix, -1 beyond it, so ONE D2H transfer carries tokens and counts —
    plus new_last [B], new_seq_lens [B], new_active [B], stats, t_paged,
    d_paged). Row semantics: `last_tokens` is
    the already-emitted token at position seq_lens-1 whose KV is not yet
    written (the same invariant as the plain decode step); the round emits
    n_out = n_acc+1 tokens per active row. Greedy rows reproduce the
    target's exact greedy chain for any draft model.

    Liveness is tracked ON DEVICE, mirroring the host's _maybe_finish the
    way the plain block does (engine._decode_fn): n_out truncates at the
    first EOS and at the position cap, and `new_active` goes False for
    stopped rows — so a host-finished stream is already stopped here and
    stale lookahead rounds emit nothing and write only stationary garbage
    inside the row's own gamma page slack.
    """
    B = last_tokens.shape[0]
    rows = jnp.arange(B, dtype=jnp.int32)
    pos = jnp.maximum(seq_lens - 1, 0)
    greedy_row = temperature == 0.0                       # [B]
    temp = jnp.maximum(temperature, 1e-6)                 # [B]
    # Per-lane RNG roots; each draw keys on fold_in(base, token position)
    # plus a stream tag, so draft sampling / acceptance / residual draws
    # are independent AND a request's randomness is reproducible and
    # batch-independent (same contract as the plain path's sampling.sample_tail).
    base = lane_keys(seeds[:, 0], seeds[:, 1])            # [B, 2]

    def _tagged(positions, tag):
        """Per-lane keys fold_in(fold_in(base, position), tag) for [B] or
        [B, n] positions — THE key-derivation scheme; acceptance uniforms
        and residual draws must use this same helper so the (seed,
        position, tag) contract cannot drift between streams."""
        def one(base_row, p):
            return jax.random.fold_in(jax.random.fold_in(base_row, p), tag)

        if positions.ndim == 1:
            return jax.vmap(one)(base, positions)
        return jax.vmap(
            lambda b, ps: jax.vmap(lambda q: one(b, q))(ps)
        )(base, positions)
    # Greedy rows must see untruncated dists (their acceptance is argmax
    # equality; truncation is irrelevant and top_p may be any value).
    eff_top_p = jnp.where(greedy_row, 1.0, top_p)         # [B]
    eff_top_k = jnp.where(greedy_row, 0, top_k)           # [B]

    # --- Draft gamma tokens autoregressively (bandwidth-light model). -----
    def draft_step(carry, _):
        d_paged, tok, p = carry
        hidden, d_paged = forward_paged(
            d_params, d_cfg, tok[:, None], p[:, None], d_paged, page_tables,
            mesh=mesh,
        )
        logits = unembed(d_params, d_cfg, hidden[:, 0])   # [B, V]
        dist = (
            truncated_dist(logits, temp, eff_top_p, eff_top_k, candidates)
            if candidates
            else jax.nn.softmax(logits / temp[:, None], axis=-1)
        )
        sampled = _row_categorical(
            _tagged(p + 1, 101), jnp.log(jnp.maximum(dist, 1e-20))
        )
        nxt = jnp.where(
            greedy_row, jnp.argmax(logits, axis=-1).astype(jnp.int32), sampled
        )
        return (d_paged, nxt, p + 1), (nxt, dist)

    (d_paged, _, _), (drafts, d_dists) = jax.lax.scan(
        draft_step, (d_paged, last_tokens, pos), None, length=gamma
    )
    drafts = drafts.T                                     # [B, gamma]
    d_dists = jnp.swapaxes(d_dists, 0, 1)                 # [B, gamma, V]

    # --- Verify: ONE target forward over [prev, drafts] (gamma+1 wide —
    # prefill-shaped MXU work instead of gamma bandwidth-bound steps). -----
    window = jnp.concatenate([last_tokens[:, None], drafts], axis=1)
    w_pos = pos[:, None] + jnp.arange(gamma + 1, dtype=jnp.int32)[None, :]
    t_hidden, t_paged = forward_paged(
        t_params, t_cfg, window, w_pos, t_paged, page_tables, mesh=mesh
    )
    t_logits = unembed(t_params, t_cfg, t_hidden)         # [B, gamma+1, V]
    # Draft-cache sync over the same window: the scan wrote pos..pos+γ-1
    # only, so on full acceptance slot pos+γ would be a permanent hole
    # (models/speculative.py:164-169 rationale, ported to pages).
    _, d_paged = forward_paged(
        d_params, d_cfg, window, w_pos, d_paged, page_tables, mesh=mesh
    )

    # --- Acceptance: exact-match for greedy rows, rejection sampling else
    # (shared math: models/speculative.py rejection_accept /
    # residual_extra_dist — one implementation for both cache layouts). ---
    from ..models.speculative import rejection_accept, residual_extra_dist

    t_choice = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # [B, γ+1]
    match = drafts == t_choice[:, :gamma]
    draft_idx = pos[:, None] + 1 + jnp.arange(gamma, dtype=jnp.int32)[None, :]

    if candidates:
        t_probs = truncated_dist(
            t_logits,
            jnp.broadcast_to(temp[:, None], t_logits.shape[:2]),
            jnp.broadcast_to(eff_top_p[:, None], t_logits.shape[:2]),
            jnp.broadcast_to(eff_top_k[:, None], t_logits.shape[:2]),
            candidates,
        )
    else:
        t_probs = jax.nn.softmax(t_logits / temp[:, None, None], axis=-1)
    u = jax.vmap(jax.vmap(lambda k: jax.random.uniform(k)))(
        _tagged(draft_idx, 102)
    )                                                     # [B, gamma]
    accept_sampled = rejection_accept(t_probs, d_dists, drafts, u)

    accept = jnp.where(greedy_row[:, None], match, accept_sampled)
    acc = jnp.cumprod(accept.astype(jnp.int32), axis=1)
    n_acc = jnp.sum(acc, axis=1)                          # [B]

    # Extra token: target argmax at the frontier (greedy) / residual or
    # bonus sample (sampled rows) [Leviathan et al. 2023].
    dist = residual_extra_dist(t_probs, d_dists, n_acc)
    extra_sampled = _row_categorical(
        _tagged(pos + 1 + n_acc, 103), jnp.log(jnp.maximum(dist, 1e-20))
    )
    extra = jnp.where(greedy_row, t_choice[rows, n_acc], extra_sampled)

    # --- Emit accepted prefix + extra; advance per-row state. -------------
    emit = jnp.concatenate([drafts, jnp.zeros((B, 1), jnp.int32)], axis=1)
    emit = emit.at[rows, n_acc].set(extra)                # [B, gamma+1]
    n_out = (n_acc + 1) * active.astype(jnp.int32)

    # Device-side stopping (mirrors engine._decode_fn / host _maybe_finish):
    # truncate at the first EOS in the emitted prefix and at the row's
    # position cap, and retire stopped rows from the next round.
    cols = jnp.arange(gamma + 1, dtype=jnp.int32)[None, :]
    is_eos = (emit == eos_id) & (cols < n_out[:, None])
    has_eos = jnp.any(is_eos, axis=1)
    first_eos = jnp.argmax(is_eos, axis=1).astype(jnp.int32)
    n_out = jnp.where(has_eos, first_eos + 1, n_out)
    n_out = jnp.minimum(n_out, jnp.maximum(caps - seq_lens, 0))

    emit = jnp.where(active[:, None], emit, 0)
    new_seq_lens = seq_lens + n_out
    new_last = jnp.where(
        active & (n_out > 0), emit[rows, jnp.maximum(n_out - 1, 0)], last_tokens
    )
    new_active = active & ~has_eos & (new_seq_lens < caps)
    packed = jnp.where(cols < n_out[:, None], emit, -1)   # [B, gamma+1]

    # Acceptance-dial stats, computed HERE because truncation happens here
    # (the host only sees truncated n_out): per ADVICE r1, a round cut
    # short by EOS/cap counts only the drafts that had a chance to be
    # emitted — sent/sent, so a perfect draft reads exactly 1.0 — while a
    # full round counts n_acc/gamma. Inactive lanes contribute nothing.
    untrunc = (n_acc + 1) * active.astype(jnp.int32)
    cut = n_out < untrunc
    acc_rows = jnp.minimum(jnp.maximum(untrunc - 1, 0), n_out)
    prop_rows = jnp.where(cut, n_out, gamma) * active.astype(jnp.int32)
    stats = jnp.stack([jnp.sum(acc_rows), jnp.sum(prop_rows)])

    return (
        packed, new_last, new_seq_lens, new_active, stats,
        t_paged, d_paged,
    )
