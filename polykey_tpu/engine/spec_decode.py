"""Speculative decoding over the paged cache: the engine's draft/verify step.

models/speculative.py proves the draft/verify recurrence on the contiguous
cache; this module carries it into the serving path (measurement config 5 —
BASELINE.md: "server-streamed gRPC with speculative decode"). The cache-
rewind question the contiguous design dodges (VERDICT r1 weak #7) resolves
the same way for the paged layout: position p always maps to the same
physical slot (page_tables[p // page_size], p % page_size), so stale KV
written for rejected drafts at positions ≥ the accepted frontier is
overwritten by the next verify window's own writes *before* any query
attends it — the window starts exactly at the frontier and spans gamma+1
positions, which covers every stale slot (positions advance by ≤ gamma+1
per round). The engine allocates `gamma` extra positions of page slack per
request so the final window's overdraft lands in owned pages, never page 0.

The round is FULLY device-resident (ISSUE 19): acceptance, the extra-token
draw, EOS/cap truncation, per-row state advancement, AND the per-lane
adaptive-gamma dial all run inside one jitted step. The host reads ONE
packed int32 matrix per round — gamma+1 emit columns followed by
SPEC_STAT_COLS stat columns (accepted, proposed, acceptance EWMA in 1e-6
fixed point, next gamma dial) — through the same once-per-block D2H copy
the lookahead pipeline overlaps, instead of the old packed + stats pair.

Per-lane gamma: `gamma_lane` [B] rides the donated slot state. A lane at
dial g < gamma simply never offers drafts beyond g (force-masked in the
acceptance scan), so ONE executable per static `gamma` serves every mix of
dials; when every offered draft is accepted the extra token is the
Leviathan BONUS sample from the target's own distribution at the frontier
(the masked positions were never offered — taking the residual there would
charge the lane for a rejection that never happened). The dial itself
updates on device from a per-lane acceptance EWMA with the same hysteresis
band the old engine-global host ladder used (constants below).

Per-row sampling settings are data (temperature [B], top_p [B]): greedy
rows accept by exact argmax match; sampled rows use Leviathan-style
rejection sampling. top_p composes with speculation by truncating BOTH
distributions: the draft samples from its top-p-truncated dist q' and the
verify accepts against the top-p-truncated target p' — the rejection
identity (accept min(1, p'/q'), residual (p'-q')+) holds for any pair of
distributions, and p' is exactly what the plain sampled path draws from,
so outputs stay target-exact. Truncation uses the same top-k prefilter as
sampling.py (`candidates`; full-vocab probabilities via logsumexp, no
sort); candidates=0 disables the top-p path, and the engine then routes
top_p<1 batches through the plain decode step instead.

RNG: every draw keys on fold_in(lane seed key, token position) plus a
stream tag (draft sample / acceptance uniform / residual), so WITHIN the
spec path a seeded request's randomness is reproducible. Note the spec
path's sampled STREAM differs from the plain path's for the same seed
(drafts draw from the draft model's distribution before acceptance), and
which path a block takes can depend on batchmates (engine._dispatch_step
gates on the whole batch) — so spec-enabled engines guarantee greedy
exactness and distributional reproducibility, not draw-for-draw
batch-independence; plain engines guarantee the full contract.

`ragged_spec_fn` lifts the spec×ragged exclusion (ISSUE 19 tentpole b):
the gamma+1-token verify windows ride the flat ragged token stream as
ordinary per-sequence ranges in the scalar-prefetch metadata — rows
[0, B·(gamma+1)) are the verify windows, rows [B·(gamma+1), +W) the
prefill stream — so ONE mixed dispatch serves prefill chunks AND spec
verify lanes. The draft model runs its own ragged forward over the SAME
flat stream: for verify rows that is the draft-cache sync rewrite, for
prefill rows it is the draft-cache prompt prefill — one pass does both
jobs the bucketed path needed spec_prefill_fn + a window rewrite for.

All functions are pure; the engine jits them with its mesh out_shardings.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..models.config import ModelConfig
from ..models.transformer import forward_paged, forward_ragged, unembed
from .sampling import (
    _row_categorical,
    lane_keys,
    sample_tail,
    truncated_dist,
)

# --- Per-lane adaptive-gamma dial (ISSUE 19 tentpole c). -------------------
# The EWMA blend and hysteresis band live HERE because the update now runs
# inside the jitted round (device-resident, zero crossings); the engine and
# the autopilot import these so host-side reasoning about the dial cannot
# drift from what the device computes.
GAMMA_EWMA_BETA = 0.8        # the old host ladder's 0.8/0.2 blend
GAMMA_ACCEPT_FLOOR = 0.35    # EWMA below → lane dials down to gamma_low
GAMMA_ACCEPT_CEIL = 0.55     # EWMA above → lane dials back to gamma_max

# Stat columns appended after the gamma+1 emit columns of the packed row:
# [accepted, proposed, acceptance EWMA (1e-6 fixed point), next gamma
# dial]. ONE packed [B, gamma+1+SPEC_STAT_COLS] readback per round carries
# tokens, counts, and the dial — the collapse of the old separate stats
# vector readback.
SPEC_STAT_COLS = 4


def _lane_tagger(seeds):
    """Per-lane RNG roots; each draw keys on fold_in(base, token position)
    plus a stream tag, so draft sampling / acceptance / residual draws are
    independent AND a request's randomness is reproducible and
    batch-independent (same contract as the plain path's
    sampling.sample_tail). THE key-derivation scheme: acceptance uniforms
    and residual draws must use this same helper so the (seed, position,
    tag) contract cannot drift between streams."""
    base = lane_keys(seeds[:, 0], seeds[:, 1])            # [B, 2]

    def tagged(positions, tag):
        def one(base_row, p):
            return jax.random.fold_in(jax.random.fold_in(base_row, p), tag)

        if positions.ndim == 1:
            return jax.vmap(one)(base, positions)
        return jax.vmap(
            lambda b, ps: jax.vmap(lambda q: one(b, q))(ps)
        )(base, positions)

    return tagged


def _draft_scan(
    d_params, d_cfg, d_paged, last_tokens, pos, page_tables, greedy_row,
    temp, eff_top_p, eff_top_k, tagged, gamma, candidates, mesh,
):
    """Draft gamma tokens autoregressively (bandwidth-light model).

    Returns (d_paged, drafts [B, gamma], d_dists [B, gamma, V])."""

    def draft_step(carry, _):
        d_paged, tok, p = carry
        hidden, d_paged = forward_paged(
            d_params, d_cfg, tok[:, None], p[:, None], d_paged, page_tables,
            mesh=mesh,
        )
        logits = unembed(d_params, d_cfg, hidden[:, 0])   # [B, V]
        dist = (
            truncated_dist(logits, temp, eff_top_p, eff_top_k, candidates)
            if candidates
            else jax.nn.softmax(logits / temp[:, None], axis=-1)
        )
        sampled = _row_categorical(
            tagged(p + 1, 101), jnp.log(jnp.maximum(dist, 1e-20))
        )
        nxt = jnp.where(
            greedy_row, jnp.argmax(logits, axis=-1).astype(jnp.int32), sampled
        )
        return (d_paged, nxt, p + 1), (nxt, dist)

    (d_paged, _, _), (drafts, d_dists) = jax.lax.scan(
        draft_step, (d_paged, last_tokens, pos), None, length=gamma
    )
    drafts = drafts.T                                     # [B, gamma]
    d_dists = jnp.swapaxes(d_dists, 0, 1)                 # [B, gamma, V]
    return d_paged, drafts, d_dists


def _accept_merge(
    t_logits, drafts, d_dists, last_tokens, seq_lens, active, caps,
    accept_ewma, gamma_lane, pos, greedy_row, temp, eff_top_p, eff_top_k,
    tagged, *, gamma: int, gamma_low: int, gamma_max: int, eos_id: int,
    candidates: int,
):
    """The fused accept/merge core (ISSUE 19 tentpole a) — shared by
    spec_decode_fn (bucketed) and ragged_spec_fn so the acceptance math,
    truncation, and the gamma dial cannot drift between dispatch modes.

    Acceptance: exact-match for greedy rows, rejection sampling else
    (shared math: models/speculative.py rejection_accept /
    residual_extra_dist — one implementation for both cache layouts).

    Device-side stopping mirrors engine._decode_fn / host _maybe_finish:
    n_out truncates at the first EOS and at the position cap, and
    `new_active` goes False for stopped rows — so a host-finished stream
    is already stopped here and stale lookahead rounds emit nothing and
    write only stationary garbage inside the row's own gamma page slack.

    Returns (packed [B, gamma+1+SPEC_STAT_COLS], new_last, new_seq_lens,
    new_active, new_ewma, new_gamma_lane)."""
    from ..models.speculative import rejection_accept, residual_extra_dist

    B = last_tokens.shape[0]
    rows = jnp.arange(B, dtype=jnp.int32)
    t_choice = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # [B, γ+1]
    match = drafts == t_choice[:, :gamma]
    draft_idx = pos[:, None] + 1 + jnp.arange(gamma, dtype=jnp.int32)[None, :]

    if candidates:
        t_probs = truncated_dist(
            t_logits,
            jnp.broadcast_to(temp[:, None], t_logits.shape[:2]),
            jnp.broadcast_to(eff_top_p[:, None], t_logits.shape[:2]),
            jnp.broadcast_to(eff_top_k[:, None], t_logits.shape[:2]),
            candidates,
        )
    else:
        t_probs = jax.nn.softmax(t_logits / temp[:, None, None], axis=-1)
    u = jax.vmap(jax.vmap(lambda k: jax.random.uniform(k)))(
        tagged(draft_idx, 102)
    )                                                     # [B, gamma]
    accept_sampled = rejection_accept(t_probs, d_dists, drafts, u)

    accept = jnp.where(greedy_row[:, None], match, accept_sampled)
    # Per-lane dial: a lane at dial g < gamma never OFFERS drafts beyond
    # g — they are force-masked here, so one executable per static gamma
    # serves every mix of dials.
    g_lane = jnp.clip(gamma_lane, 1, gamma)               # [B]
    offered = jnp.arange(gamma, dtype=jnp.int32)[None, :] < g_lane[:, None]
    accept = accept & offered
    acc = jnp.cumprod(accept.astype(jnp.int32), axis=1)
    n_acc = jnp.sum(acc, axis=1)                          # [B]

    # Extra token: target argmax at the frontier (greedy) / residual or
    # bonus sample (sampled rows) [Leviathan et al. 2023]. A lane whose
    # OFFERED drafts were all accepted takes the bonus (target) dist at
    # the frontier, never the residual — the masked positions past its
    # dial were never offered, so there is no rejection to correct for.
    bonus = n_acc >= g_lane
    dist = jnp.where(
        bonus[:, None],
        t_probs[rows, n_acc],
        residual_extra_dist(t_probs, d_dists, n_acc),
    )
    extra_sampled = _row_categorical(
        tagged(pos + 1 + n_acc, 103), jnp.log(jnp.maximum(dist, 1e-20))
    )
    extra = jnp.where(greedy_row, t_choice[rows, n_acc], extra_sampled)

    # --- Emit accepted prefix + extra; advance per-row state. -------------
    emit = jnp.concatenate([drafts, jnp.zeros((B, 1), jnp.int32)], axis=1)
    emit = emit.at[rows, n_acc].set(extra)                # [B, gamma+1]
    n_out = (n_acc + 1) * active.astype(jnp.int32)

    cols = jnp.arange(gamma + 1, dtype=jnp.int32)[None, :]
    is_eos = (emit == eos_id) & (cols < n_out[:, None])
    has_eos = jnp.any(is_eos, axis=1)
    first_eos = jnp.argmax(is_eos, axis=1).astype(jnp.int32)
    n_out = jnp.where(has_eos, first_eos + 1, n_out)
    n_out = jnp.minimum(n_out, jnp.maximum(caps - seq_lens, 0))

    emit = jnp.where(active[:, None], emit, 0)
    new_seq_lens = seq_lens + n_out
    new_last = jnp.where(
        active & (n_out > 0), emit[rows, jnp.maximum(n_out - 1, 0)], last_tokens
    )
    new_active = active & ~has_eos & (new_seq_lens < caps)
    tokens_out = jnp.where(cols < n_out[:, None], emit, -1)  # [B, gamma+1]

    # Acceptance-dial stats, computed HERE because truncation happens here
    # (the host only sees truncated n_out): per ADVICE r1, a round cut
    # short by EOS/cap counts only the drafts that had a chance to be
    # emitted — sent/sent, so a perfect draft reads exactly 1.0 — while a
    # full round counts n_acc over the lane's OFFERED count (its dial,
    # not the static gamma). Inactive lanes contribute nothing.
    untrunc = (n_acc + 1) * active.astype(jnp.int32)
    cut = n_out < untrunc
    acc_rows = jnp.minimum(jnp.maximum(untrunc - 1, 0), n_out)
    prop_rows = jnp.where(cut, n_out, g_lane) * active.astype(jnp.int32)

    # Per-lane dial update, ON DEVICE: the old engine-global host ladder
    # (engine.py _process_spec) moves here, one EWMA + hysteresis band per
    # lane, carried in the donated slot state so it costs no crossings.
    rate = acc_rows.astype(jnp.float32) / jnp.maximum(
        prop_rows, 1
    ).astype(jnp.float32)
    new_ewma = jnp.where(
        prop_rows > 0,
        GAMMA_EWMA_BETA * accept_ewma + (1.0 - GAMMA_EWMA_BETA) * rate,
        accept_ewma,
    )
    # Hold band keeps the STORED dial (not the clipped g_lane): a round
    # dispatched at the low rung must not silently forget that a lane's
    # dial was at gamma_max.
    new_gamma_lane = jnp.where(
        new_ewma < GAMMA_ACCEPT_FLOOR,
        jnp.int32(gamma_low),
        jnp.where(
            new_ewma > GAMMA_ACCEPT_CEIL,
            jnp.int32(gamma_max),
            jnp.clip(gamma_lane, gamma_low, gamma_max),
        ),
    ).astype(jnp.int32)

    packed = jnp.concatenate([
        tokens_out,
        acc_rows[:, None],
        prop_rows[:, None],
        jnp.round(new_ewma * 1e6).astype(jnp.int32)[:, None],
        new_gamma_lane[:, None],
    ], axis=1)                                # [B, gamma+1+SPEC_STAT_COLS]
    return packed, new_last, new_seq_lens, new_active, new_ewma, new_gamma_lane


def spec_prefill_fn(
    t_params, d_params, t_cfg: ModelConfig, d_cfg: ModelConfig,
    t_paged, d_paged,
    tokens, start, last_rel, page_table, seeds, temperature, top_p, top_k,
    greedy: bool = False, candidates: int = 0, mesh=None,
):
    """Prefill BOTH caches for N windows; first tokens from the TARGET.

    Same contract as engine._prefill_fn (N windows at per-row start
    offsets + relative sampling indices → serves batched burst
    admissions, single admissions, and long-prompt chunks alike) plus
    the draft pool: the draft model must see the full prompt or its
    proposals start from a cold cache and acceptance collapses.
    """
    N, T = tokens.shape
    positions = start[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    hidden, t_paged = forward_paged(
        t_params, t_cfg, tokens, positions, t_paged, page_table, mesh=mesh
    )
    _, d_paged = forward_paged(
        d_params, d_cfg, tokens, positions, d_paged, page_table, mesh=mesh
    )
    last = hidden[jnp.arange(N), last_rel]                # [N, H]
    logits = unembed(t_params, t_cfg, last)               # [N, V]
    token = sample_tail(
        logits, seeds, start + last_rel + 1, temperature, top_p, top_k,
        greedy, candidates,
    )
    return token, t_paged, d_paged


def spec_decode_fn(
    t_params, d_params, t_cfg: ModelConfig, d_cfg: ModelConfig,
    t_paged, d_paged,
    last_tokens, seq_lens, page_tables, active, caps, seeds, temperature,
    top_p, top_k, accept_ewma, gamma_lane,
    gamma: int, eos_id: int, gamma_low: int | None = None,
    gamma_max: int | None = None, candidates: int = 0, mesh=None,
):
    """One draft/verify round for the whole slot batch (bucketed path).

    Returns (packed [B, gamma+1+SPEC_STAT_COLS] — emit token id within
    each row's emitted prefix, -1 beyond it, then the stat columns, so
    ONE D2H transfer carries tokens, counts, AND the gamma dial — plus
    new_last [B], new_seq_lens [B], new_active [B], new_ewma [B],
    new_gamma_lane [B], t_paged, d_paged). Row semantics: `last_tokens`
    is the already-emitted token at position seq_lens-1 whose KV is not
    yet written (the same invariant as the plain decode step); the round
    emits n_out = n_acc+1 tokens per active row. Greedy rows reproduce
    the target's exact greedy chain for any draft model.
    """
    if gamma_low is None:
        gamma_low = gamma
    if gamma_max is None:
        gamma_max = gamma
    B = last_tokens.shape[0]
    pos = jnp.maximum(seq_lens - 1, 0)
    greedy_row = temperature == 0.0                       # [B]
    temp = jnp.maximum(temperature, 1e-6)                 # [B]
    tagged = _lane_tagger(seeds)
    # Greedy rows must see untruncated dists (their acceptance is argmax
    # equality; truncation is irrelevant and top_p may be any value).
    eff_top_p = jnp.where(greedy_row, 1.0, top_p)         # [B]
    eff_top_k = jnp.where(greedy_row, 0, top_k)           # [B]

    d_paged, drafts, d_dists = _draft_scan(
        d_params, d_cfg, d_paged, last_tokens, pos, page_tables, greedy_row,
        temp, eff_top_p, eff_top_k, tagged, gamma, candidates, mesh,
    )

    # --- Verify: ONE target forward over [prev, drafts] (gamma+1 wide —
    # prefill-shaped MXU work instead of gamma bandwidth-bound steps). -----
    window = jnp.concatenate([last_tokens[:, None], drafts], axis=1)
    w_pos = pos[:, None] + jnp.arange(gamma + 1, dtype=jnp.int32)[None, :]
    t_hidden, t_paged = forward_paged(
        t_params, t_cfg, window, w_pos, t_paged, page_tables, mesh=mesh
    )
    t_logits = unembed(t_params, t_cfg, t_hidden)         # [B, gamma+1, V]
    # Draft-cache sync over the same window: the scan wrote pos..pos+γ-1
    # only, so on full acceptance slot pos+γ would be a permanent hole
    # (models/speculative.py:164-169 rationale, ported to pages).
    _, d_paged = forward_paged(
        d_params, d_cfg, window, w_pos, d_paged, page_tables, mesh=mesh
    )

    packed, new_last, new_seq_lens, new_active, new_ewma, new_gamma_lane = (
        _accept_merge(
            t_logits, drafts, d_dists, last_tokens, seq_lens, active, caps,
            accept_ewma, gamma_lane, pos, greedy_row, temp, eff_top_p,
            eff_top_k, tagged, gamma=gamma, gamma_low=gamma_low,
            gamma_max=gamma_max, eos_id=eos_id, candidates=candidates,
        )
    )
    return (
        packed, new_last, new_seq_lens, new_active, new_ewma,
        new_gamma_lane, t_paged, d_paged,
    )


def ragged_spec_fn(
    t_params, d_params, t_cfg: ModelConfig, d_cfg: ModelConfig,
    t_paged, d_paged,
    last_tokens, seq_lens, page_tables, active, caps, seeds, temperature,
    top_p, top_k, accept_ewma, gamma_lane,
    pre_tokens, pre_pos, pre_table_idx, pre_tables,
    pre_range_start, pre_range_len, pre_range_kv, pre_range_table,
    pre_sample_idx, pre_sample_pos, pre_seeds, pre_temp, pre_top_p,
    pre_top_k,
    *, gamma: int, eos_id: int, gamma_low: int | None = None,
    gamma_max: int | None = None, greedy: bool = False,
    candidates: int = 0, mesh=None,
):
    """ONE ragged dispatch for mixed prefill + SPEC VERIFY lanes (ISSUE 19
    tentpole b — the lifted spec×ragged exclusion): every decode lane runs
    a full draft/verify round AND up to `W` prefill tokens advance, in one
    flat ragged forward per model.

    Layout: flat rows [0, B·(gamma+1)) are the verify windows ([prev,
    drafts] per lane, lane-major — lane b's window is rows b·(gamma+1)..);
    rows [B·(gamma+1), +W) are the prefill stream, with the same
    `pre_*` operand contract as engine._ragged_fn (pre_table_idx == B →
    the all-garbage table row; unused ranges sit past the stream end).
    The verify windows enter the ragged sequence metadata as ordinary
    per-sequence ranges: starts b·(gamma+1), length gamma+1, kv frontier
    max(seq_lens,1)+gamma — gamma-token speculation IS just a ragged
    range, which is the whole point.

    The draft model's ragged forward runs over the SAME flat stream:
    verify rows give the draft-cache sync rewrite (the bucketed path's
    post-scan window forward), prefill rows give the draft-cache prompt
    prefill (the bucketed path's spec_prefill_fn second forward) — one
    pass, both jobs.

    Sampling mirrors the bucketed paths EXACTLY: verify lanes use the
    shared _accept_merge core (greedy rows reproduce the target's greedy
    chain bit-for-bit), and per slot b `pre_sample_idx[b]` names the
    prefill-stream row whose hidden state samples that slot's FIRST token
    at position key `pre_sample_pos[b]`, exactly as in _ragged_fn (the
    host merges only final-chunk slots; other rows' draws are discarded).

    Returns (packed [B, gamma+1+SPEC_STAT_COLS], new_last, new_seq_lens,
    new_active, new_ewma, new_gamma_lane, first [B], t_paged, d_paged).
    """
    if gamma_low is None:
        gamma_low = gamma
    if gamma_max is None:
        gamma_max = gamma
    B = last_tokens.shape[0]
    W = pre_tokens.shape[0]
    G1 = gamma + 1
    pos = jnp.maximum(seq_lens - 1, 0)
    greedy_row = temperature == 0.0                       # [B]
    temp = jnp.maximum(temperature, 1e-6)                 # [B]
    tagged = _lane_tagger(seeds)
    eff_top_p = jnp.where(greedy_row, 1.0, top_p)         # [B]
    eff_top_k = jnp.where(greedy_row, 0, top_k)           # [B]

    # Draft proposals: the same bandwidth-light autoregressive scan as the
    # bucketed path (the draft runs B×1 paged steps — its work is not
    # range-shaped; only the WIDE forwards ride the ragged stream).
    d_paged, drafts, d_dists = _draft_scan(
        d_params, d_cfg, d_paged, last_tokens, pos, page_tables, greedy_row,
        temp, eff_top_p, eff_top_k, tagged, gamma, candidates, mesh,
    )

    # --- Flat stream: B verify windows then the prefill stream. -----------
    window = jnp.concatenate([last_tokens[:, None], drafts], axis=1)
    w_pos = pos[:, None] + jnp.arange(G1, dtype=jnp.int32)[None, :]
    tokens = jnp.concatenate([window.reshape(-1), pre_tokens])   # [B·G1+W]
    positions = jnp.concatenate([w_pos.reshape(-1), pre_pos])
    garbage_row = jnp.zeros_like(pre_tables[:1])
    tables_ext = jnp.concatenate([pre_tables, garbage_row])      # [B+1, P]
    token_tables = jnp.concatenate([
        jnp.repeat(page_tables, G1, axis=0), tables_ext[pre_table_idx],
    ])                                                           # [B·G1+W, P]
    # Ragged sequence metadata: B verify ranges then the prefill ranges,
    # starts ascending (unused prefill ranges sit past the stream end).
    rng_starts = jnp.concatenate([
        jnp.arange(B, dtype=jnp.int32) * G1, B * G1 + pre_range_start,
    ])
    rng_lens = jnp.concatenate([
        jnp.full((B,), G1, jnp.int32), pre_range_len,
    ])
    rng_kv = jnp.concatenate([
        jnp.maximum(seq_lens, 1) + gamma, pre_range_kv,
    ])
    seq_tables = jnp.concatenate(
        [page_tables, tables_ext[pre_range_table]]
    )                                                            # [2B, P]

    hidden, t_paged = forward_ragged(
        t_params, t_cfg, tokens, positions, t_paged, token_tables,
        rng_starts, rng_lens, rng_kv, seq_tables, mesh=mesh,
    )
    t_logits = unembed(
        t_params, t_cfg, hidden[: B * G1].reshape(B, G1, -1)
    )                                                     # [B, gamma+1, V]
    # Draft ragged forward over the same stream: window sync + prompt
    # prefill in one pass (see module docstring).
    _, d_paged = forward_ragged(
        d_params, d_cfg, tokens, positions, d_paged, token_tables,
        rng_starts, rng_lens, rng_kv, seq_tables, mesh=mesh,
    )

    packed, new_last, new_seq_lens, new_active, new_ewma, new_gamma_lane = (
        _accept_merge(
            t_logits, drafts, d_dists, last_tokens, seq_lens, active, caps,
            accept_ewma, gamma_lane, pos, greedy_row, temp, eff_top_p,
            eff_top_k, tagged, gamma=gamma, gamma_low=gamma_low,
            gamma_max=gamma_max, eos_id=eos_id, candidates=candidates,
        )
    )

    # Prefill first tokens: one row per slot, _ragged_fn verbatim (garbage
    # for slots without a final chunk this dispatch — never read).
    rows = hidden[B * G1 + jnp.clip(pre_sample_idx, 0, W - 1)]   # [B, H]
    first = sample_tail(
        unembed(t_params, t_cfg, rows), pre_seeds, pre_sample_pos,
        pre_temp, pre_top_p, pre_top_k, greedy, candidates,
    )
    return (
        packed, new_last, new_seq_lens, new_active, new_ewma,
        new_gamma_lane, first, t_paged, d_paged,
    )
