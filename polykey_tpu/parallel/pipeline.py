"""GPipe-style pipeline parallelism over the mesh's ``pp`` axis.

SURVEY.md §2b lists pipeline parallelism among the axes the framework owes
the north star; round 1 shipped only the memory distribution (layers stacked
on a leading axis sharded over ``pp``, parallel/sharding.py). This module
adds the actual stage schedule: microbatches enter at stage 0, flow
stage-to-stage over the ICI via ``lax.ppermute``, and every stage computes a
different microbatch concurrently.

Design (TPU-first, not a port — the reference has no ML code at all):

- **Partial-manual shard_map**: the stage loop is manual over ``pp`` only
  (``axis_names={"pp"}``); every other mesh axis (dp/tp/ep/sp) stays under
  GSPMD, so Megatron TP inside a stage keeps its compiler-inserted
  collectives — no hand-written all-reduces in the layer body.
- **One compiled schedule**: the tick loop is a ``lax.scan`` over
  M + P - 1 ticks (M microbatches, P stages). Stage p processes microbatch
  m = t - p at tick t; invalid (m out of range) lanes compute garbage that
  is never written — occupancy is data, not control flow, exactly like the
  engine's slot masks.
- **Same math as the unsharded stack**: stages run
  models.transformer.apply_layer — the identical block body ``lax.scan``
  uses — over their local layer slice, with global layer indices so
  Gemma-2's sliding-window interleaving lands on the right layers.
- **Autodiff = backward schedule**: ``ppermute``/``scan`` transpose cleanly,
  so ``jax.grad`` through this forward yields the mirrored reverse
  pipeline (grads flow stage P-1 → 0); no hand-written backward pass.

Bubble fraction is the GPipe (P-1)/(M+P-1); choose M ≥ ~4·P to amortize.
The collected outputs live on the last stage and are replicated with one
masked ``psum`` over ``pp`` — at [B, T, H] this is the layout where the
final-norm/unembed (vocab-sharded over tp) runs everywhere; a production
multi-pod layout would instead keep logits on the last stage.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.config import ModelConfig
from ..models.transformer import (
    apply_layer,
    embed_tokens,
    make_causal_attend,
)
from ..models.layers import rms_norm
from ..compat import shard_map


def pipeline_forward(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,        # [B, T] int32
    positions: jax.Array,     # [B, T] int32
    mesh: Mesh,
    num_microbatches: int,
) -> jax.Array:
    """Run the transformer stack pipelined over ``mesh``'s pp axis.

    Returns hidden states [B, T, H] after the final norm — the same
    contract as ``forward(...)[0]`` on the no-cache path, so callers
    (train/train.py) unembed identically. Requires num_layers % pp == 0
    and batch % num_microbatches == 0.
    """
    n_stages = mesh.shape["pp"]
    M = num_microbatches
    B, T = tokens.shape
    if cfg.num_layers % n_stages != 0:
        raise ValueError(
            f"pp={n_stages} must divide num_layers={cfg.num_layers}"
        )
    if B % M != 0:
        raise ValueError(f"microbatches={M} must divide batch={B}")
    norm_offset = 1.0 if cfg.scale_embeddings else 0.0

    x = embed_tokens(params, cfg, tokens)               # [B, T, H]
    hidden = _staged(cfg, mesh, M, B, T)(params["layers"], x, positions)

    return rms_norm(
        hidden, params["final_norm"], cfg.rms_norm_eps, norm_offset
    )


@functools.lru_cache(maxsize=32)
def _staged(cfg: ModelConfig, mesh: Mesh, M: int, B: int, T: int):
    """Jitted pipelined stack, memoized per (cfg, mesh, M, B, T) so eager
    callers hit the jit cache instead of re-tracing the schedule per call
    (cfg and Mesh are hashable; the layer pytree is a runtime argument)."""
    n_stages = mesh.shape["pp"]
    layers_per_stage = cfg.num_layers // n_stages

    def stage_fn(local_layers, x, positions):
        # Manual over pp: local_layers is this stage's [L/P, ...] slice;
        # x/positions are pp-replicated (dp/tp shardings stay automatic).
        p = lax.axis_index("pp")

        xs = x.reshape(M, B // M, T, -1)
        pos = positions.reshape(M, B // M, T)

        def run_local(x_in, pos_in):
            attend = make_causal_attend(cfg, pos_in)

            def body(h, scanned):
                lp, idx, kc, vc = scanned
                h, _, _ = apply_layer(
                    lp, idx, h, pos_in, cfg, attend, kc, vc
                )
                return h, None

            idxs = p * layers_per_stage + jnp.arange(
                layers_per_stage, dtype=jnp.int32
            )
            empty = jnp.zeros((layers_per_stage, 0), jnp.float32)
            h, _ = lax.scan(body, x_in, (local_layers, idxs, empty, empty))
            return h

        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        x_state = jnp.zeros_like(xs[0])
        outs = jnp.zeros_like(xs)

        def tick(carry, t):
            x_state, outs = carry
            m = t - p                                   # this stage's microbatch
            m_c = jnp.clip(m, 0, M - 1)
            valid = jnp.logical_and(m >= 0, m < M)
            inject = jnp.logical_and(p == 0, t < M)     # stage 0 feeds in
            x_in = jnp.where(
                inject,
                lax.dynamic_index_in_dim(xs, jnp.clip(t, 0, M - 1), 0, False),
                x_state,
            )
            # Positions are pp-replicated input — index the local copy by
            # microbatch instead of rotating them over the ICI.
            pos_in = lax.dynamic_index_in_dim(pos, m_c, 0, False)
            y = run_local(x_in, pos_in)
            # Last stage banks finished microbatches.
            write = jnp.logical_and(valid, p == n_stages - 1)
            prev = lax.dynamic_index_in_dim(outs, m_c, 0, False)
            outs = lax.dynamic_update_index_in_dim(
                outs, jnp.where(write, y, prev), m_c, 0
            )
            # Rotate activations to the next stage.
            x_next = lax.ppermute(y, "pp", perm)
            return (x_next, outs), None

        (x_state, outs), _ = lax.scan(
            tick,
            (x_state, outs),
            jnp.arange(M + n_stages - 1, dtype=jnp.int32),
        )
        # Results live on the last stage only; masked psum replicates.
        outs = jnp.where(p == n_stages - 1, outs, jnp.zeros_like(outs))
        outs = lax.psum(outs, "pp")
        return outs.reshape(B, T, -1)

    # Partial-manual shard_map (manual pp, auto dp/tp/ep) only traces under
    # jit — eager mode rejects out_specs that leave auto axes unmentioned.
    # The jit is inlined when callers are already tracing (train_step).
    return jax.jit(shard_map(
        stage_fn,
        mesh=mesh,
        in_specs=(P("pp"), P(), P()),
        out_specs=P(),
        axis_names=frozenset({"pp"}),
        check_vma=False,
    ))
