"""Parallelism: device mesh + sharding specs over ICI/DCN.

The reference has no distributed backend at all (SURVEY.md §5: no NCCL/MPI/
Gloo — its only transport is north-south gRPC). The TPU-native equivalent is
not a comm library but a declaration layer: axes on a `jax.sharding.Mesh`
(dp/pp/sp/ep/tp) plus PartitionSpecs on parameters and activations; XLA's
SPMD partitioner inserts the all-gathers/reduce-scatters/all-to-alls that a
GPU stack would issue through NCCL, and lays them onto ICI (intra-slice axes)
or DCN (the leading axis under multi-host `jax.distributed`).
"""

from .mesh import MeshConfig, create_mesh  # noqa: F401
from .sharding import param_shardings, shard_params  # noqa: F401
