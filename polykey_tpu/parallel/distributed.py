"""Multi-host distributed runtime: process bootstrap + hybrid DCN meshes.

The reference has no distributed communication at all (SURVEY.md §5: "no
NCCL/MPI/Gloo/UCX, not even Go multi-process coordination"). The TPU-native
equivalent is not a socket library: `jax.distributed.initialize` brings every
host's chips into one global device list, the mesh declares where each named
axis lives, and XLA emits the collectives — over ICI inside a slice, over
DCN between slices. The mesh + partition specs ARE the comm backend.

Layout rule (mesh.py's axis order makes this automatic): put ONLY
data-parallel on DCN — gradient all-reduce is the one collective whose
volume amortizes DCN latency; tp/sp/ep collectives must stay on ICI.
`create_hybrid_mesh` encodes exactly that: the dp axis is (num_slices ×
per-slice dp), every other axis lives inside a slice.

Bootstrap env (standard JAX multi-process contract, overridable for tests):
    POLYKEY_COORDINATOR   host:port of process 0 (e.g. "10.0.0.1:8476")
    POLYKEY_NUM_PROCESSES total process count
    POLYKEY_PROCESS_ID    this process's rank
On TPU pods these are auto-detected from the metadata server, so
`initialize_from_env()` with no env set simply calls
`jax.distributed.initialize()` when running under a multi-host runtime and
is a no-op on a single host. The gateway server calls this before engine
init (gateway/server.py:_default_service), and the path is executed for
real — two localhost processes, gloo collectives across the boundary —
by tests/test_distributed_multiproc.py / scripts/run_multiproc_demo.sh.

Multi-host SERVING topology (design note): JAX is multi-controller — every
process must dispatch identical programs in identical order — so the
engine's dynamic scheduler (admissions, block sizing, spec-gamma dial)
cannot make independent per-host decisions against one shared mesh.
Two deployment shapes follow:
- **tp/pp within a host, dp across hosts, one engine per host** (the
  shape this framework ships): each host runs its own gateway + engine
  on its local chips; a stateless gRPC load balancer spreads requests.
  No cross-host collective is on the decode path at all, which is
  strictly better than DCN attention reads; the hybrid-mesh path
  (EngineConfig.num_slices) covers the single-controller multi-slice
  case where one process owns several ICI domains.
- A model too large for one host's chips (tp spanning hosts) requires
  lock-step scheduling: every host runs the same engine loop on the
  same request stream (rank 0 broadcasts admissions via the mesh, as in
  the multiproc train test). Supported by the sharded step functions;
  the scheduler-broadcast harness is deliberately not built until a
  target deployment needs it — the reference has no analog.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import AXIS_NAMES, MeshConfig


def _runtime_initialized() -> bool:
    """Is jax.distributed already up? `jax.distributed.is_initialized`
    only exists on newer JAX; older releases (e.g. the 0.4.37 this image
    ships) expose the same fact via the distributed global state's
    client handle. Either probe failing closed (False) is safe: the
    caller's `initialize` raises a clear error on double-init."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        # jax-internal layout moved (no public probe exists on this
        # version): treat as not-initialized — the only consequence is
        # that initialize() runs and raises its own clear double-init
        # error, which is strictly more informative than failing here.
        return False


def initialize_from_env(logger=None) -> bool:
    """Bring up the multi-process runtime if configured; returns True when
    jax.distributed was initialized (idempotent; safe single-host no-op)."""
    coordinator = os.environ.get("POLYKEY_COORDINATOR")
    num_procs = os.environ.get("POLYKEY_NUM_PROCESSES")
    proc_id = os.environ.get("POLYKEY_PROCESS_ID")

    if coordinator is None and num_procs is None and proc_id is None:
        # No explicit config: only auto-initialize under a real multi-host
        # TPU runtime (where JAX can discover peers); never on CPU/dev.
        if os.environ.get("TPU_WORKER_HOSTNAMES", "").count(",") == 0:
            return False
        try:
            jax.distributed.initialize()
            return True
        except Exception as e:  # already initialized or no runtime support
            if logger is not None:
                logger.warn("jax.distributed auto-init skipped", error=str(e))
            return False

    # ANY of the three set = explicit config (ADVICE r4: a lone
    # POLYKEY_PROCESS_ID used to fall through the auto branch silently).
    # All three must be present, non-empty, and the counts int-parseable —
    # otherwise jax.distributed.initialize dies with an opaque error.
    def _int_ok(v):
        try:
            int(v)
            return True
        except (TypeError, ValueError):
            return False

    if not (coordinator and num_procs and proc_id
            and _int_ok(num_procs) and _int_ok(proc_id)):
        raise ValueError(
            "partial distributed config: POLYKEY_COORDINATOR, "
            "POLYKEY_NUM_PROCESSES and POLYKEY_PROCESS_ID must be set "
            "together, non-empty, with integer counts "
            f"(coordinator={coordinator!r}, "
            f"num_processes={num_procs!r}, process_id={proc_id!r})"
        )
    if _runtime_initialized():
        # Keep the documented idempotency on the explicit path too (ADVICE
        # r4: a second _default_service build in one process would crash).
        return True
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=int(num_procs),
        process_id=int(proc_id),
    )
    if logger is not None:
        logger.info(
            "distributed runtime initialized",
            coordinator=coordinator,
            process_id=jax.process_index(),
            num_processes=jax.process_count(),
            global_devices=jax.device_count(),
        )
    return True


def create_hybrid_mesh(
    config: MeshConfig,
    num_slices: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh spanning `num_slices` ICI domains connected by DCN.

    The dp axis becomes (num_slices × config.dp) with the slice dimension
    outermost, so data-parallel gradient reduction is the only collective
    crossing DCN; pp/sp/ep/tp keep their full extent inside each slice.
    Axis names are unchanged — training/serving code is layout-agnostic.

    On real multi-slice TPU hardware, `mesh_utils.create_hybrid_device_mesh`
    assigns devices slice-by-slice; elsewhere (CPU simulation, subsets) the
    devices are split into equal contiguous groups, which preserves the
    axis semantics for tests.
    """
    if devices is None:
        devices = jax.devices()
    per_slice = config.num_devices
    if per_slice * num_slices != len(devices):
        raise ValueError(
            f"hybrid mesh needs {per_slice} × {num_slices} devices, "
            f"have {len(devices)}"
        )

    if num_slices == 1:
        from .mesh import create_mesh

        return create_mesh(config, devices)

    try:
        from jax.experimental import mesh_utils

        dcn_shape = (num_slices,) + (1,) * (len(AXIS_NAMES) - 1)
        device_array = mesh_utils.create_hybrid_device_mesh(
            config.shape, dcn_shape, devices=np.asarray(devices)
        )
    except Exception:
        # CPU simulation / device subsets: contiguous per-slice groups.
        device_array = np.asarray(devices).reshape(
            (num_slices,) + config.shape
        )
        device_array = device_array.reshape(
            (num_slices * config.dp,) + config.shape[1:]
        )
    return Mesh(device_array, AXIS_NAMES)


def mesh_from_env(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Mesh from POLYKEY_{DP,PP,SP,EP,TP,NUM_SLICES} (defaults: everything 1
    except dp, which absorbs the remaining devices)."""
    if devices is None:
        devices = jax.devices()
    axes = {
        name: int(os.environ.get(f"POLYKEY_{name.upper()}", "0") or 0)
        for name in AXIS_NAMES
    }
    # polylint: disable=ML004(mesh bootstrap runs before any EngineConfig exists; from_env later reads the same env)
    num_slices = int(os.environ.get("POLYKEY_NUM_SLICES", "1") or 1)
    known = 1
    for v in axes.values():
        known *= max(v, 1)
    if axes["dp"] == 0:
        axes["dp"] = len(devices) // (known * num_slices)
    config = MeshConfig(**{k: max(v, 1) for k, v in axes.items()})
    return create_hybrid_mesh(config, num_slices, devices)
