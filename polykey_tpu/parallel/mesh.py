"""Device mesh construction: dp × pp × sp × ep × tp axes.

Axis meanings (sizes of 1 leave an axis declared but unused — specs stay
uniform across configurations):

- ``dp`` — data parallel: batch (decode slots / train batch) sharding.
- ``pp`` — pipeline parallel: the stacked layer axis of the parameter pytree
  is sharded over it (inter-stage memory distribution; layers stream through
  `lax.scan`).
- ``sp`` — sequence/context parallel: long-context activation sharding
  (ring attention rotates KV blocks along this axis — ops/ring_attention.py).
- ``ep`` — expert parallel: MoE expert axis (Mixtral), token dispatch rides
  all-to-all over this axis.
- ``tp`` — tensor parallel: Megatron-style head/hidden sharding. Kept as the
  *last* (fastest-varying) axis so TP collectives land on adjacent-device ICI
  links; under multi-host, the leading axes map to DCN.

`jax.distributed.initialize` (multi-host) composes transparently: the same
axis declaration spans all hosts' devices.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_NAMES = ("dp", "pp", "sp", "ep", "tp")


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    tp: int = 1

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.dp, self.pp, self.sp, self.ep, self.tp)

    @property
    def num_devices(self) -> int:
        return math.prod(self.shape)


def create_mesh(
    config: MeshConfig = MeshConfig(),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if config.num_devices != len(devices):
        raise ValueError(
            f"mesh {dict(zip(AXIS_NAMES, config.shape))} needs "
            f"{config.num_devices} devices, have {len(devices)}"
        )
    if len(devices) == jax.device_count() and devices[0].platform == "tpu":
        # Topology-aware assignment: keeps tp (innermost) on adjacent chips.
        device_array = mesh_utils.create_device_mesh(
            config.shape, devices=np.asarray(devices)
        )
    else:
        device_array = np.asarray(devices).reshape(config.shape)
    return Mesh(device_array, AXIS_NAMES)


def single_device_mesh() -> Mesh:
    """1-device mesh: all axes size 1 — specs apply, no communication."""
    return create_mesh(MeshConfig(), devices=jax.devices()[:1])
