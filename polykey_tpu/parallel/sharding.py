"""Partition specs: how parameters, caches, and activations shard on the mesh.

Megatron-style tensor parallelism for the transformer block: column-parallel
first matmuls (wq/wk/wv, gate/up shard their *output* features over ``tp``),
row-parallel second matmuls (wo, down shard their *input* features), so the
only cross-device traffic per block is the reduce of the row-parallel output
— which XLA's SPMD partitioner emits as reduce-scatter/all-gather pairs over
the ICI ``tp`` axis on its own; no hand-written collectives.

Other axes: the stacked layer dim shards over ``pp``; MoE expert dims over
``ep``; the KV page pool shards its head dim over ``tp``; the decode batch
shards over ``dp``.

GQA constraint: num_kv_heads must divide by tp (Llama-3-8B: 8 kv heads →
tp ∈ {1,2,4,8}).
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig

# Leaf-path (within a layer) → PartitionSpec *without* the leading stacked
# layer axis (added uniformly below as the pp dimension).
_LAYER_RULES: dict[tuple[str, ...], P] = {
    ("attn", "wq"): P(None, "tp"),
    ("attn", "wk"): P(None, "tp"),
    ("attn", "wv"): P(None, "tp"),
    ("attn", "wo"): P("tp", None),
    ("mlp", "gate"): P(None, "tp"),
    ("mlp", "up"): P(None, "tp"),
    ("mlp", "down"): P("tp", None),
    ("router",): P(None, None),
    ("experts", "gate"): P("ep", None, "tp"),
    ("experts", "up"): P("ep", None, "tp"),
    ("experts", "down"): P("ep", "tp", None),
    ("ln1",): P(None),
    ("ln2",): P(None),
    ("post_ln1",): P(None),
    ("post_ln2",): P(None),
}

_TOP_RULES: dict[tuple[str, ...], P] = {
    ("embed",): P("tp", None),     # vocab-sharded; lookup gathers over tp
    ("final_norm",): P(None),
    ("lm_head",): P(None, "tp"),   # logits shard over vocab on tp
}


def _spec_for_path(
    path: tuple[str, ...], leaf=None, mesh: Optional[Mesh] = None
) -> P:
    # Quantized leaves (models/quant.py QuantizedTensor): `q` keeps the
    # weight's spec. int8 `s` is the weight shape minus the contraction
    # (-2) axis, so its spec is the weight spec with that axis dropped
    # (e.g. wq [L, H, out] P("pp", None, "tp") → s [L, out] P("pp", "tp")).
    # int4 `s` is group-wise [..., in/g, out] — SAME rank as q with the
    # group axis in the contraction position, so a tp-sharded contraction
    # axis shards the groups the same way WHEN the group count divides;
    # otherwise (tiny models: one group) the group axis replicates and
    # GSPMD re-shards at the dequant reshape. Discriminated by rank.
    if path and path[-1] in ("q", "s"):
        base = _spec_for_path(path[:-1])
        if path[-1] == "q":
            return base
        ndim = getattr(leaf, "ndim", -1)
        if ndim == len(base):                # group-wise (int4)
            contr = base[-2]
            if contr is not None and mesh is not None:
                axes = contr if isinstance(contr, tuple) else (contr,)
                size = 1
                for a in axes:
                    size *= mesh.shape[a]
                if leaf.shape[-2] % size != 0:
                    return P(*base[:-2], None, base[-1])
            return base
        return P(*base[:-2], base[-1]) if len(base) >= 2 else base
    if path in _TOP_RULES:
        return _TOP_RULES[path]
    if path and path[0] == "layers":
        layer_path = path[1:]
        if layer_path in _LAYER_RULES:
            inner = _LAYER_RULES[layer_path]
            return P("pp", *inner)  # leading stacked-layer axis → pp
    raise KeyError(f"no sharding rule for param path {path}")


def _path_keys(path) -> tuple[str, ...]:
    keys = []
    for entry in path:
        if isinstance(entry, jax.tree_util.DictKey):
            keys.append(str(entry.key))
        elif isinstance(entry, jax.tree_util.GetAttrKey):
            keys.append(entry.name)  # QuantizedTensor fields: 'q' / 's'
        else:
            keys.append(str(entry))
    return tuple(keys)


def param_shardings(cfg: ModelConfig, mesh: Mesh, params_tree=None):
    """NamedSharding pytree matching init_params' structure."""
    if params_tree is None:
        from ..models.transformer import init_params

        params_tree = jax.eval_shape(
            lambda: init_params(jax.random.PRNGKey(0), cfg)
        )
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(
            mesh, _spec_for_path(_path_keys(path), leaf, mesh)
        ),
        params_tree,
    )


def shard_params(params: dict, cfg: ModelConfig, mesh: Mesh) -> dict:
    """Place a param pytree onto the mesh under the TP/PP/EP specs."""
    return jax.device_put(params, param_shardings(cfg, mesh, params))


def paged_kv_sharding(mesh: Mesh) -> NamedSharding:
    """Page pools [L, N, page_size, Hk, D]: heads shard over tp.

    Pages are *not* dp-sharded: any decode slot may hold any page, so the
    pool replicates over dp (each dp replica serves its own slot subset with
    its own pool in the dp>1 serving layout).
    """
    return NamedSharding(mesh, P("pp", None, None, "tp", None))


def paged_kv_scale_sharding(mesh: Mesh) -> NamedSharding:
    """int8-KV scale pools [L, N, page_size, Hk]: same placement as the
    data pools (paged_kv_sharding) with the head axis LAST — kept beside
    it so the two specs cannot drift apart."""
    return NamedSharding(mesh, P("pp", None, None, "tp"))


def contiguous_kv_sharding(mesh: Mesh) -> NamedSharding:
    """Contiguous cache [L, B, S, Hk, D]: batch over dp, heads over tp."""
    return NamedSharding(mesh, P("pp", "dp", None, "tp", None))


def batch_sharding(mesh: Mesh, ndim: int, seq_axis: Optional[int] = None):
    """Token batches [B, T, ...]: batch over dp, optionally T over sp."""
    spec = ["dp"] + [None] * (ndim - 1)
    if seq_axis is not None:
        spec[seq_axis] = "sp"
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
