"""Version-compat shims over the installed JAX.

The codebase targets current JAX spellings (``jax.shard_map``,
pallas-TPU ``CompilerParams``); some images pin older releases (this
container ships 0.4.37) where the identical functionality lives under
legacy names (``jax.experimental.shard_map.shard_map`` with
``check_rep``/``auto``, ``pltpu.TPUCompilerParams``). Each shim prefers
the modern API and degrades to the legacy one, so the code reads
current while running on both — the "stub or gate missing deps"
discipline, applied to API renames.

Kept deliberately tiny and argument-explicit: a shim that forwards
**kwargs blindly would hide real signature drift until runtime on the
OTHER jax version.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
              axis_names=None):
    """``jax.shard_map`` when available, else the legacy
    ``jax.experimental.shard_map.shard_map``.

    Maps the modern kwargs onto the legacy ones: ``check_vma`` was
    named ``check_rep``; partial-manual mode was expressed as ``auto``
    (the complement set — axes NOT manually mapped) instead of
    ``axis_names`` (the axes that ARE)."""
    if hasattr(jax, "shard_map"):
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(f, **kw)
    from jax.experimental.shard_map import shard_map as _legacy

    kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_rep=check_vma)
    if axis_names is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _legacy(f, **kw)


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` (current) or ``pltpu.TPUCompilerParams``
    (legacy) — same fields either way."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)
