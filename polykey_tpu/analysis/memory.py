"""memlint — the fourth analysis tier: memory & capacity contracts.

Every next roadmap direction is a bytes problem (adapter slabs paged
like KV, page-level compression targets, resident-floor autopilot), yet
the repo's capacity statements were prose computed ad hoc. This tier
turns them into contracts a CI gate re-derives, in the ``ML`` namespace
alongside PL (polylint), GL (graphlint) and CL (racelint), with the
same committed-empty baseline (``memlint-baseline.json``) and the same
line-suppression syntax (``# polylint: disable=ML002(reason)``).

Three rule families, stdlib-only (the ledger is analytic — it mirrors
the allocator arithmetic in ``kv_cache.init_paged_kv`` via the pure
helpers in ``engine/roofline.py``, and tests pin the mirror byte-for-
byte against the jax-backed allocator):

``ML001`` capacity contracts
    An analytic byte ledger per served engine config: resident weights
    (``roofline.weight_resident_bytes``), the preallocated device KV
    pool and its int8 scale planes (``roofline.kv_pool_bytes_split``),
    the draft model's pool under speculation, plus first-order peak
    transients for every warmed jit executable (prefill at the largest
    bucket, decode/ragged at full slots, spec at gamma+1 positions,
    gather/restore staging at one full sequence of pages). Donation
    credits come from the same alias map GL002 audits: executables that
    donate ``paged`` reuse the pool in place, so the ledger counts it
    once (and records the credit — if donation breaks, GL002 fails
    before this ledger lies). The contract: per-chip resident + largest
    transient must fit ``ChipSpec.hbm_bytes`` for every entry of the
    served matrix, and every matrix entry must pass
    ``EngineConfig.validate()`` — a validate()-accepted config that
    cannot fit is a finding, not a surprise OOM at warmup.

``ML002`` unbounded growth
    Module/class containers that long-lived objects grow without a cap,
    ring, LRU, or amortized-gc discipline. A class counts as long-lived
    when it holds a threading primitive or runs a ``while True`` loop
    (serve-path objects); module-level containers are process-lived by
    definition. Discipline is any shrink path on the same container
    (pop/popitem/clear/del/discard/popleft, reassignment outside
    __init__, a ``len(...)`` cap check, or ``deque(maxlen=...)`` at
    construction). Deliberate survivors (the flight-deck rings, sticky
    maps, EWMA state, witness edge sets) carry ML002 annotations with
    reasons.

``ML003``/``ML004``/``ML005`` knob contracts
    Every ``POLYKEY_*`` env read must appear as a row in DEPLOY.md's
    knob tables or be declared internal-only here (ML003); a knob that
    ``EngineConfig.from_env`` owns must not be re-parsed ad hoc
    elsewhere in the package (ML004 — default drift); and every knob
    ``from_env`` reads must ship to disagg workers via ``_config_env``
    or carry a coordinator-only exemption with a reason (ML005 — the
    PR 15 "knob not shipped to workers" bug class, made structural).

``ML006`` observed growth (``--witness``)
    Merges runtime heap-witness series (analysis/heapwitness.py,
    ``POLYKEY_HEAP_WITNESS=1``) into the static findings: sustained
    tracemalloc growth after warmup, or a pool observed above its
    declared capacity, is a finding carrying real evidence. The hostkv
    and disagg smokes run under the witness and gate on zero.

``ML000`` is the meta rule (suppression hygiene, unparseable inputs,
stale matrix entries); like PL000/GL000/CL000 it refuses --prune and
--write-baseline while present.
"""

from __future__ import annotations

import argparse
import ast
import json
import math
import sys
from dataclasses import replace as dc_replace
from pathlib import Path
from typing import Iterable, Iterator, Optional

from .baseline import (
    apply_baseline,
    load_baseline,
    prune_baseline,
    write_baseline,
)
from .core import (
    DEFAULT_TARGETS,
    FileContext,
    Finding,
    Rule,
    UsageError,
    iter_py_files,
    load_witness_arg,
    parse_only,
    require_full_run,
)

MEM_BASELINE = "memlint-baseline.json"

# Repo root of the PACKAGE (ledger anchors name this repo's files; the
# scanned --root may be elsewhere, but the capacity contract is about
# the code that is actually imported).
_PKG_ROOT = Path(__file__).resolve().parents[2]

# ---------------------------------------------------------------------------
# ML001: the served-model capacity matrix.
#
# One entry per BASELINE.md measurement config that reaches a TPU:
# single-chip 8B in both quantization widths (config 2), the TP=4 bf16
# variant (config 3), expert-parallel Mixtral (config 4), and Gemma-2
# with its speculative draft (config 5). Geometry not listed here is
# the EngineConfig default (2048 pages x 16 tokens, 16 decode slots).
# ---------------------------------------------------------------------------

SERVED_MATRIX: tuple[dict, ...] = (
    {"name": "llama3-8b-int8", "model": "llama-3-8b", "dtype": "bfloat16",
     "quantize": True, "quantize_bits": 8, "kv_dtype": "int8",
     "chip": "tpu-v5e", "n_chips": 1},
    {"name": "llama3-8b-int4", "model": "llama-3-8b", "dtype": "bfloat16",
     "quantize": True, "quantize_bits": 4, "kv_dtype": "int8",
     "chip": "tpu-v5e", "n_chips": 1},
    {"name": "llama3-8b-bf16-tp4", "model": "llama-3-8b",
     "dtype": "bfloat16", "quantize": False, "quantize_bits": 8,
     "kv_dtype": "", "chip": "tpu-v5e", "n_chips": 4, "mesh": {"tp": 4}},
    {"name": "mixtral-8x7b-int8-ep4", "model": "mixtral-8x7b",
     "dtype": "bfloat16", "quantize": True, "quantize_bits": 8,
     "kv_dtype": "int8", "chip": "tpu-v5e", "n_chips": 4,
     "mesh": {"ep": 4}},
    {"name": "gemma2-27b-int8-spec-tp4", "model": "gemma-2-27b",
     "dtype": "bfloat16", "quantize": True, "quantize_bits": 8,
     "kv_dtype": "int8", "chip": "tpu-v5e", "n_chips": 4,
     "mesh": {"tp": 4}, "draft_model": "gemma-2-2b"},
)

# Executables that donate their KV pool operand (mirrors engine.py's
# donate_argnames, which GL002 audits against the compiled alias map).
# The ledger counts a donated pool once: in+out alias in place.
DONATED_EXECUTABLES = {
    "prefill": ("paged",),
    "decode": ("paged", "last_tokens", "seq_lens", "active"),
    "ragged": ("paged",),
    "spec_prefill": ("t_paged", "d_paged"),
    "spec_decode": ("t_paged", "d_paged"),
    "kv_restore": ("paged",),
}

# ---------------------------------------------------------------------------
# ML003: knobs that are deliberately NOT operator surface. Each entry is
# an explicit internal-only annotation — the documented alternative to a
# DEPLOY.md row. A knob must appear in exactly one place.
# ---------------------------------------------------------------------------

INTERNAL_KNOB_PREFIXES: dict[str, str] = {
    # bench.py's phase harness: workload shaping for one-off measurement
    # runs (request counts, prompt lengths, sweep axes). Not serving
    # configuration; documented inline in bench.py's phase docstrings.
    "POLYKEY_BENCH_": "bench.py harness workload knobs (PERF.md runbook)",
}

INTERNAL_KNOBS: dict[str, str] = {
    # dev/test escape hatches and harness-local switches; each is
    # documented at its read site.
    "POLYKEY_PROFILE_N": "bench profiler sample count (bench.py only)",
    "POLYKEY_PROFILE_QUANT":
        "bench profiler quantization override (bench.py only)",
    "POLYKEY_PROFILE_KV": "bench profiler KV override (bench.py only)",
    "POLYKEY_LOOP_TRACE":
        "engine-loop trace dump for dispatch debugging (tests/bench)",
    "POLYKEY_FAULTS":
        "chaos fault-injection spec (faults.py); test/soak harness "
        "surface, never an operator knob",
    "POLYKEY_LOOKAHEAD":
        "legacy alias for POLYKEY_DISPATCH_LOOKAHEAD, which holds the "
        "DEPLOY.md row",
}

# ---------------------------------------------------------------------------
# ML005: from_env knobs that legitimately never ship to disagg workers.
# Reasons are part of the contract — an exemption without a mechanism
# ("validate() rejects it" / "coordinator consumes it") would just be
# the PR 15 bug with paperwork.
# ---------------------------------------------------------------------------

WORKER_ENV_EXEMPT: dict[str, str] = {
    "POLYKEY_LOOKAHEAD":
        "legacy alias; the canonical POLYKEY_DISPATCH_LOOKAHEAD ships",
    "POLYKEY_DRAFT_MODEL":
        "validate() rejects draft models under disagg (spec decode is "
        "single-engine); a worker can never need it",
    "POLYKEY_DRAFT_CHECKPOINT": "rides POLYKEY_DRAFT_MODEL (see above)",
    "POLYKEY_SPEC_GAMMA": "rides POLYKEY_DRAFT_MODEL (see above)",
    "POLYKEY_ADAPTIVE_GAMMA": "rides POLYKEY_DRAFT_MODEL (see above)",
    "POLYKEY_ROUTE_W_PREFIX":
        "replica-pool routing weight; the coordinator routes, workers "
        "only serve what arrives",
    "POLYKEY_ROUTE_W_DELAY": "coordinator routing weight (see above)",
    "POLYKEY_MAX_REROUTES": "coordinator routing policy (see above)",
    "POLYKEY_DISAGG":
        "the spawn pins POLYKEY_DISAGG=\"\" on workers (no recursive "
        "pools); shipping the parent's value would fork-bomb",
    "POLYKEY_REPLICAS":
        "the spawn pins POLYKEY_REPLICAS=1 on workers (see above)",
    "POLYKEY_DISAGG_HEARTBEAT":
        "coordinator liveness policy; workers answer heartbeats, they "
        "do not time them",
    "POLYKEY_DISAGG_MISS": "coordinator liveness policy (see above)",
    "POLYKEY_DISAGG_RECOVERY_WAIT":
        "coordinator liveness policy (see above)",
}

# ML006 thresholds: growth below the floor OR below the fraction of the
# post-warmup base is noise (allocator jitter, late caches); both must
# be exceeded AND the growth must be sustained (still rising in the
# final half) to flag.
WITNESS_GROWTH_FLOOR_BYTES = 16 << 20
WITNESS_GROWTH_FRACTION = 0.20
WITNESS_MIN_CHECKPOINTS = 6


# ---------------------------------------------------------------------------
# The analytic byte ledger
# ---------------------------------------------------------------------------


def _engine_config(entry: dict):
    """Materialize a SERVED_MATRIX entry as an EngineConfig (defaults +
    the entry's model/precision/mesh overrides)."""
    from ..engine.config import EngineConfig

    mesh = entry.get("mesh", {})
    return dc_replace(
        EngineConfig(),
        model=entry["model"],
        dtype=entry["dtype"],
        quantize=entry["quantize"],
        quantize_bits=entry["quantize_bits"],
        kv_dtype=entry["kv_dtype"],
        draft_model=entry.get("draft_model"),
        tp=mesh.get("tp", 1),
        dp=mesh.get("dp", 1),
        ep=mesh.get("ep", 1),
        sp=mesh.get("sp", 1),
        pp=mesh.get("pp", 1),
    )


def build_ledger(cfg, chip_name: str, n_chips: int,
                 chip_specs: Optional[dict] = None) -> dict:
    """Analytic resident + peak-transient bytes for one engine config.

    All arithmetic is stdlib: weights via roofline's geometry model,
    pools via the pure mirror of kv_cache.init_paged_kv (a test pins
    the mirror against the allocator), transients first-order — the
    activation stream (4H + 2I per token), fp32 logits rows, and the
    paged staging of one full sequence for gather/restore. That is the
    same fidelity stance roofline.py documents: good enough to tell "it
    fits with 40% headroom" from "warmup OOMs", which is the contract.
    """
    from ..engine import roofline
    from ..models.config import get_config

    specs = chip_specs if chip_specs is not None else roofline.CHIP_SPECS
    chip = specs[chip_name]
    mcfg = get_config(cfg.model)
    kv_dt = cfg.kv_dtype or cfg.dtype
    act = 2.0 if cfg.dtype == "bfloat16" else 4.0

    weights = roofline.weight_resident_bytes(
        mcfg, cfg.dtype, cfg.quantize, cfg.quantize_bits)
    kv_values, kv_scales = roofline.kv_pool_bytes_split(
        mcfg, cfg.num_pages, cfg.page_size, kv_dt)

    draft_weights = draft_kv = 0.0
    dcfg = None
    if cfg.draft_model:
        dcfg = get_config(cfg.draft_model)
        weights_d = roofline.weight_resident_bytes(
            dcfg, cfg.dtype, cfg.quantize, cfg.quantize_bits)
        draft_weights = weights_d
        draft_kv = roofline.kv_pool_bytes_spec(
            dcfg, cfg.num_pages, cfg.page_size, kv_dt)

    def stream(tokens: float, m) -> float:
        # Residual stream + attention projections (~4H) and the gated
        # MLP pair (~2I) per token — the dominant live activations.
        return tokens * (4.0 * m.hidden_size
                         + 2.0 * m.intermediate_size) * act

    max_bucket = float(max(cfg.prefill_buckets))
    slots = float(cfg.max_decode_slots)
    vocab = float(mcfg.vocab_size)
    # fp32 logits: one row for prefill's final position, one per lane
    # for decode.
    transients = {
        "prefill": stream(max_bucket, mcfg) + vocab * 4.0,
        "decode": stream(slots, mcfg) + slots * vocab * 4.0,
        "ragged": stream(max_bucket + slots, mcfg) + slots * vocab * 4.0,
    }
    if dcfg is not None:
        spec_tokens = slots * (cfg.spec_gamma + 1.0)
        transients["spec_decode"] = (
            stream(spec_tokens, mcfg) + stream(spec_tokens, dcfg)
            + spec_tokens * vocab * 4.0)
    # Gather/restore staging: the KV pages of one full sequence cross as
    # a dense operand (handoff upload, host-tier restore scatter).
    seq_pages = math.ceil(cfg.max_seq_len / cfg.page_size)
    page_bytes = roofline.kv_pool_bytes_spec(mcfg, 1, cfg.page_size, kv_dt)
    transients["kv_gather"] = float(seq_pages) * page_bytes
    if cfg.host_kv_bytes > 0:
        transients["kv_restore"] = float(seq_pages) * page_bytes

    resident = weights + kv_values + kv_scales + draft_weights + draft_kv
    peak_transient = max(transients.values())
    per_chip = resident / n_chips + peak_transient
    # Donation credit: every pool-touching executable donates its pool
    # (DONATED_EXECUTABLES, audited by GL002), so no executable ever
    # holds an undonated output copy of the pool. The credit is what
    # the peak would grow by if that contract broke.
    donation_credit = kv_values + kv_scales + draft_kv

    host = {}
    if cfg.host_kv_bytes > 0:
        host_page = roofline.kv_pool_bytes_spec(
            mcfg, 1, cfg.page_size, kv_dt)
        host = {
            "host_kv_bytes": float(cfg.host_kv_bytes),
            "host_kv_page_bytes": host_page,
            "host_capacity_pages": int(cfg.host_kv_bytes // host_page),
        }

    return {
        "model": cfg.model,
        "chip": chip_name,
        "n_chips": n_chips,
        "weights_bytes": weights,
        "draft_weights_bytes": draft_weights,
        "kv_pool_bytes": kv_values,
        "kv_scale_pool_bytes": kv_scales,
        "draft_kv_pool_bytes": draft_kv,
        "transient_bytes": transients,
        "peak_transient_bytes": peak_transient,
        "donation_credit_bytes": donation_credit,
        "resident_bytes": resident,
        "per_chip_bytes": per_chip,
        "hbm_bytes_per_chip": float(chip.hbm_bytes),
        "hbm_fraction": per_chip / chip.hbm_bytes,
        "fits": per_chip <= chip.hbm_bytes,
        **host,
    }


def _anchor(rel: str, needle: str) -> tuple[str, int]:
    """(rel, line) of the first source line containing `needle` in a
    package file — capacity findings anchor where the violated number
    is declared, so the baseline fingerprint tracks the declaration."""
    try:
        text = (_PKG_ROOT / rel).read_text(encoding="utf-8")
        for i, line in enumerate(text.splitlines(), 1):
            if needle in line:
                return rel, i
    except OSError:
        pass
    return rel, 1


def check_capacity(matrix: Optional[Iterable[dict]] = None,
                   chip_specs: Optional[dict] = None,
                   ) -> tuple[list[Finding], list[dict]]:
    """ML001: every served matrix entry must validate() AND fit the
    ledger into its chip's HBM. Returns (findings, ledger entries)."""
    findings: list[Finding] = []
    ledgers: list[dict] = []
    roofline_rel = "polykey_tpu/engine/roofline.py"
    config_rel = "polykey_tpu/engine/config.py"
    for entry in (matrix if matrix is not None else SERVED_MATRIX):
        try:
            cfg = _engine_config(entry)
            cfg.validate()
        except Exception as e:
            rel, line = _anchor(config_rel, "def validate")
            findings.append(Finding(
                rule="ML000", path=rel, line=line,
                message=f"served-matrix entry {entry['name']!r} no longer "
                        f"passes EngineConfig.validate(): {e} — the "
                        "capacity matrix is stale",
                snippet=entry["name"]))
            continue
        ledger = build_ledger(cfg, entry["chip"], entry["n_chips"],
                              chip_specs=chip_specs)
        ledger["name"] = entry["name"]
        ledgers.append(ledger)
        if not ledger["fits"]:
            rel, line = _anchor(roofline_rel, f'"{entry["chip"]}"')
            gib = 1 << 30
            findings.append(Finding(
                rule="ML001", path=rel, line=line,
                message=f"capacity contract violated for "
                        f"{entry['name']}: weights "
                        f"{ledger['weights_bytes'] / gib:.2f} GiB + KV "
                        f"pool {(ledger['kv_pool_bytes'] + ledger['kv_scale_pool_bytes']) / gib:.2f} GiB "
                        f"+ peak transient "
                        f"{ledger['peak_transient_bytes'] / gib:.2f} GiB = "
                        f"{ledger['per_chip_bytes'] / gib:.2f} GiB/chip > "
                        f"{ledger['hbm_bytes_per_chip'] / gib:.0f} GiB "
                        f"{entry['chip']} HBM (x{entry['n_chips']} chips) "
                        "— a validate()-accepted config that OOMs at "
                        "warmup",
                snippet=entry["name"]))
    return findings, ledgers


# ---------------------------------------------------------------------------
# ML002: unbounded-growth AST rule
# ---------------------------------------------------------------------------

_GROW_METHODS = {"append", "appendleft", "add", "insert", "extend",
                 "setdefault", "update"}
_SHRINK_METHODS = {"pop", "popitem", "popleft", "clear", "remove",
                   "discard"}
_CONTAINER_FACTORIES = {"dict", "list", "set", "OrderedDict",
                        "defaultdict", "Counter"}
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore", "allocate_lock"}


def _call_name(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _empty_container_kind(node: ast.AST) -> Optional[str]:
    """Container-typed initializer with no bound: {} / [] / set() /
    dict() / list() / OrderedDict() / defaultdict(...) / Counter() /
    deque(...) WITHOUT maxlen. Returns the kind name or None."""
    if isinstance(node, ast.Dict) and not node.keys:
        return "dict"
    if isinstance(node, ast.List) and not node.elts:
        return "list"
    if isinstance(node, ast.Call):
        name = _call_name(node.func)
        if name == "deque":
            if any(kw.arg == "maxlen" for kw in node.keywords):
                return None
            return "deque"
        if name in _CONTAINER_FACTORIES and not node.args:
            return name
        if name == "defaultdict":
            return name
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for `self.x`, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _ClassScan:
    def __init__(self) -> None:
        self.containers: dict[str, tuple[str, int]] = {}  # attr -> kind, line
        self.growth: dict[str, tuple[int, str]] = {}      # attr -> line, method
        self.disciplined: set[str] = set()
        self.long_lived = False


def _scan_class(cls: ast.ClassDef) -> _ClassScan:
    scan = _ClassScan()
    if any(_call_name(b) == "Thread" for b in cls.bases):
        scan.long_lived = True
    for method in cls.body:
        if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        is_init = method.name == "__init__"
        for node in ast.walk(method):
            if isinstance(node, ast.While):
                test = node.test
                if isinstance(test, ast.Constant) and test.value is True:
                    scan.long_lived = True
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                value = node.value
                for tgt in targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    if value is not None and isinstance(value, ast.Call) \
                            and _call_name(value.func) in _LOCK_FACTORIES:
                        scan.long_lived = True
                    if is_init:
                        if value is not None:
                            kind = _empty_container_kind(value)
                            if kind is not None:
                                scan.containers.setdefault(
                                    attr, (kind, node.lineno))
                    else:
                        # Reassignment outside __init__ is a reset /
                        # truncation path: discipline.
                        scan.disciplined.add(attr)
            if isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Attribute):
                    attr = _self_attr(func.value)
                    if attr is not None:
                        if func.attr in _GROW_METHODS and not is_init:
                            scan.growth.setdefault(
                                attr, (node.lineno, method.name))
                        elif func.attr in _SHRINK_METHODS:
                            scan.disciplined.add(attr)
                if isinstance(func, ast.Name) and func.id == "len" \
                        and node.args:
                    attr = _self_attr(node.args[0])
                    if attr is not None:
                        # A len() check anywhere in the class is a cap /
                        # amortized-gc signal.
                        scan.disciplined.add(attr)
            if isinstance(node, ast.Delete):
                for tgt in node.targets:
                    base = tgt.value if isinstance(tgt, ast.Subscript) \
                        else tgt
                    attr = _self_attr(base)
                    if attr is not None:
                        scan.disciplined.add(attr)
            if isinstance(node, ast.Assign) and not is_init:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript):
                        attr = _self_attr(tgt.value)
                        if attr is not None and attr in scan.containers:
                            scan.growth.setdefault(
                                attr, (node.lineno, method.name))
    return scan


class GrowthRule(Rule):
    id = "ML002"
    name = "unbounded-growth"
    description = ("long-lived container grows without a cap, ring, LRU, "
                   "or amortized-gc discipline")

    def applies(self, rel: str) -> bool:
        # Serve-path packages only: harness scripts accumulate results
        # for the lifetime of one bounded run.
        return rel.startswith("polykey_tpu/")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # Module-level containers are process-lived by definition.
        module_containers: dict[str, tuple[str, int]] = {}
        module_disciplined: set[str] = set()
        module_growth: dict[str, tuple[int, str]] = {}
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                kind = _empty_container_kind(node.value)
                if kind is not None:
                    module_containers.setdefault(
                        node.targets[0].id, (kind, node.lineno))
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Attribute) \
                        and isinstance(func.value, ast.Name) \
                        and func.value.id in module_containers:
                    if func.attr in _GROW_METHODS:
                        module_growth.setdefault(
                            func.value.id, (node.lineno, func.attr))
                    elif func.attr in _SHRINK_METHODS:
                        module_disciplined.add(func.value.id)
                if isinstance(func, ast.Name) and func.id == "len" \
                        and node.args \
                        and isinstance(node.args[0], ast.Name) \
                        and node.args[0].id in module_containers:
                    module_disciplined.add(node.args[0].id)
            if isinstance(node, ast.Delete):
                for tgt in node.targets:
                    base = tgt.value if isinstance(tgt, ast.Subscript) \
                        else tgt
                    if isinstance(base, ast.Name) \
                            and base.id in module_containers:
                        module_disciplined.add(base.id)
            if isinstance(node, ast.Assign) \
                    and not isinstance(node, ast.AnnAssign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id in module_containers:
                        module_growth.setdefault(
                            tgt.value.id, (node.lineno, "[]="))
        for name, (line, how) in sorted(module_growth.items()):
            kind, decl = module_containers[name]
            if name in module_disciplined:
                continue
            if decl == line:
                continue
            yield ctx.finding(
                "ML002", line,
                f"module-level {kind} `{name}` (declared line {decl}) "
                f"grows via {how} with no shrink path — module state "
                "lives for the process; bound it or annotate "
                "ML002(reason)")

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            scan = _scan_class(node)
            if not scan.long_lived:
                continue
            for attr, (line, method) in sorted(scan.growth.items()):
                if attr not in scan.containers:
                    continue
                if attr in scan.disciplined:
                    continue
                kind, decl = scan.containers[attr]
                yield ctx.finding(
                    "ML002", line,
                    f"{node.name}.{attr} ({kind}, created line {decl}) "
                    f"grows in {method}() with no cap, ring, LRU, or "
                    "amortized-gc discipline — this class is long-lived "
                    "(lock/serve loop); bound it or annotate "
                    "ML002(reason)")


# ---------------------------------------------------------------------------
# Knob contracts (ML003/ML004/ML005)
# ---------------------------------------------------------------------------

_ENV_GET_ATTRS = {"get", "getenv", "pop"}
_ENV_HELPERS = {"_env_int", "_env_float", "_env_bool", "getenv"}


def _attr_chain(node: ast.AST) -> list[str]:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return parts


def _const_str(node: ast.AST, consts: dict[str, str]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def module_env_reads(tree: ast.AST) -> list[tuple[str, int, str]]:
    """Every POLYKEY_* env READ in a module: (knob, line, enclosing
    function name or '<module>'). Reads are .get/.getenv/.pop calls on
    an environ-like object, the config helpers (_env_int/_env_float/
    _env_bool), and environ[...] subscripts in Load context — dict
    literal keys and env[...] = assignments (the ship side) don't
    count. Module-level string constants resolve one level deep."""
    consts: dict[str, str] = {}
    for node in getattr(tree, "body", []):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            consts[node.targets[0].id] = node.value.value
    reads: list[tuple[str, int, str]] = []

    def visit(node: ast.AST, func: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = node.name
        if isinstance(node, ast.Call):
            knob = None
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _ENV_GET_ATTRS:
                chain = _attr_chain(f.value)
                if "environ" in chain or (
                        chain == ["os"] and f.attr == "getenv"):
                    knob = _const_str(node.args[0], consts) \
                        if node.args else None
            elif isinstance(f, ast.Name) and f.id in _ENV_HELPERS:
                knob = _const_str(node.args[0], consts) \
                    if node.args else None
            if knob and knob.startswith("POLYKEY_") \
                    and len(knob) > len("POLYKEY_"):
                reads.append((knob, node.lineno, func))
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load) \
                and "environ" in _attr_chain(node.value):
            knob = _const_str(node.slice, consts)
            if knob and knob.startswith("POLYKEY_") \
                    and len(knob) > len("POLYKEY_"):
                reads.append((knob, node.lineno, "<subscript>"))
        for child in ast.iter_child_nodes(node):
            visit(child, func)

    visit(tree, "<module>")
    return reads


def deploy_documented_knobs(deploy_text: str) -> set[str]:
    """Knob names with a row in a DEPLOY.md knob table: every
    backticked POLYKEY_* in the FIRST cell of a table line (one row may
    document a family, e.g. the mesh axes TP/DP/EP/SP/PP). Mentions in
    later cells (runbook prose) don't count as documentation."""
    import re

    documented: set[str] = set()
    for m in re.finditer(r"(?m)^\|\s*(`[^|]*)\|", deploy_text):
        documented.update(
            re.findall(r"`(POLYKEY_[A-Z0-9_]+)`", m.group(1)))
    return documented


def _knob_internal(knob: str) -> Optional[str]:
    if knob in INTERNAL_KNOBS:
        return INTERNAL_KNOBS[knob]
    for prefix, reason in INTERNAL_KNOB_PREFIXES.items():
        if knob.startswith(prefix):
            return reason
    return None


def check_knob_docs(env_reads: dict[str, list[tuple[str, int, str]]],
                    deploy_text: Optional[str],
                    ) -> list[Finding]:
    """ML003: every knob read anywhere must have a DEPLOY.md table row
    or an internal-only annotation (INTERNAL_KNOBS). One finding per
    knob, at its first read site."""
    findings: list[Finding] = []
    if deploy_text is None:
        rel, line = _anchor("polykey_tpu/analysis/memory.py",
                            "def check_knob_docs")
        return [Finding(
            rule="ML000", path=rel, line=line,
            message="DEPLOY.md is missing or unreadable — the knob-"
                    "documentation contract (ML003) cannot run")]
    documented = deploy_documented_knobs(deploy_text)
    first_site: dict[str, tuple[str, int]] = {}
    for rel in sorted(env_reads):
        for knob, line, _fn in env_reads[rel]:
            first_site.setdefault(knob, (rel, line))
    for knob in sorted(first_site):
        if knob in documented or _knob_internal(knob) is not None:
            continue
        rel, line = first_site[knob]
        findings.append(Finding(
            rule="ML003", path=rel, line=line,
            message=f"{knob} is read here but has no DEPLOY.md knob-"
                    "table row and no internal-only annotation "
                    "(analysis/memory.py INTERNAL_KNOBS) — an operator "
                    "cannot discover it",
            snippet=knob))
    return findings


CONFIG_REL = "polykey_tpu/engine/config.py"
DISAGG_REL = "polykey_tpu/engine/disagg_pool.py"


def check_knob_single_parse(
        env_reads: dict[str, list[tuple[str, int, str]]]) -> list[Finding]:
    """ML004: a knob EngineConfig.from_env owns must not be re-read ad
    hoc elsewhere in the package — two parse sites mean two defaults
    that drift apart. Harness scripts/bench are exempt (they *set* the
    env for the engine to read)."""
    owned = {knob for knob, _l, fn in env_reads.get(CONFIG_REL, ())}
    findings: list[Finding] = []
    for rel in sorted(env_reads):
        if rel == CONFIG_REL or not rel.startswith("polykey_tpu/"):
            continue
        seen: set[str] = set()
        for knob, line, _fn in env_reads[rel]:
            if knob in owned and knob not in seen:
                seen.add(knob)
                findings.append(Finding(
                    rule="ML004", path=rel, line=line,
                    message=f"{knob} already parses in "
                            "EngineConfig.from_env — a second ad-hoc "
                            "read risks default drift; route through "
                            "the config object (or annotate "
                            "ML004(reason))",
                    snippet=knob))
    return findings


def from_env_knobs(config_tree: ast.AST) -> set[str]:
    """Knobs EngineConfig.from_env reads (the engine-relevant set)."""
    for node in ast.walk(config_tree):
        if isinstance(node, ast.FunctionDef) and node.name == "from_env":
            return {knob for knob, _l, _f in module_env_reads(
                ast.Module(body=[node], type_ignores=[]))}
    return set()


def shipped_knobs(disagg_tree: ast.AST) -> set[str]:
    """Knobs _config_env renders (dict-literal keys) plus any
    env["POLYKEY_X"] = ... pins elsewhere in the module (the spawn's
    DISAGG/REPLICAS/METRICS_PORT overrides)."""
    shipped: set[str] = set()
    for node in ast.walk(disagg_tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "_config_env":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Dict):
                    for key in sub.keys:
                        if isinstance(key, ast.Constant) \
                                and isinstance(key.value, str) \
                                and key.value.startswith("POLYKEY_"):
                            shipped.add(key.value)
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) \
                        and isinstance(tgt.slice, ast.Constant) \
                        and isinstance(tgt.slice.value, str) \
                        and tgt.slice.value.startswith("POLYKEY_"):
                    shipped.add(tgt.slice.value)
    return shipped


def check_ship_contract(config_tree: ast.AST, disagg_tree: ast.AST,
                        disagg_rel: str = DISAGG_REL,
                        exempt: Optional[dict[str, str]] = None,
                        ) -> list[Finding]:
    """ML005: from_env ∖ (_config_env ∪ spawn pins ∪ exemptions) must be
    empty — a knob the engine parses but the disagg spawn doesn't ship
    silently reverts to its default inside every worker (the PR 15
    _config_env bug class)."""
    exempt_map = WORKER_ENV_EXEMPT if exempt is None else exempt
    env = from_env_knobs(config_tree)
    shipped = shipped_knobs(disagg_tree)
    def_line = 1
    for node in ast.walk(disagg_tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "_config_env":
            def_line = node.lineno
    findings: list[Finding] = []
    for knob in sorted(env - shipped):
        if knob in exempt_map:
            continue
        findings.append(Finding(
            rule="ML005", path=disagg_rel, line=def_line,
            message=f"{knob} parses in EngineConfig.from_env but "
                    "_config_env never ships it — disagg workers "
                    "silently run the default (the PR 15 bug class); "
                    "add it to _config_env or exempt it with a reason "
                    "in analysis/memory.py WORKER_ENV_EXEMPT",
            snippet=knob))
    for knob in sorted(set(exempt_map) - env):
        findings.append(Finding(
            rule="ML000", path=disagg_rel, line=def_line,
            message=f"WORKER_ENV_EXEMPT names {knob}, which from_env "
                    "no longer reads — stale exemption, delete it",
            snippet=knob))
    return findings


# ---------------------------------------------------------------------------
# ML006: heap-witness merge
# ---------------------------------------------------------------------------


def _witness_growth(series: list[int]) -> tuple[int, bool]:
    """(growth bytes, sustained?) after discarding the warmup prefix."""
    if len(series) < WITNESS_MIN_CHECKPOINTS:
        return 0, False
    warm = max(2, len(series) // 3)
    base = series[warm]
    mid = series[(warm + len(series) - 1) // 2]
    last = series[-1]
    growth = last - base
    sustained = last > base and last >= mid
    return growth, sustained


def witness_findings(processes: list[dict]) -> list[Finding]:
    findings: list[Finding] = []
    for proc in processes:
        cps = proc.get("checkpoints", [])
        path = proc.get("argv0") or "<heap-witness>"
        series = [int(cp.get("traced_current", 0)) for cp in cps]
        growth, sustained = _witness_growth(series)
        if sustained and growth > max(
                WITNESS_GROWTH_FLOOR_BYTES,
                WITNESS_GROWTH_FRACTION * series[max(2, len(series) // 3)]):
            warm = max(2, len(series) // 3)
            base_top = {t["file"]: t["bytes"]
                        for t in cps[warm].get("top", [])}
            deltas = sorted(
                ((t["bytes"] - base_top.get(t["file"], 0), t["file"])
                 for t in cps[-1].get("top", [])),
                reverse=True)[:3]
            sites = ", ".join(f"{f} (+{d >> 10} KiB)"
                              for d, f in deltas if d > 0) or "unknown"
            findings.append(Finding(
                rule="ML006", path=path, line=1,
                message=f"observed unbounded heap growth: traced heap "
                        f"grew {growth >> 20} MiB after warmup over "
                        f"{len(cps)} checkpoints (pid "
                        f"{proc.get('pid')}); top growing sites: "
                        f"{sites}",
                snippet=f"pid={proc.get('pid')}"))
        overflowed: set[str] = set()
        for cp in cps:
            for name, pool in (cp.get("pools") or {}).items():
                used = pool.get("used")
                cap = pool.get("capacity")
                if used is None or cap is None or name in overflowed:
                    continue
                if used > cap:
                    # First offending checkpoint per pool — one finding,
                    # not one per sample of the same breach.
                    overflowed.add(name)
                    findings.append(Finding(
                        rule="ML006", path=path, line=1,
                        message=f"pool {name!r} observed above its "
                                f"declared capacity at checkpoint "
                                f"{cp.get('label')!r}: used {used} > "
                                f"capacity {cap} — the static ledger "
                                "no longer matches the allocator",
                        snippet=name))
    return findings


# ---------------------------------------------------------------------------
# Rule registry (for --list-rules and namespace validation)
# ---------------------------------------------------------------------------


class _ProjectRule(Rule):
    """Project-scope rule: implemented as a cross-file check, present
    here so the ML namespace validates suppressions and --only ids."""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        return iter(())


class CapacityRule(_ProjectRule):
    id = "ML001"
    name = "capacity-contract"
    description = ("served config's weights + KV pool + scale pools + "
                   "largest transient must fit ChipSpec.hbm_bytes")


class KnobDocRule(_ProjectRule):
    id = "ML003"
    name = "knob-documented"
    description = ("every POLYKEY_* read needs a DEPLOY.md row or an "
                   "internal-only annotation")


class KnobSingleParseRule(_ProjectRule):
    id = "ML004"
    name = "knob-single-parse"
    description = ("a from_env-owned knob must not be re-read ad hoc "
                   "elsewhere in the package")


class KnobShipRule(_ProjectRule):
    id = "ML005"
    name = "knob-ships-to-workers"
    description = ("every from_env knob ships via disagg _config_env "
                   "or carries a coordinator-only exemption")


class WitnessGrowthRule(_ProjectRule):
    id = "ML006"
    name = "observed-growth"
    description = ("heap witness observed sustained growth or a pool "
                   "above its declared capacity (--witness)")


MEM_RULES: list[Rule] = [
    CapacityRule(), GrowthRule(), KnobDocRule(), KnobSingleParseRule(),
    KnobShipRule(), WitnessGrowthRule(),
]


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


def run_memlint(root: Path, targets: Optional[Iterable[str]] = None,
                only: Optional[set[str]] = None,
                witness: Optional[list[dict]] = None,
                ) -> tuple[list[Finding], list[dict]]:
    """Run the tier. Returns (findings, capacity ledgers). `only`
    filters rule ids; project checks whose inputs fall outside the
    scanned targets are skipped on partial runs (mirroring racelint:
    a partial run refuses --prune, so skipping can't drop debt)."""
    if targets is None:
        targets = [t for t in DEFAULT_TARGETS if (root / t).exists()]
        if not targets:
            raise FileNotFoundError(
                f"none of the default lint targets "
                f"({', '.join(DEFAULT_TARGETS)}) exist under {root}")
    want = (lambda rid: only is None or rid in only)

    contexts: dict[str, FileContext] = {}
    findings: list[Finding] = []
    for path in iter_py_files(root, targets):
        rel = path.resolve().relative_to(root.resolve()).as_posix()
        if rel.startswith("polykey_tpu/proto/"):
            continue
        source = path.read_text(encoding="utf-8")
        try:
            contexts[rel] = FileContext(path, rel, source)
        except SyntaxError as e:
            findings.append(Finding(
                rule="ML000", path=rel, line=e.lineno or 1,
                message=f"syntax error: {e.msg}"))

    by_path: dict[str, list[Finding]] = {rel: [] for rel in contexts}

    if want("ML002"):
        rule = next(r for r in MEM_RULES if r.id == "ML002")
        for rel, ctx in contexts.items():
            if rule.applies(rel):
                by_path[rel].extend(rule.check(ctx))

    env_reads = {rel: module_env_reads(ctx.tree)
                 for rel, ctx in contexts.items()}
    env_reads = {rel: reads for rel, reads in env_reads.items() if reads}

    def _sink(fs: list[Finding]) -> None:
        for f in fs:
            by_path.setdefault(f.path, []).append(f)

    if want("ML003"):
        deploy = root / "DEPLOY.md"
        deploy_text = None
        try:
            deploy_text = deploy.read_text(encoding="utf-8")
        except OSError:
            pass
        _sink(check_knob_docs(env_reads, deploy_text))
    if want("ML004"):
        _sink(check_knob_single_parse(env_reads))
    if want("ML005") and CONFIG_REL in contexts and DISAGG_REL in contexts:
        _sink(check_ship_contract(contexts[CONFIG_REL].tree,
                                  contexts[DISAGG_REL].tree))

    ledgers: list[dict] = []
    if want("ML001"):
        cap_findings, ledgers = check_capacity()
        _sink(cap_findings)

    if want("ML006") and witness is not None:
        _sink(witness_findings(witness))

    out: list[Finding] = []
    for rel in sorted(by_path):
        ctx = contexts.get(rel)
        fs = by_path[rel]
        if ctx is not None:
            fs = ctx.apply_suppressions(fs, rules=MEM_RULES)
        out.extend(fs)
    return sorted(out, key=lambda f: (f.path, f.line, f.rule)), ledgers


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m polykey_tpu.analysis mem",
        description="memlint: memory & capacity contract analysis "
                    "(byte ledger, unbounded growth, knob contracts)",
    )
    parser.add_argument(
        "targets", nargs="*", default=None,
        help=f"files/directories to scan (default: "
             f"{' '.join(DEFAULT_TARGETS)})")
    parser.add_argument("--root", default=".",
                        help="repo root (default: cwd)")
    parser.add_argument("--baseline", default=MEM_BASELINE, metavar="FILE",
                        help="grandfathering baseline file")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline file entirely")
    parser.add_argument("--write-baseline", action="store_true",
                        help="grandfather current blocking findings")
    parser.add_argument("--prune", action="store_true",
                        help="drop stale baseline entries, then exit")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings + ledger + summary as JSON")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--only", metavar="IDS",
                        help="comma-separated rule ids to run "
                             "(e.g. ML002,ML005)")
    parser.add_argument("--witness", metavar="PATH",
                        help="heap-witness JSON file or directory to "
                             "merge (ML006)")
    return parser


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        print("ML000  meta                       suppression hygiene, "
              "unparseable inputs, stale matrix")
        for rule in MEM_RULES:
            print(f"{rule.id}  {rule.name:<26} {rule.description}")
        return 0

    root = Path(args.root).resolve()
    if not root.is_dir():
        print(f"memlint: --root {args.root} is not a directory",
              file=sys.stderr)
        return 2

    targets = args.targets or None
    try:
        only = parse_only(args.only, {r.id for r in MEM_RULES})
        # A partial run can't tell "fixed" from "not scanned" (shared
        # refusal semantics, core.py).
        require_full_run(partial=bool(targets) or only is not None,
                         prune=args.prune,
                         write_baseline=args.write_baseline)
        from . import heapwitness

        witness = load_witness_arg(args.witness, heapwitness.load_witness)
    except UsageError as e:
        print(f"memlint: {e}", file=sys.stderr)
        return 2
    partial = bool(targets) or only is not None

    try:
        findings, ledgers = run_memlint(root, targets, only, witness)
    except FileNotFoundError as e:
        print(f"memlint: {e}", file=sys.stderr)
        return 2

    if partial:
        # Unused-suppression and stale-baseline signals need the full
        # sweep; a partial run must neither report nor act on them.
        findings = [f for f in findings
                    if not (f.rule == "ML000"
                            and "unused suppression" in f.message)]

    meta = [f for f in findings if f.rule == "ML000" and f.blocking]
    baseline_path = root / args.baseline
    if args.prune:
        if meta:
            print("memlint: refusing --prune while ML000 findings exist "
                  "(a broken check is a partial run in disguise):",
                  file=sys.stderr)
            for f in meta:
                print(f"  {f.render()}", file=sys.stderr)
            return 2
        kept, dropped = prune_baseline(baseline_path, findings)
        print(f"memlint: pruned {dropped} stale baseline entr"
              f"{'y' if dropped == 1 else 'ies'} from {baseline_path} "
              f"({kept} kept)")
        return 0
    if args.write_baseline:
        if meta:
            print("memlint: refusing --write-baseline while ML000 "
                  "findings exist — fix the infrastructure first:",
                  file=sys.stderr)
            for f in meta:
                print(f"  {f.render()}", file=sys.stderr)
            return 2
        count = write_baseline(baseline_path, findings)
        print(f"memlint: wrote {count} baseline entr"
              f"{'y' if count == 1 else 'ies'} to {baseline_path}")
        return 0

    stale: list[str] = []
    if not args.no_baseline:
        findings, stale = apply_baseline(
            findings, load_baseline(baseline_path))

    blocking = [f for f in findings if f.blocking]
    suppressed = sum(1 for f in findings if f.suppressed)
    baselined = sum(1 for f in findings if f.baselined)

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in findings],
            "ledger": [
                {k: (round(v, 4) if isinstance(v, float) else v)
                 for k, v in entry.items()
                 if k != "transient_bytes"}
                | {"transient_bytes": {
                    k: round(v, 1)
                    for k, v in entry["transient_bytes"].items()}}
                for entry in ledgers
            ],
            "summary": {
                "blocking": len(blocking),
                "suppressed": suppressed,
                "baselined": baselined,
                "stale_baseline_entries": stale,
                "witness_processes": len(witness) if witness else 0,
                "mem_clean": not blocking,
            },
        }, indent=2))
    else:
        for f in findings:
            if f.blocking:
                print(f.render())
        parts = [f"{len(blocking)} blocking"]
        if suppressed:
            parts.append(f"{suppressed} suppressed")
        if baselined:
            parts.append(f"{baselined} baselined")
        if ledgers:
            fits = sum(1 for e in ledgers if e["fits"])
            parts.append(f"{fits}/{len(ledgers)} capacity entries fit")
        if witness:
            parts.append(f"{len(witness)} witness process"
                         f"{'' if len(witness) == 1 else 'es'} merged")
        print(f"memlint: {', '.join(parts)}")
        if stale and not partial:
            print(f"memlint: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed findings) "
                  "— re-run with --prune")
    return 1 if blocking else 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
