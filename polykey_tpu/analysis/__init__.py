"""polylint — project-invariant static analysis for the TPU serving stack.

The engine's hot path survives on rules no general-purpose linter knows:
host↔device syncs are only legal at annotated resolve points, latency
math must use monotonic clocks, ``except Exception`` must never wedge a
request silently, nothing may block under the engine's locks, threads
must be daemons or owned by a ``stop()``, jit boundaries must stay pure,
and metric families must follow the ``obs/`` naming contract. PR 1 made
regressions in these invariants *observable*; this package makes a whole
class of them impossible to merge.

Usage::

    python -m polykey_tpu.analysis                    # lint the repo
    python -m polykey_tpu.analysis --json             # machine-readable
    python -m polykey_tpu.analysis --list-rules       # rule table
    python -m polykey_tpu.analysis --write-baseline   # grandfather
    python -m polykey_tpu.analysis --prune            # drop stale baseline
    python -m polykey_tpu.analysis graph              # graphlint (2nd tier)
    python -m polykey_tpu.analysis race               # racelint (3rd tier)
    python -m polykey_tpu.analysis mem                # memlint (4th tier)
    python -m polykey_tpu.analysis sched              # schedlint (5th tier)
    python -m polykey_tpu.analysis all                # every tier, one exit

Five tiers, one discipline (per-tier baselines that trend toward
empty, mandatory-reason suppressions, content-hashed fingerprints):

- **polylint** (``rules.py``, PL***) — what the *source* promises:
  per-file AST invariants on syncs, clocks, excepts, locks, threads,
  jit purity, metric naming. Stdlib-only.
- **graphlint** (``graph.py``, GL***) — what the *compiled graphs*
  actually do: recompile stability, donation aliasing, dtype policy,
  host-transfer discipline, kernel/sharding layout, by tracing the real
  engine on a CPU backend. Needs jax; imported lazily by the ``graph``
  subcommand only.
- **racelint** (``concurrency.py``, CL***) — what the *threads and
  processes* do to each other: the interprocedural lock-acquisition
  graph (cycles = deadlocks), unguarded shared state, lock-scope
  escapes, blocking-under-lock across call boundaries, and the disagg
  coordinator/worker + KV-wire protocol conformance. Stdlib-only, with
  an opt-in runtime witness (``witness.py``, POLYKEY_LOCK_WITNESS=1)
  that merges *observed* acquisition-order edges — with stacks — into
  the static graph (``race --witness``).
- **memlint** (``memory.py``, ML***) — what the *bytes* do: an
  analytic capacity ledger (weights + device KV pool + int8 scale
  planes + largest jit transient, with donation aliasing credits) that
  must fit ``ChipSpec.hbm_bytes`` for every served-matrix entry,
  unbounded-growth rules over long-lived containers, and the
  ``POLYKEY_*`` knob contracts (documented in DEPLOY.md, single parse
  site, shipped to disagg workers via ``_config_env``). Stdlib-only,
  with an opt-in runtime heap witness (``heapwitness.py``,
  POLYKEY_HEAP_WITNESS=1) that merges *observed* tracemalloc growth
  and pool occupancies into the findings (``mem --witness``).
- **schedlint** (``sched.py``, SL***) — what the *scheduler* promises:
  liveness and fairness contracts over the engine loop — every
  budget-bounded dispatch loop has a statically provable progress
  floor, every round-robin cursor advances or re-anchors
  (starved-first) on every consumption path, the restore→prefill→
  decode frontier order holds per iteration, consumed queues pair with
  an admission bound or shed path, and ragged per-range accounting
  sums exactly to the dispatch width. Stdlib-only, with an opt-in
  runtime starvation witness (``schedwitness.py``,
  POLYKEY_SCHED_WITNESS=1) that records per-slot wait ages and
  consecutive-skip counts at dispatch boundaries and merges them into
  the verdict under a max-starvation-age gate (``sched --witness``).

Per-line suppression (reason required; reasonless or unused suppressions
are themselves findings; the rule id's prefix names the tier that
validates it, so PL/CL/ML/SL entries never cross-fire)::

    packed = np.asarray(data)  # polylint: disable=PL001(resolve point)
    self._closing = True  # polylint: disable=CL002(one-way latch)
    self._sticky[k] = v  # polylint: disable=ML002(EWMA per replica id)
    drain()  # polylint: disable=SL004(shutdown path, loop already dead)

The package is stdlib-only by design: the CI lint job installs ruff and
nothing else, and ``python -m polykey_tpu.analysis`` must run there.
"""

from .baseline import (
    apply_baseline,
    load_baseline,
    prune_baseline,
    write_baseline,
)
from .core import (
    FileContext,
    Finding,
    Rule,
    all_rules,
    check_file,
    register,
    run_paths,
)

# Importing the rules module populates the registry as a side effect
# (it must follow the core import that defines the registry).
from . import rules

__all__ = [
    "FileContext",
    "Finding",
    "Rule",
    "all_rules",
    "apply_baseline",
    "check_file",
    "load_baseline",
    "prune_baseline",
    "register",
    "rules",
    "run_paths",
    "write_baseline",
]
