"""polylint — project-invariant static analysis for the TPU serving stack.

The engine's hot path survives on rules no general-purpose linter knows:
host↔device syncs are only legal at annotated resolve points, latency
math must use monotonic clocks, ``except Exception`` must never wedge a
request silently, nothing may block under the engine's locks, threads
must be daemons or owned by a ``stop()``, jit boundaries must stay pure,
and metric families must follow the ``obs/`` naming contract. PR 1 made
regressions in these invariants *observable*; this package makes a whole
class of them impossible to merge.

Usage::

    python -m polykey_tpu.analysis                    # lint the repo
    python -m polykey_tpu.analysis --json             # machine-readable
    python -m polykey_tpu.analysis --list-rules       # rule table
    python -m polykey_tpu.analysis --write-baseline   # grandfather
    python -m polykey_tpu.analysis --prune            # drop stale baseline
    python -m polykey_tpu.analysis graph              # graphlint (2nd tier)

The second tier ("graphlint", ``analysis/graph.py``) verifies what the
COMPILED graphs actually do — recompile stability, donation aliasing,
dtype policy, host-transfer discipline, kernel/sharding layout — by
tracing the real engine on a CPU backend. It needs jax and is imported
lazily by the ``graph`` subcommand only; everything below stays
stdlib-only.

Per-line suppression (reason required; reasonless or unused suppressions
are themselves findings)::

    packed = np.asarray(data)  # polylint: disable=PL001(resolve point)

The package is stdlib-only by design: the CI lint job installs ruff and
nothing else, and ``python -m polykey_tpu.analysis`` must run there.
"""

from .baseline import (
    apply_baseline,
    load_baseline,
    prune_baseline,
    write_baseline,
)
from .core import (
    FileContext,
    Finding,
    Rule,
    all_rules,
    check_file,
    register,
    run_paths,
)

# Importing the rules module populates the registry as a side effect
# (it must follow the core import that defines the registry).
from . import rules

__all__ = [
    "FileContext",
    "Finding",
    "Rule",
    "all_rules",
    "apply_baseline",
    "check_file",
    "load_baseline",
    "prune_baseline",
    "register",
    "rules",
    "run_paths",
    "write_baseline",
]
