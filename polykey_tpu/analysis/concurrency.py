"""racelint — concurrency & cross-process protocol contracts (3rd tier).

PR 7 and PR 13 turned a single-threaded engine into a concurrent
system: thread spawn sites across engine/obs/gateway, ~20 locks, and a
hand-rolled length-framed socket protocol between the disagg
coordinator and its workers. polylint's PL004 only sees a blocking call
*lexically* inside a ``with lock:`` body and graphlint only audits
compiled graphs — neither can see a deadlock forming across call
boundaries or a coordinator/worker protocol drift. This tier can:

| Rule  | Contract                                                         |
|-------|------------------------------------------------------------------|
| CL001 | the interprocedural lock-acquisition graph is acyclic            |
| CL002 | state shared between a thread entry's call tree and public       |
|       | methods is written under the owning class's lock                 |
| CL003 | lock-guarded mutable containers never escape by reference        |
| CL004 | no blocking call is *reachable* while a lock is held (the        |
|       | interprocedural generalization of PL004)                         |
| CL005 | the disagg control-plane protocol and the KV wire format agree   |
|       | on both sides (ops ↔ handlers, fields, header symmetry)          |

Everything is stdlib-only AST like polylint, shares the PR 2
baseline/fingerprint machinery (``racelint-baseline.json``, committed
empty) and the ``# polylint: disable=CL00x(reason)`` suppression
comment (the CL namespace is validated by THIS tier only — a plain
polylint run ignores it).

**The model.** One pass parses every scanned file and indexes classes,
functions, lock constructions (``self._x = threading.Lock()`` /
``RLock`` / dataclass ``field(default_factory=threading.Lock)`` /
module-level locks) and a light type environment: ``self``/``cls``,
parameter annotations naming project classes, locals assigned from a
project-class constructor, and ``self.attr`` types assigned in
``__init__``. Call edges resolve through that environment — same-class
methods, same-module functions, ``from``-imports, and
attribute calls on typed receivers. The lock graph's nodes are lock
*creation sites* (``Class.attr`` anchored at ``path:line``), which is
also the identity the runtime witness records, so
``race --witness <file-or-dir>`` merges observed edges (with stacks)
into the static graph before cycle detection.

**Approximations** (each documented on its rule): the call graph is
name-and-annotation resolved — unresolvable calls contribute no edges
(missed deadlocks possible, the witness exists for exactly this), and
``getattr``/callback indirection is invisible. Lock acquisition is the
``with`` statement only; bare ``.acquire()`` discipline is not modeled.
``threading.Condition`` is deliberately not a lock here (waiting under
a condition is its sanctioned use).

Run::

    python -m polykey_tpu.analysis race              # repo gate
    python -m polykey_tpu.analysis race --json       # machine-readable
    python -m polykey_tpu.analysis race --witness perf/lock-witness/
    python -m polykey_tpu.analysis race --dump-graph graph.json
"""

from __future__ import annotations

import argparse
import ast
import json
import re
import sys
from pathlib import Path
from typing import Iterator, Optional

from .baseline import (
    apply_baseline,
    load_baseline,
    prune_baseline,
    write_baseline,
)
from .core import (
    DEFAULT_TARGETS,
    _EXCLUDE_PREFIXES,
    FileContext,
    Finding,
    Rule,
    UsageError,
    iter_py_files,
    load_witness_arg,
    parse_only,
    require_full_run,
)
from .rules import call_name, dotted, walk_no_nested_functions

RACE_BASELINE = "racelint-baseline.json"

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
_RLOCK_CTORS = {"threading.RLock", "RLock"}

# Mutable-container constructors/displays for CL003's escape analysis.
_CONTAINER_CTORS = {
    "dict", "list", "set", "collections.OrderedDict", "OrderedDict",
    "collections.defaultdict", "defaultdict", "collections.deque", "deque",
}
_MUTATING_METHODS = {
    "append", "add", "update", "pop", "popitem", "remove", "discard",
    "clear", "setdefault", "extend", "insert", "appendleft",
    "move_to_end",
}

# Lexically-blocking calls CL004 hunts through the call graph. get/put
# additionally fire on queue-looking receivers (PL004's heuristic plus
# the request-out-queue convention).
_BLOCKING_NAMES = {
    "time.sleep", "socket.create_connection", "subprocess.run",
    "subprocess.check_output", "subprocess.check_call", "select.select",
}
_BLOCKING_ATTRS = {
    "sleep", "accept", "recv", "recvfrom", "recv_into", "sendall",
    "connect", "communicate", "wait", "join", "result",
}
_QUEUE_HINT_RE = re.compile(r"(queue|_q$|submit|(^|\.)out$)",
                            re.IGNORECASE)


# -- rule registry (ids/docs only; the analyzer below drives) -----------------


class RaceRule(Rule):
    """CL rules are cross-file: they run from the project index, not
    per-FileContext — check() is unused. The class still subclasses
    core.Rule so suppression validation shares one shape."""

    def check(self, ctx):  # pragma: no cover - not used by this tier
        return iter(())


class LockOrderCycles(RaceRule):
    id = "CL001"
    name = "lock-order-cycle"
    description = ("the interprocedural lock-acquisition graph has a "
                   "cycle — a potential deadlock")


class UnguardedSharedState(RaceRule):
    id = "CL002"
    name = "unguarded-shared-state"
    description = ("attribute written from a thread's call tree and "
                   "from public methods without the owning lock")


class LockScopeEscape(RaceRule):
    id = "CL003"
    name = "lock-scope-escape"
    description = ("lock-guarded mutable container returned/yielded by "
                   "reference instead of a copy")


class BlockingReachableUnderLock(RaceRule):
    id = "CL004"
    name = "blocking-reachable-under-lock"
    description = ("blocking call reachable through the call graph "
                   "while a lock is held")


class ProtocolConformance(RaceRule):
    id = "CL005"
    name = "protocol-conformance"
    description = ("disagg coordinator/worker ops, event fields, and "
                   "the KV wire header agree on both sides")


RACE_RULES: list[Rule] = [
    LockOrderCycles(), UnguardedSharedState(), LockScopeEscape(),
    BlockingReachableUnderLock(), ProtocolConformance(),
]
RACE_RULE_IDS = {r.id for r in RACE_RULES}


def _finding(rule: str, path: str, line: int, message: str,
             snippet: str = "") -> Finding:
    return Finding(rule=rule, path=path, line=line, message=message,
                   snippet=snippet)


# -- project model ------------------------------------------------------------


class FuncInfo:
    __slots__ = ("key", "rel", "cls_key", "cls_name", "name", "node",
                 "label")

    def __init__(self, key: str, rel: str, cls_key: Optional[str],
                 cls_name: Optional[str], name: str, node: ast.AST):
        self.key = key
        self.rel = rel
        self.cls_key = cls_key
        self.cls_name = cls_name
        self.name = name
        self.node = node
        self.label = f"{cls_name}.{name}" if cls_name else name


class ClassInfo:
    __slots__ = ("key", "name", "rel", "node", "locks", "rlocks",
                 "field_locks", "attr_types", "container_attrs",
                 "methods")

    def __init__(self, key: str, name: str, rel: str, node: ast.ClassDef):
        self.key = key
        self.name = name
        self.rel = rel
        self.node = node
        self.locks: dict[str, int] = {}       # attr -> creation line
        self.rlocks: set[str] = set()
        # Locks declared as dataclass field(default_factory=...): their
        # RUNTIME creation site is the ClassName(...) construction line
        # (the generated __init__ has no witnessable frame), so the
        # witness merge must key them by construction sites too.
        self.field_locks: set[str] = set()
        self.attr_types: dict[str, str] = {}  # self.attr -> class key
        self.container_attrs: dict[str, int] = {}
        self.methods: dict[str, FuncInfo] = {}


class ModuleInfo:
    __slots__ = ("rel", "ctx", "classes", "functions", "imports",
                 "module_locks")

    def __init__(self, rel: str, ctx: FileContext):
        self.rel = rel
        self.ctx = ctx
        self.classes: dict[str, ClassInfo] = {}
        self.functions: dict[str, FuncInfo] = {}
        # local name -> (module rel, remote name) for from-imports
        self.imports: dict[str, tuple[str, str]] = {}
        self.module_locks: dict[str, int] = {}


def _is_lock_ctor(node: ast.AST) -> Optional[bool]:
    """None = not a lock; False = Lock; True = RLock. Handles direct
    constructor calls, dataclass field(default_factory=...), and the
    shared-lock idiom ``x if x is not None else threading.Lock()``."""
    if isinstance(node, ast.IfExp):
        body = _is_lock_ctor(node.body)
        orelse = _is_lock_ctor(node.orelse)
        if body is None and orelse is None:
            return None
        return bool(body) or bool(orelse)
    if not isinstance(node, ast.Call):
        return None
    name = call_name(node)
    if name in _LOCK_CTORS:
        return name in _RLOCK_CTORS
    if name.rsplit(".", 1)[-1] == "field":
        for kw in node.keywords:
            if kw.arg == "default_factory":
                factory = dotted(kw.value)
                if factory in _LOCK_CTORS:
                    return factory in _RLOCK_CTORS
    return None


def _is_field_call(node: Optional[ast.AST]) -> bool:
    return isinstance(node, ast.Call) \
        and call_name(node).rsplit(".", 1)[-1] == "field"


def _is_container_ctor(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                         ast.ListComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name in _CONTAINER_CTORS:
            return True
        if name.rsplit(".", 1)[-1] == "field":
            for kw in node.keywords:
                if kw.arg == "default_factory" \
                        and dotted(kw.value) in _CONTAINER_CTORS:
                    return True
    return False


def _annotation_name(node: Optional[ast.AST]) -> Optional[str]:
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.strip()
    name = dotted(node)
    return name or None


class Project:
    """The cross-file index every CL rule reads."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}
        self.classes: dict[str, ClassInfo] = {}        # by key
        self.class_names: dict[str, list[str]] = {}    # name -> keys
        self.functions: dict[str, FuncInfo] = {}       # by key
        self.syntax_errors: list[Finding] = []

    # -- construction --------------------------------------------------------

    def add_file(self, path: Path, root: Path) -> None:
        """Parse one file. Cross-module resolution happens in
        finalize() — imports may point at files not yet added."""
        rel = path.resolve().relative_to(root.resolve()).as_posix()
        if rel.startswith(_EXCLUDE_PREFIXES):
            return
        source = path.read_text(encoding="utf-8")
        try:
            ctx = FileContext(path, rel, source)
        except SyntaxError as e:
            self.syntax_errors.append(_finding(
                "CL000", rel, e.lineno or 1, f"syntax error: {e.msg}",
            ))
            return
        module = ModuleInfo(rel, ctx)
        self.modules[rel] = module
        self._index_imports(module)

    def finalize(self) -> None:
        """Index classes/functions/locks (pass A), then resolve typed
        attributes — which needs the full class-name index (pass B)."""
        for module in self.modules.values():
            for node in module.ctx.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._index_class(module, node)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    self._index_function(module, None, node)
                elif isinstance(node, ast.Assign):
                    if _is_lock_ctor(node.value) is not None:
                        for target in node.targets:
                            if isinstance(target, ast.Name):
                                module.module_locks[target.id] = \
                                    node.lineno
            # Nested functions (thread targets like create()'s _boot):
            # indexed by bare name when nothing top-level claims it.
            method_nodes = {
                id(m.node) for cls in module.classes.values()
                for m in cls.methods.values()
            }
            for node in ast.walk(module.ctx.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node.name not in module.functions \
                        and id(node) not in method_nodes:
                    self._index_function(module, None, node)
        for module in self.modules.values():
            for cls in module.classes.values():
                self._resolve_attr_types(module, cls)

    def _index_imports(self, module: ModuleInfo) -> None:
        parts = module.rel[:-3].split("/")      # drop .py
        for node in ast.walk(module.ctx.tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            if node.level > 0:
                base = parts[:-(node.level)]
                if node.module:
                    base = base + node.module.split(".")
            elif node.module and node.module.startswith("polykey_tpu"):
                base = node.module.split(".")
            else:
                continue
            target_rel = "/".join(base) + ".py"
            pkg_rel = "/".join(base) + "/__init__.py"
            for alias in node.names:
                module.imports[alias.asname or alias.name] = (
                    target_rel if not alias.name == "*" else pkg_rel,
                    alias.name,
                )

    def _index_class(self, module: ModuleInfo, node: ast.ClassDef) -> None:
        key = f"{module.rel}::{node.name}"
        cls = ClassInfo(key, node.name, module.rel, node)
        module.classes[node.name] = cls
        self.classes[key] = cls
        self.class_names.setdefault(node.name, []).append(key)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_function(module, cls, stmt)
            elif isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name):
                kind = _is_lock_ctor(stmt.value) \
                    if stmt.value is not None else None
                if kind is not None:
                    cls.locks[stmt.target.id] = stmt.lineno
                    if kind:
                        cls.rlocks.add(stmt.target.id)
                    if _is_field_call(stmt.value):
                        cls.field_locks.add(stmt.target.id)
                elif stmt.value is not None \
                        and _is_container_ctor(stmt.value):
                    cls.container_attrs[stmt.target.id] = stmt.lineno
            elif isinstance(stmt, ast.Assign):
                kind = _is_lock_ctor(stmt.value)
                for target in stmt.targets:
                    if not isinstance(target, ast.Name):
                        continue
                    if kind is not None:
                        cls.locks[target.id] = stmt.lineno
                        if kind:
                            cls.rlocks.add(target.id)
                        if _is_field_call(stmt.value):
                            cls.field_locks.add(target.id)
                    elif _is_container_ctor(stmt.value):
                        cls.container_attrs[target.id] = stmt.lineno
        # Method-body attribute facts: locks and containers assigned to
        # self (typed attrs wait for pass B — see _resolve_attr_types).
        for method in cls.methods.values():
            for stmt in ast.walk(method.node):
                if not isinstance(stmt, ast.Assign):
                    continue
                for target in stmt.targets:
                    if not (isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id == "self"):
                        continue
                    attr = target.attr
                    kind = _is_lock_ctor(stmt.value)
                    if kind is not None:
                        cls.locks.setdefault(attr, stmt.lineno)
                        if kind:
                            cls.rlocks.add(attr)
                    elif _is_container_ctor(stmt.value):
                        cls.container_attrs.setdefault(attr, stmt.lineno)

    def _resolve_attr_types(self, module: ModuleInfo,
                            cls: ClassInfo) -> None:
        for method in cls.methods.values():
            for stmt in ast.walk(method.node):
                if not isinstance(stmt, ast.Assign) \
                        or not isinstance(stmt.value, ast.Call):
                    continue
                ctor = self.resolve_class_name(
                    module, call_name(stmt.value))
                if ctor is None:
                    continue
                for target in stmt.targets:
                    if isinstance(target, ast.Attribute) \
                            and isinstance(target.value, ast.Name) \
                            and target.value.id == "self":
                        cls.attr_types.setdefault(target.attr, ctor)

    def _index_function(self, module: ModuleInfo, cls: Optional[ClassInfo],
                        node: ast.AST) -> None:
        if cls is not None:
            key = f"{module.rel}::{cls.name}.{node.name}"
            info = FuncInfo(key, module.rel, cls.key, cls.name,
                            node.name, node)
            cls.methods[node.name] = info
        else:
            key = f"{module.rel}::{node.name}"
            info = FuncInfo(key, module.rel, None, None, node.name, node)
            module.functions.setdefault(node.name, info)
        self.functions.setdefault(key, info)

    # -- resolution ----------------------------------------------------------

    def resolve_class_name(self, module: ModuleInfo,
                           name: Optional[str]) -> Optional[str]:
        """Class key for a (possibly dotted) name seen in `module`."""
        if not name:
            return None
        tail = name.rsplit(".", 1)[-1]
        if tail in module.classes:
            return module.classes[tail].key
        imported = module.imports.get(tail)
        if imported is not None:
            target = self.modules.get(imported[0])
            if target is not None and imported[1] in target.classes:
                return target.classes[imported[1]].key
        keys = self.class_names.get(tail, [])
        if len(keys) == 1:
            return keys[0]
        return None

    def local_types(self, fn: FuncInfo) -> dict[str, str]:
        """name -> class key for self/cls, annotated params, and locals
        assigned from a project-class constructor."""
        module = self.modules[fn.rel]
        out: dict[str, str] = {}
        if fn.cls_key is not None:
            out["self"] = fn.cls_key
            out["cls"] = fn.cls_key
        args = fn.node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            ann = _annotation_name(a.annotation)
            resolved = self.resolve_class_name(module, ann)
            if resolved is not None:
                out[a.arg] = resolved
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                resolved = self.resolve_class_name(
                    module, call_name(node.value))
                if resolved is not None:
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            out.setdefault(target.id, resolved)
            elif isinstance(node, ast.For):
                # for worker in self.workers / list(self.workers): the
                # element type is invisible; annotated loops are rare —
                # accept the miss (documented).
                pass
        return out

    def expr_type(self, expr: ast.AST, types: dict[str, str],
                  ) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return types.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self.expr_type(expr.value, types)
            if base is not None:
                cls = self.classes.get(base)
                if cls is not None:
                    return cls.attr_types.get(expr.attr)
        return None

    def resolve_lock(self, expr: ast.AST, fn: FuncInfo,
                     types: dict[str, str]) -> Optional[str]:
        """Lock key ('path::Class.attr' / 'path::name') for a with-item
        context expression, or None."""
        module = self.modules[fn.rel]
        if isinstance(expr, ast.Name):
            if expr.id in module.module_locks:
                return f"{fn.rel}::{expr.id}"
            return None
        if isinstance(expr, ast.Attribute):
            base = self.expr_type(expr.value, types)
            if base is not None:
                cls = self.classes.get(base)
                if cls is not None and expr.attr in cls.locks:
                    return f"{base}.{expr.attr}"
        return None

    def lock_site(self, lock_key: str) -> tuple[str, int, str]:
        """(path, line, display) of a lock key's creation site."""
        path, _, tail = lock_key.partition("::")
        if "." in tail:
            cls_name, attr = tail.rsplit(".", 1)
            cls = self.classes.get(f"{path}::{cls_name}")
            if cls is not None and attr in cls.locks:
                return path, cls.locks[attr], f"{cls_name}.{attr}"
        module = self.modules.get(path)
        if module is not None and tail in module.module_locks:
            return path, module.module_locks[tail], \
                f"{path.rsplit('/', 1)[-1]}:{tail}"
        return path, 0, tail

    def is_rlock(self, lock_key: str) -> bool:
        path, _, tail = lock_key.partition("::")
        if "." in tail:
            cls_name, attr = tail.rsplit(".", 1)
            cls = self.classes.get(f"{path}::{cls_name}")
            return cls is not None and attr in cls.rlocks
        return False

    def resolve_call(self, call: ast.Call, fn: FuncInfo,
                     types: dict[str, str]) -> Optional[FuncInfo]:
        module = self.modules[fn.rel]
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            if name in types:       # cls(...) / a constructor-typed local
                cls = self.classes.get(types[name])
                if cls is not None:
                    return cls.methods.get("__init__")
            if name in module.classes:
                return module.classes[name].methods.get("__init__")
            if name in module.functions:
                return module.functions[name]
            imported = module.imports.get(name)
            if imported is not None:
                target = self.modules.get(imported[0])
                if target is not None:
                    if imported[1] in target.functions:
                        return target.functions[imported[1]]
                    if imported[1] in target.classes:
                        return target.classes[imported[1]] \
                            .methods.get("__init__")
            return None
        if isinstance(func, ast.Attribute):
            base = self.expr_type(func.value, types)
            if base is not None:
                cls = self.classes.get(base)
                if cls is not None:
                    return cls.methods.get(func.attr)
        return None

    def resolve_func_ref(self, expr: ast.AST, fn: FuncInfo,
                         types: dict[str, str]) -> Optional[FuncInfo]:
        """A function REFERENCE (Thread target=...), not a call."""
        module = self.modules[fn.rel]
        if isinstance(expr, ast.Name):
            if expr.id in module.functions:
                return module.functions[expr.id]
            imported = module.imports.get(expr.id)
            if imported is not None:
                target = self.modules.get(imported[0])
                if target is not None:
                    return target.functions.get(imported[1])
            return None
        if isinstance(expr, ast.Attribute):
            base = self.expr_type(expr.value, types)
            if base is not None:
                cls = self.classes.get(base)
                if cls is not None:
                    return cls.methods.get(expr.attr)
        return None


# -- the analyzer -------------------------------------------------------------


class RaceAnalyzer:
    """Runs the CL rules over a built Project. Traversals memoize per
    function; the whole pass is one repo walk plus linear graph work."""

    def __init__(self, project: Project):
        self.project = project
        self._types: dict[str, dict[str, str]] = {}
        self._summaries: Optional[dict[str, dict]] = None
        # Static lock-order edges: (src, dst) -> edge info dict.
        self.edges: dict[tuple[str, str], dict] = {}
        self.witness_edges: dict[tuple[str, str], dict] = {}
        self.witness_unmapped: dict[str, dict] = {}
        self.cycles: list[list[str]] = []
        # Non-reentrant lock reacquired while held (a self-deadlock,
        # not an ordering problem): (lock, path, line, chain).
        self.self_deadlocks: list[tuple[str, str, int, str]] = []

    def types_for(self, fn: FuncInfo) -> dict[str, str]:
        cached = self._types.get(fn.key)
        if cached is None:
            # polylint: disable=ML002(memo keyed by function identity: bounded by the scanned repo, analyzer lives one run)
            cached = self._types[fn.key] = self.project.local_types(fn)
        return cached

    # -- shared walks ---------------------------------------------------------

    def _with_acquisitions(self, fn: FuncInfo) -> list[tuple[str, ast.With]]:
        out = []
        types = self.types_for(fn)
        for node in walk_no_nested_functions(
                getattr(fn.node, "body", [])):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lock = self.project.resolve_lock(
                        item.context_expr, fn, types)
                    if lock is not None:
                        out.append((lock, node))
        return out

    def _calls_in(self, body_nodes) -> Iterator[ast.Call]:
        for node in body_nodes:
            if isinstance(node, ast.Call):
                yield node

    def _ensure_summaries(self) -> dict[str, dict]:
        """Per-function summaries with TRANSITIVE acquire/blocking sets
        computed by fixpoint propagation over the call graph — not by
        recursive memoization, whose in-progress placeholder would
        poison results in call cycles (a caller memoized against a
        half-computed callee silently loses that callee's locks
        forever, and whether it happens depends on iteration order)."""
        if self._summaries is not None:
            return self._summaries
        summaries: dict[str, dict] = {}
        for fn in self.project.functions.values():
            types = self.types_for(fn)
            acquires: dict[str, tuple] = {}
            for lock, _node in self._with_acquisitions(fn):
                acquires.setdefault(lock, (fn.label,))
            blocking: dict[str, dict] = {}
            for node, desc in self._lexical_blocking(fn):
                key = f"{fn.rel}:{node.lineno}:{desc}"
                blocking.setdefault(key, {
                    "desc": desc, "path": fn.rel, "line": node.lineno,
                    "chain": (fn.label,),
                })
            callees: list[str] = []
            for node in walk_no_nested_functions(
                    getattr(fn.node, "body", [])):
                if isinstance(node, ast.Call):
                    callee = self.project.resolve_call(node, fn, types)
                    if callee is not None and callee.key != fn.key:
                        callees.append(callee.key)
            summaries[fn.key] = {
                "label": fn.label, "acquires": acquires,
                "blocking": blocking, "callees": callees,
            }
        # Propagate until stable: only new KEYS are ever added (each
        # key's chain is fixed at first insertion), so the loop
        # terminates in at most |locks|+|blocking sites| rounds.
        changed = True
        while changed:
            changed = False
            for s in summaries.values():
                for callee_key in s["callees"]:
                    callee = summaries.get(callee_key)
                    if callee is None:
                        continue
                    for lock, chain in callee["acquires"].items():
                        if lock not in s["acquires"]:
                            s["acquires"][lock] = (s["label"],) + chain
                            changed = True
                    for key, info in callee["blocking"].items():
                        if key not in s["blocking"]:
                            s["blocking"][key] = {
                                **info,
                                "chain": (s["label"],) + info["chain"],
                            }
                            changed = True
        self._summaries = summaries
        return summaries

    def reachable_acquires(self, fn: FuncInfo) -> dict[str, tuple]:
        """lock key -> call chain (labels) by which `fn` can acquire it,
        including transitively through resolvable calls."""
        return self._ensure_summaries().get(
            fn.key, {"acquires": {}})["acquires"]

    def _lexical_blocking(self, fn: FuncInfo) -> list[tuple[ast.Call, str]]:
        out = []
        for node in walk_no_nested_functions(getattr(fn.node, "body", [])):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            func = node.func
            attr = func.attr if isinstance(func, ast.Attribute) else ""
            if attr == "join" and isinstance(
                    func.value, (ast.Constant, ast.JoinedStr, ast.BinOp)):
                continue    # ", ".join(...) — a string, not a thread
            blocking = name in _BLOCKING_NAMES or attr in _BLOCKING_ATTRS
            if not blocking and attr in ("get", "put"):
                receiver = dotted(func.value) \
                    if isinstance(func, ast.Attribute) else ""
                has_kw = any(kw.arg in ("timeout", "block")
                             for kw in node.keywords)
                blocking = bool(_QUEUE_HINT_RE.search(receiver)) or has_kw
            if blocking:
                out.append((node, name or f".{attr}()"))
        return out

    def reachable_blocking(self, fn: FuncInfo) -> dict[str, dict]:
        """blocking-site key -> {desc, path, line, chain}."""
        return self._ensure_summaries().get(
            fn.key, {"blocking": {}})["blocking"]

    # -- CL001 ----------------------------------------------------------------

    def collect_lock_edges(self) -> None:
        """Populate self.edges: (src, dst) lock-order edges with the
        lexically-anchored site each edge was proven at."""
        for fn in self.project.functions.values():
            types = self.types_for(fn)
            for lock, with_node in self._with_acquisitions(fn):
                for node in walk_no_nested_functions(with_node.body):
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        for item in node.items:
                            inner = self.project.resolve_lock(
                                item.context_expr, fn, types)
                            if inner is None:
                                continue
                            if inner != lock:
                                self._add_edge(
                                    lock, inner, fn.rel, node.lineno,
                                    (fn.label,),
                                )
                            elif not self.project.is_rlock(lock):
                                # polylint: disable=ML002(findings list: bounded by acquire sites in the scanned repo, analyzer lives one run)
                                self.self_deadlocks.append((
                                    lock, fn.rel, node.lineno, fn.label,
                                ))
                    elif isinstance(node, ast.Call):
                        callee = self.project.resolve_call(node, fn, types)
                        if callee is None or callee.key == fn.key:
                            continue
                        for inner, chain in \
                                self.reachable_acquires(callee).items():
                            if inner != lock:
                                self._add_edge(
                                    lock, inner, fn.rel, node.lineno,
                                    (fn.label,) + chain,
                                )
                            elif not self.project.is_rlock(lock):
                                self.self_deadlocks.append((
                                    lock, fn.rel, node.lineno,
                                    " -> ".join((fn.label,) + chain),
                                ))

    def _add_edge(self, src: str, dst: str, path: str, line: int,
                  chain: tuple) -> None:
        key = (src, dst)
        existing = self.edges.get(key)
        if existing is None or (path, line) < (existing["path"],
                                               existing["line"]):
            # polylint: disable=ML002(lock-order edge set: bounded by lock-class pairs in the scanned repo, analyzer lives one run)
            self.edges[key] = {
                "path": path, "line": line,
                "via": " -> ".join(chain),
                "witnessed": False, "count": 0,
            }

    def merge_witness(self, witness_data: dict) -> None:
        """Fold observed (runtime) edges into the graph. Witness sites
        are creation sites (path:line); locks the static pass knows are
        mapped onto their static node, the rest become their own
        witness-only nodes."""
        site_to_lock: dict[str, str] = {}
        for module in self.project.modules.values():
            for cls in module.classes.values():
                for attr, line in cls.locks.items():
                    site_to_lock[f"{cls.rel}:{line}"] = f"{cls.key}.{attr}"
            for name, line in module.module_locks.items():
                site_to_lock[f"{module.rel}:{line}"] = \
                    f"{module.rel}::{name}"
        # Dataclass field(default_factory=threading.Lock) locks are
        # created inside the GENERATED __init__, which has no
        # witnessable frame — the runtime attributes them to the
        # ClassName(...) construction line. Register every resolvable
        # construction site as an alias of the field lock (only when
        # the class has exactly one field lock: two would be
        # indistinguishable at one call line).
        for module in self.project.modules.values():
            for node in ast.walk(module.ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                resolved = self.project.resolve_class_name(
                    module, call_name(node))
                if resolved is None:
                    continue
                cls = self.project.classes.get(resolved)
                if cls is None or len(cls.field_locks) != 1:
                    continue
                (attr,) = cls.field_locks
                site_to_lock.setdefault(
                    f"{module.rel}:{node.lineno}", f"{cls.key}.{attr}")

        def node_for(site: str) -> str:
            mapped = site_to_lock.get(site)
            if mapped is not None:
                return mapped
            # polylint: disable=ML002(bounded by distinct witness sites in one merged run, analyzer lives one run)
            self.witness_unmapped.setdefault(
                site, witness_data.get("sites", {}).get(site, {}))
            return f"witness::{site}"

        for edge in witness_data.get("edges", []):
            src = node_for(edge["src"])
            dst = node_for(edge["dst"])
            if src == dst:
                continue
            key = (src, dst)
            info = {
                "count": edge.get("count", 0),
                "stack": edge.get("stack") or [],
            }
            # polylint: disable=ML002(bounded by witness edge pairs in one merged run, analyzer lives one run)
            self.witness_edges[key] = info
            static = self.edges.get(key)
            if static is not None:
                static["witnessed"] = True
                static["count"] = info["count"]

    def _adjacency(self) -> dict[str, set[str]]:
        adj: dict[str, set[str]] = {}
        for src, dst in self.edges:
            adj.setdefault(src, set()).add(dst)
            adj.setdefault(dst, set())
        for src, dst in self.witness_edges:
            adj.setdefault(src, set()).add(dst)
            adj.setdefault(dst, set())
        return adj

    def find_cycles(self) -> list[list[str]]:
        """Cycles in the merged graph, one representative per SCC
        (Tarjan); deterministic order."""
        adj = self._adjacency()
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set[str] = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            work = [(v, iter(sorted(adj.get(v, ()))))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(adj.get(w, ())))))
                        advanced = True
                        break
                    elif w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    component = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        component.append(w)
                        if w == node:
                            break
                    if len(component) > 1:
                        sccs.append(sorted(component))

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        self.cycles = sorted(sccs)
        return self.cycles

    def _display(self, lock_key: str) -> str:
        if lock_key.startswith("witness::"):
            return lock_key[len("witness::"):]
        _, _, display = self.project.lock_site(lock_key)
        return display

    def cl001_findings(self) -> list[Finding]:
        findings: list[Finding] = []
        for lock, path, line, chain in sorted(set(self.self_deadlocks)):
            findings.append(_finding(
                "CL001", path, line,
                f"non-reentrant lock {self._display(lock)} is "
                f"re-acquired while already held (via {chain}) — a "
                "guaranteed self-deadlock; use the unlocked inner "
                "helper or an RLock",
            ))
        for cycle in self.find_cycles():
            members = set(cycle)
            edge_bits = []
            anchor: Optional[tuple[str, int]] = None
            witnessed_any = False
            for (src, dst), info in sorted(self.edges.items()):
                if src in members and dst in members:
                    tag = " [witnessed]" if info["witnessed"] else ""
                    edge_bits.append(
                        f"{self._display(src)} -> {self._display(dst)} "
                        f"at {info['path']}:{info['line']} "
                        f"(via {info['via']}){tag}"
                    )
                    witnessed_any |= bool(info["witnessed"])
                    site = (info["path"], info["line"])
                    if anchor is None or site < anchor:
                        anchor = site
            for (src, dst), info in sorted(self.witness_edges.items()):
                if src in members and dst in members \
                        and (src, dst) not in self.edges:
                    head = (info.get("stack") or ["?"])[-1]
                    edge_bits.append(
                        f"{self._display(src)} -> {self._display(dst)} "
                        f"witnessed only ({info['count']}x, at {head})"
                    )
                    witnessed_any = True
            if anchor is None:
                # Pure-witness cycle: anchor at a member lock's creation
                # site so the finding still lands on a suppressible line.
                path, line, _ = self.project.lock_site(cycle[0])
                anchor = (path, max(1, line))
            evidence = ("confirmed by the runtime witness"
                        if witnessed_any else "static approximation — "
                        "run the witness to confirm or refute")
            names = " -> ".join(self._display(c) for c in cycle)
            findings.append(_finding(
                "CL001", anchor[0], anchor[1],
                f"lock-order cycle ({names}): potential deadlock, "
                f"{evidence}; edges: " + "; ".join(edge_bits),
            ))
        return findings

    # -- CL002 ----------------------------------------------------------------

    def _thread_entries(self) -> list[FuncInfo]:
        entries: list[FuncInfo] = []
        for fn in self.project.functions.values():
            types = self.types_for(fn)
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if not (name.endswith(".Thread") or name == "Thread"):
                    continue
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    target = self.project.resolve_func_ref(
                        kw.value, fn, types)
                    if target is not None:
                        entries.append(target)
        return entries

    def _reachable_set(self, roots: list[FuncInfo]) -> set[str]:
        seen: set[str] = set()
        frontier = list(roots)
        while frontier:
            fn = frontier.pop()
            if fn.key in seen:
                continue
            seen.add(fn.key)
            types = self.types_for(fn)
            for node in walk_no_nested_functions(
                    getattr(fn.node, "body", [])):
                if isinstance(node, ast.Call):
                    callee = self.project.resolve_call(node, fn, types)
                    if callee is not None and callee.key not in seen:
                        frontier.append(callee)
        return seen

    def _attr_writes(self) -> dict[tuple[str, str], list[dict]]:
        """(class key, attr) -> write sites, for classes that own a
        lock. A write is an attribute (re)bind or augmented assign on a
        typed receiver; container mutation is CL003's domain."""
        writes: dict[tuple[str, str], list[dict]] = {}
        for fn in self.project.functions.values():
            if fn.name in ("__init__", "__post_init__"):
                continue        # construction happens-before publication
            types = self.types_for(fn)
            held_spans: list[tuple[str, int, int]] = []
            for lock, node in self._with_acquisitions(fn):
                held_spans.append(
                    (lock, node.lineno, node.end_lineno or node.lineno))
            for node in walk_no_nested_functions(
                    getattr(fn.node, "body", [])):
                targets: list[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for target in targets:
                    if not isinstance(target, ast.Attribute):
                        continue
                    owner = self.project.expr_type(target.value, types)
                    if owner is None:
                        continue
                    cls = self.project.classes.get(owner)
                    if cls is None or not cls.locks:
                        continue
                    if target.attr in cls.locks:
                        continue        # rebinding the lock itself
                    held = any(
                        lock.startswith(owner + ".")
                        and start <= node.lineno <= end
                        for lock, start, end in held_spans
                    )
                    writes.setdefault((owner, target.attr), []).append({
                        "fn": fn, "line": node.lineno, "held": held,
                    })
        return writes

    def cl002_findings(self) -> list[Finding]:
        thread_tree = self._reachable_set(self._thread_entries())
        public_roots = [
            fn for fn in self.project.functions.values()
            if not fn.name.startswith("_")
        ]
        public_tree = self._reachable_set(public_roots)
        findings: list[Finding] = []
        for (owner, attr), sites in sorted(self._attr_writes().items()):
            thread_sites = [s for s in sites
                            if s["fn"].key in thread_tree]
            public_sites = [s for s in sites
                            if s["fn"].key in public_tree]
            if not thread_sites or not public_sites:
                continue
            unguarded = [s for s in thread_sites + public_sites
                         if not s["held"]]
            if not unguarded:
                continue
            site = min(unguarded, key=lambda s: (s["fn"].rel, s["line"]))
            cls = self.project.classes[owner]
            lock_names = ", ".join(sorted(cls.locks))
            findings.append(_finding(
                "CL002", site["fn"].rel, site["line"],
                f"{cls.name}.{attr} is written from a thread entry's "
                f"call tree ({thread_sites[0]['fn'].label}) AND from "
                f"public-path code ({public_sites[0]['fn'].label}) "
                f"without holding {cls.name}'s lock ({lock_names}) — "
                "guard the write or annotate why the race is benign",
            ))
        return findings

    # -- CL003 ----------------------------------------------------------------

    def cl003_findings(self) -> list[Finding]:
        findings: list[Finding] = []
        for cls in self.project.classes.values():
            if not cls.locks or not cls.container_attrs:
                continue
            guarded: set[str] = set()
            for fn in cls.methods.values():
                types = self.types_for(fn)
                for lock, with_node in self._with_acquisitions(fn):
                    if not lock.startswith(cls.key + "."):
                        continue
                    for node in walk_no_nested_functions(with_node.body):
                        guarded.update(self._mutated_attrs(node, types,
                                                           cls))
            if not guarded:
                continue
            for fn in cls.methods.values():
                for node in walk_no_nested_functions(
                        getattr(fn.node, "body", [])):
                    if isinstance(node, (ast.Return, ast.Yield)):
                        value = node.value
                    else:
                        continue
                    if not (isinstance(value, ast.Attribute)
                            and isinstance(value.value, ast.Name)
                            and value.value.id == "self"):
                        continue
                    if value.attr in guarded:
                        kind = "returns" if isinstance(node, ast.Return) \
                            else "yields"
                        findings.append(_finding(
                            "CL003", fn.rel, node.lineno,
                            f"{cls.name}.{fn.name} {kind} a reference "
                            f"to lock-guarded container "
                            f"self.{value.attr} — the caller reads it "
                            "unsynchronized while writers mutate it "
                            "under the lock; return a copy "
                            f"(dict/list(self.{value.attr}))",
                        ))
        return findings

    def _mutated_attrs(self, node: ast.AST, types: dict[str, str],
                       cls: ClassInfo) -> set[str]:
        out: set[str] = set()

        def self_attr(expr: ast.AST) -> Optional[str]:
            if isinstance(expr, ast.Attribute) \
                    and isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self" \
                    and expr.attr in cls.container_attrs:
                return expr.attr
            return None

        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Subscript):
                    attr = self_attr(target.value)
                    if attr:
                        out.add(attr)
                else:
                    attr = self_attr(target)
                    if attr:
                        out.add(attr)
        elif isinstance(node, ast.AugAssign):
            base = node.target.value if isinstance(
                node.target, ast.Subscript) else node.target
            attr = self_attr(base)
            if attr:
                out.add(attr)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATING_METHODS:
            attr = self_attr(node.func.value)
            if attr:
                out.add(attr)
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                if isinstance(target, ast.Subscript):
                    attr = self_attr(target.value)
                    if attr:
                        out.add(attr)
        return out

    # -- CL004 ----------------------------------------------------------------

    def cl004_findings(self) -> list[Finding]:
        findings: list[Finding] = []
        seen: set[tuple] = set()
        for fn in self.project.functions.values():
            types = self.types_for(fn)
            for lock, with_node in self._with_acquisitions(fn):
                display = self._display(lock)
                for node in walk_no_nested_functions(with_node.body):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = self.project.resolve_call(node, fn, types)
                    if callee is None or callee.key == fn.key:
                        continue
                    for info in self.reachable_blocking(callee).values():
                        key = (fn.rel, node.lineno, lock, info["desc"],
                               info["path"], info["line"])
                        if key in seen:
                            continue
                        seen.add(key)
                        chain = " -> ".join(info["chain"])
                        findings.append(_finding(
                            "CL004", fn.rel, node.lineno,
                            f"holding {display}, this call reaches "
                            f"blocking {info['desc']} at "
                            f"{info['path']}:{info['line']} "
                            f"(via {chain}) — move the wait outside "
                            "the critical section or annotate",
                        ))
        return findings

    # -- CL005 ----------------------------------------------------------------

    def cl005_findings(self) -> list[Finding]:
        coordinator = self._module_endswith("engine/disagg_pool.py")
        worker = self._module_endswith("engine/worker.py")
        findings: list[Finding] = []
        if coordinator is not None and worker is not None:
            findings.extend(self._protocol_findings(coordinator, worker))
        kv = self._module_endswith("engine/kv_cache.py")
        if kv is not None:
            findings.extend(self._kv_wire_findings(kv))
        return findings

    def _module_endswith(self, suffix: str) -> Optional[ModuleInfo]:
        for rel, module in sorted(self.project.modules.items()):
            if rel.endswith(suffix):
                return module
        return None

    def _sent_ops(self) -> dict[str, list[tuple[str, int]]]:
        """op -> send sites, scanned repo-wide: the coordinator owns the
        protocol but scripts/tests also drive worker ops (arm_faults)."""
        ops: dict[str, list[tuple[str, int]]] = {}
        for module in self.project.modules.values():
            for node in ast.walk(module.ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                attr = func.attr if isinstance(func, ast.Attribute) else ""
                if attr not in ("request", "send") or not node.args:
                    continue
                value = _dict_const(node.args[0], "op")
                if value is not None:
                    ops.setdefault(value, []).append(
                        (module.rel, node.lineno))
        return ops

    def _handled_ops(self, worker: ModuleInfo,
                     ) -> dict[str, tuple[str, int]]:
        """op -> dispatch-branch site: string constants compared against
        a name assigned from header.get("op")."""
        handled: dict[str, tuple[str, int]] = {}
        op_names = _get_assignees(worker.ctx.tree, "op")
        for node in ast.walk(worker.ctx.tree):
            for const in _compared_constants(node, op_names, "op"):
                handled.setdefault(const, (worker.rel, node.lineno))
        return handled

    def _protocol_findings(self, coordinator: ModuleInfo,
                           worker: ModuleInfo) -> list[Finding]:
        findings: list[Finding] = []
        sent = self._sent_ops()
        handled = self._handled_ops(worker)
        coord_sent = {
            op: sites for op, sites in sent.items()
            if any(rel == coordinator.rel for rel, _ in sites)
        }
        for op, sites in sorted(coord_sent.items()):
            if op not in handled:
                rel, line = next(
                    s for s in sites if s[0] == coordinator.rel)
                findings.append(_finding(
                    "CL005", rel, line,
                    f"coordinator sends op {op!r} but the worker "
                    "dispatch has no handler branch for it — the "
                    "request would die with 'unknown op'",
                ))
        for op, (rel, line) in sorted(handled.items()):
            if op not in sent:
                findings.append(_finding(
                    "CL005", rel, line,
                    f"worker handles op {op!r} but nothing in the repo "
                    "ever sends it — dead protocol surface or a "
                    "renamed sender",
                ))
        # Worker-emitted events vs coordinator expectations.
        worker_events = self._emitted_events(worker)
        expected = self._expected_events(coordinator)
        for kind, (rel, line) in sorted(expected.items()):
            if kind not in worker_events:
                findings.append(_finding(
                    "CL005", rel, line,
                    f"coordinator expects stream event {kind!r} that "
                    "the worker never emits",
                ))
        for kind, info in sorted(worker_events.items()):
            if kind not in expected:
                findings.append(_finding(
                    "CL005", info["site"][0], info["site"][1],
                    f"worker emits stream event {kind!r} that the "
                    "coordinator never matches — it would hit the "
                    "unexpected-event re-route path",
                ))
        # Field sets: every event/reply field the coordinator reads must
        # be producible by some worker send; every req field the worker
        # reads must appear in the coordinator's request payloads.
        event_fields = set()
        for info in worker_events.values():
            event_fields.update(info["fields"])
        # Reply payloads often route through a builder
        # (send_msg(conn, self._ping_reply())), so the reply universe is
        # every string dict key in the worker module — coarser than the
        # event check, still catches a field that exists nowhere.
        reply_fields = self._emitted_reply_fields(worker) \
            | _all_dict_keys(worker.ctx.tree)
        for var_prefix, universe, side in (
            ("event", event_fields | {"event"}, "worker event"),
            ("reply", reply_fields | {"ok"}, "worker reply"),
        ):
            for field, (rel, line) in sorted(
                    self._read_fields(coordinator, var_prefix).items()):
                if field not in universe:
                    findings.append(_finding(
                        "CL005", rel, line,
                        f"coordinator reads field {field!r} from a "
                        f"{side} but no worker send includes it — "
                        "the read always sees None",
                    ))
        coord_keys = _all_dict_keys(coordinator.ctx.tree) \
            | _subscript_store_keys(coordinator.ctx.tree)
        for field, (rel, line) in sorted(
                self._read_fields(worker, "req").items()):
            if field not in coord_keys:
                findings.append(_finding(
                    "CL005", rel, line,
                    f"worker reads request field {field!r} that the "
                    "coordinator request payload never carries",
                ))
        return findings

    def _emitted_events(self, worker: ModuleInfo) -> dict[str, dict]:
        events: dict[str, dict] = {}
        for node in ast.walk(worker.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            attr = node.func.attr \
                if isinstance(node.func, ast.Attribute) else ""
            if not (name == "send_msg" or name.endswith(".send_msg")
                    or attr in ("send", "send_msg")):
                continue
            for arg in node.args:
                kind = _dict_const(arg, "event")
                if kind is None:
                    continue
                entry = events.setdefault(
                    kind, {"site": (worker.rel, node.lineno),
                           "fields": set()})
                entry["fields"].update(_dict_keys(arg))
        return events

    def _expected_events(self, coordinator: ModuleInfo,
                         ) -> dict[str, tuple[str, int]]:
        expected: dict[str, tuple[str, int]] = {}
        kind_names = _get_assignees(coordinator.ctx.tree, "event")
        for node in ast.walk(coordinator.ctx.tree):
            for const in _compared_constants(node, kind_names, "event"):
                expected.setdefault(const, (coordinator.rel, node.lineno))
        return expected

    def _emitted_reply_fields(self, worker: ModuleInfo) -> set[str]:
        fields: set[str] = set()
        for node in ast.walk(worker.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            attr = node.func.attr \
                if isinstance(node.func, ast.Attribute) else ""
            name = call_name(node)
            if not (name == "send_msg" or name.endswith(".send_msg")
                    or attr in ("send", "send_msg")):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Dict) \
                        and _dict_const(arg, "event") is None:
                    fields.update(_dict_keys(arg))
        return fields

    def _read_fields(self, module: ModuleInfo, var_prefix: str,
                     ) -> dict[str, tuple[str, int]]:
        """Fields read (`x.get("f")` / `x["f"]` loads) off variables
        whose NAME starts with `var_prefix` ('event', 'reply', 'req') —
        the repo's (and the fixtures') naming convention for protocol
        payload dicts."""
        reads: dict[str, tuple[str, int]] = {}

        def is_target(expr: ast.AST) -> bool:
            return isinstance(expr, ast.Name) \
                and expr.id.startswith(var_prefix)

        for node in ast.walk(module.ctx.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get" \
                    and is_target(node.func.value) and node.args:
                const = node.args[0]
                if isinstance(const, ast.Constant) \
                        and isinstance(const.value, str):
                    reads.setdefault(const.value,
                                     (module.rel, node.lineno))
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and is_target(node.value) \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str):
                reads.setdefault(node.slice.value,
                                 (module.rel, node.lineno))
        return reads

    def _kv_wire_findings(self, kv: ModuleInfo) -> list[Finding]:
        """The wire header must serialize/deserialize symmetrically:
        every key the reader touches is written, every written key is
        read back (a write-only field is drift waiting to happen), and
        both directions reference the MAGIC/VERSION constants."""
        findings: list[Finding] = []
        serialize = kv.functions.get("serialize_kv_state")
        readers = [kv.functions.get(name) for name in
                   ("_parse_header", "validate_kv_blob",
                    "deserialize_kv_state")]
        readers = [r for r in readers if r is not None]
        if serialize is None or not readers:
            return findings
        written = _all_dict_keys(serialize.node)
        read: dict[str, tuple[str, int]] = {}
        for reader in readers:
            for node in ast.walk(reader.node):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "get" \
                        and dotted(node.func.value) in ("header", "entry") \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    read.setdefault(node.args[0].value,
                                    (kv.rel, node.lineno))
                elif isinstance(node, ast.Subscript) \
                        and isinstance(node.ctx, ast.Load) \
                        and dotted(node.value) in ("header", "entry") \
                        and isinstance(node.slice, ast.Constant) \
                        and isinstance(node.slice.value, str):
                    read.setdefault(node.slice.value,
                                    (kv.rel, node.lineno))
        for field, (rel, line) in sorted(read.items()):
            if field not in written:
                findings.append(_finding(
                    "CL005", rel, line,
                    f"KV wire reader touches header field {field!r} "
                    "that serialize_kv_state never writes",
                ))
        for field in sorted(written):
            if field not in read:
                findings.append(_finding(
                    "CL005", kv.rel, serialize.node.lineno,
                    f"KV wire header field {field!r} is serialized but "
                    "no reader ever consumes it — write-only fields "
                    "drift silently; read it back (or drop it)",
                ))
        for const in ("KV_WIRE_MAGIC", "KV_WIRE_VERSION"):
            write_side = any(
                isinstance(n, ast.Name) and n.id == const
                for n in ast.walk(serialize.node)
            )
            read_side = any(
                isinstance(n, ast.Name) and n.id == const
                for reader in readers for n in ast.walk(reader.node)
            )
            if write_side != read_side:
                where = "serializer" if write_side else "reader"
                findings.append(_finding(
                    "CL005", kv.rel, serialize.node.lineno,
                    f"{const} is referenced only on the {where} side — "
                    "the framing constants must gate both directions",
                ))
        return findings

    # -- graph export ---------------------------------------------------------

    def graph_dict(self) -> dict:
        locks: dict[str, dict] = {}
        for module in self.project.modules.values():
            for cls in module.classes.values():
                for attr, line in cls.locks.items():
                    locks[f"{cls.key}.{attr}"] = {
                        "path": cls.rel, "line": line,
                        "display": f"{cls.name}.{attr}",
                        "kind": "rlock" if attr in cls.rlocks else "lock",
                    }
            for name, line in module.module_locks.items():
                locks[f"{module.rel}::{name}"] = {
                    "path": module.rel, "line": line,
                    "display": f"{module.rel.rsplit('/', 1)[-1]}:{name}",
                    "kind": "lock",
                }
        edges = []
        for (src, dst), info in sorted(self.edges.items()):
            edges.append({
                "src": src, "dst": dst, "site": f"{info['path']}:"
                f"{info['line']}", "via": info["via"],
                "witnessed": info["witnessed"],
                "count": info["count"],
            })
        for (src, dst), info in sorted(self.witness_edges.items()):
            if (src, dst) not in self.edges:
                edges.append({
                    "src": src, "dst": dst, "site": None,
                    "via": None, "witnessed": True,
                    "count": info["count"],
                    "stack": info.get("stack") or [],
                })
        return {
            "version": 1,
            "generated_by": "python -m polykey_tpu.analysis race",
            "locks": locks,
            "witness_only_sites": self.witness_unmapped,
            "edges": edges,
            "cycles": self.cycles,
        }


# -- small AST helpers for CL005 ----------------------------------------------


def _dict_const(node: ast.AST, key: str) -> Optional[str]:
    """Value of a string-constant `key` in a dict display, or None."""
    if not isinstance(node, ast.Dict):
        return None
    for k, v in zip(node.keys, node.values):
        if isinstance(k, ast.Constant) and k.value == key \
                and isinstance(v, ast.Constant) \
                and isinstance(v.value, str):
            return v.value
    return None


def _dict_keys(node: ast.AST) -> set[str]:
    if not isinstance(node, ast.Dict):
        return set()
    return {
        k.value for k in node.keys
        if isinstance(k, ast.Constant) and isinstance(k.value, str)
    }


def _all_dict_keys(tree: ast.AST) -> set[str]:
    keys: set[str] = set()
    for node in ast.walk(tree):
        keys.update(_dict_keys(node))
    return keys


def _subscript_store_keys(tree: ast.AST) -> set[str]:
    keys: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Store) \
                and isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str):
            keys.add(node.slice.value)
    return keys


def _get_assignees(tree: ast.AST, field: str) -> set[str]:
    """Names assigned from `<x>.get(field)`."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and isinstance(node.value.func, ast.Attribute) \
                and node.value.func.attr == "get" \
                and node.value.args \
                and isinstance(node.value.args[0], ast.Constant) \
                and node.value.args[0].value == field:
            for target in node.targets:
                if isinstance(target, ast.Name):
                    names.add(target.id)
    return names


def _compared_constants(node: ast.AST, names: set[str],
                        field: str) -> Iterator[str]:
    """String constants compared (== / in) against one of `names` or
    directly against `<x>.get(field)`."""
    if not isinstance(node, ast.Compare):
        return
    left = node.left

    def is_probe(expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name) and expr.id in names:
            return True
        return (isinstance(expr, ast.Call)
                and isinstance(expr.func, ast.Attribute)
                and expr.func.attr == "get"
                and bool(expr.args)
                and isinstance(expr.args[0], ast.Constant)
                and expr.args[0].value == field)

    if is_probe(left):
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)) \
                    and isinstance(comp, ast.Constant) \
                    and isinstance(comp.value, str):
                yield comp.value
            elif isinstance(op, ast.In) \
                    and isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                for el in comp.elts:
                    if isinstance(el, ast.Constant) \
                            and isinstance(el.value, str):
                        yield el.value


# -- runner -------------------------------------------------------------------


def run_race(
    root: Path,
    targets: Optional[list[str]] = None,
    only: Optional[set[str]] = None,
    witness_data: Optional[dict] = None,
) -> tuple[list[Finding], RaceAnalyzer]:
    """Build the project over `targets` (polylint's defaults when None),
    run the selected CL rules, apply per-file suppressions, and return
    (findings, analyzer) — the analyzer carries the merged lock graph
    for --dump-graph and the witness gate."""
    if targets is None:
        targets = [t for t in DEFAULT_TARGETS if (root / t).exists()]
        if not targets:
            raise FileNotFoundError(
                f"none of the default race targets "
                f"({', '.join(DEFAULT_TARGETS)}) exist under {root}"
            )
    project = Project()
    for path in iter_py_files(root, targets):
        project.add_file(path, root)
    project.finalize()
    analyzer = RaceAnalyzer(project)
    findings: list[Finding] = list(project.syntax_errors)

    def want(rule_id: str) -> bool:
        return only is None or rule_id in only

    # The lock graph (+ witness merge + cycle census) is built
    # regardless of rule selection: --dump-graph and the JSON summary
    # must describe the real merged graph even under --only CL005 —
    # a dump with silently-skipped merging would read as a clean graph
    # that was never computed. Only the FINDINGS are rule-gated.
    analyzer.collect_lock_edges()
    if witness_data is not None:
        analyzer.merge_witness(witness_data)
    if want("CL001"):
        findings.extend(analyzer.cl001_findings())
    else:
        analyzer.find_cycles()
    if want("CL002"):
        findings.extend(analyzer.cl002_findings())
    if want("CL003"):
        findings.extend(analyzer.cl003_findings())
    if want("CL004"):
        findings.extend(analyzer.cl004_findings())
    if want("CL005"):
        findings.extend(analyzer.cl005_findings())

    by_path: dict[str, list[Finding]] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    out: list[Finding] = []
    for rel, module in sorted(project.modules.items()):
        tier_findings = module.ctx.apply_suppressions(
            by_path.pop(rel, []), rules=RACE_RULES)
        if only is not None:
            # A partial run can't judge "unused": CL005's suppression
            # looks dead during an --only CL001 run.
            tier_findings = [
                f for f in tier_findings
                if not (f.rule == "CL000"
                        and "unused suppression" in f.message)
            ]
        out.extend(tier_findings)
    for rest in by_path.values():
        out.extend(rest)        # syntax-error files with no context
    return sorted(out, key=lambda f: (f.path, f.line, f.rule)), analyzer


# -- CLI ----------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m polykey_tpu.analysis race",
        description="racelint: concurrency & cross-process protocol "
                    "contract analysis (stdlib-only AST + optional "
                    "runtime lock witness)",
    )
    parser.add_argument(
        "targets", nargs="*", default=None,
        help=f"files/directories to scan "
             f"(default: {' '.join(DEFAULT_TARGETS)})",
    )
    parser.add_argument("--root", default=".",
                        help="repo root paths are reported relative to")
    parser.add_argument(
        "--baseline", default=RACE_BASELINE, metavar="FILE",
        help="grandfathering baseline file (missing file = empty)",
    )
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline file entirely")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="grandfather every current blocking finding into --baseline",
    )
    parser.add_argument(
        "--prune", action="store_true",
        help="drop stale baseline entries, keep the rest, exit",
    )
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings + summary as one JSON object")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument(
        "--only", default=None, metavar="CL001[,CL004...]",
        help="run only the named rules",
    )
    parser.add_argument(
        "--witness", default=None, metavar="FILE_OR_DIR",
        help="merge a runtime lock-witness dump (file, or a directory "
             "of per-process lock_witness_*.json) into the CL001 graph",
    )
    parser.add_argument(
        "--dump-graph", default=None, metavar="FILE",
        help="write the merged lock-order graph (+ cycles) as JSON",
    )
    return parser


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in RACE_RULES:
            print(f"{rule.id}  {rule.name:<28} {rule.description}")
        return 0

    root = Path(args.root).resolve()
    if not root.is_dir():
        print(f"racelint: --root {args.root} is not a directory",
              file=sys.stderr)
        return 2
    targets = args.targets or None
    try:
        # A typo'd id silently running zero rules would read as a clean
        # repo, and a partial run can't tell "fixed" from "not checked"
        # (shared refusal semantics, core.py).
        only = parse_only(args.only, RACE_RULE_IDS)
        require_full_run(partial=bool(targets) or only is not None,
                         prune=args.prune,
                         write_baseline=args.write_baseline)
        from . import witness as witness_mod

        witness_data = load_witness_arg(args.witness,
                                        witness_mod.load_witness)
    except UsageError as e:
        print(f"racelint: {e}", file=sys.stderr)
        return 2

    try:
        findings, analyzer = run_race(root, targets, only=only,
                                      witness_data=witness_data)
    except FileNotFoundError as e:
        print(f"racelint: {e}", file=sys.stderr)
        return 2

    if args.dump_graph:
        graph = analyzer.graph_dict()
        Path(args.dump_graph).write_text(
            json.dumps(graph, indent=2, sort_keys=True) + "\n",
            encoding="utf-8",
        )

    baseline_path = root / args.baseline
    if args.prune:
        infra = [f for f in findings if f.rule == "CL000"]
        if infra:
            print(
                f"racelint: refusing to prune with {len(infra)} CL000 "
                "finding(s) present — fix the suppression/parse problem "
                "first", file=sys.stderr)
            return 1
        kept, dropped = prune_baseline(baseline_path, findings)
        print(f"racelint: pruned {dropped} stale baseline entr"
              f"{'y' if dropped == 1 else 'ies'} from {baseline_path} "
              f"({kept} kept)")
        return 0
    if args.write_baseline:
        count = write_baseline(baseline_path, findings)
        print(f"racelint: wrote {count} baseline entr"
              f"{'y' if count == 1 else 'ies'} to {baseline_path}")
        return 0

    stale: list[str] = []
    if not args.no_baseline:
        findings, stale = apply_baseline(findings,
                                         load_baseline(baseline_path))
        if only is not None:
            stale = []      # partial runs can't call entries stale

    blocking = [f for f in findings if f.blocking]
    suppressed = sum(1 for f in findings if f.suppressed)
    baselined = sum(1 for f in findings if f.baselined)

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in findings],
            "summary": {
                "blocking": len(blocking),
                "suppressed": suppressed,
                "baselined": baselined,
                "stale_baseline_entries": stale,
                "lock_edges": len(analyzer.edges),
                "witnessed_edges": len(analyzer.witness_edges),
                "cycles": analyzer.cycles,
                "race_clean": not blocking,
            },
        }, indent=2))
    else:
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
            if f.blocking:
                print(f.render())
        parts = [f"{len(blocking)} blocking"]
        if suppressed:
            parts.append(f"{suppressed} suppressed")
        if baselined:
            parts.append(f"{baselined} baselined")
        parts.append(f"{len(analyzer.edges)} lock edges")
        if witness_data is not None:
            parts.append(f"{len(analyzer.witness_edges)} witnessed")
        parts.append(f"{len(analyzer.cycles)} cycles")
        print(f"racelint: {', '.join(parts)}")
        if stale:
            print(
                f"racelint: {len(stale)} stale baseline entr"
                f"{'y' if len(stale) == 1 else 'ies'} (fixed findings) "
                "— re-run with --prune to drop them",
            )
    return 1 if blocking else 0
