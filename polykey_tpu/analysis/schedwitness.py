"""Runtime starvation witness: the dynamic half of schedlint.

Static liveness analysis (analysis/sched.py, SL001–SL005) proves the
*shape* of the scheduler's fairness machinery — every budgeted loop has
a progress floor, every round-robin cursor advances, the frontiers
issue in order. It cannot prove that under a real mixed load no lane
actually aged out: a structurally fair scheduler can still starve a
slot when the workload keeps re-triggering the path that skips it
(faulting slots waiting on restores, pending prefills behind a
saturated budget). This module records what actually happened: with
``POLYKEY_SCHED_WITNESS=1`` in the environment, the engine loop calls
:func:`note` at every dispatch boundary — one call per frontier
(``restore``, ``prefill``, ``decode``) naming which slots were served
this boundary and which were eligible but skipped. The recorder keeps,
per frontier and slot, the wall-clock age of the oldest unserved wait
and the consecutive-skip count, plus the running worst case ever
observed. The summary dumps as JSON at process exit (and on demand),
one file per process under ``POLYKEY_SCHED_WITNESS_OUT`` (a directory —
the disagg drill spans several worker processes).

``python -m polykey_tpu.analysis sched --witness <file-or-dir>`` merges
these summaries into the static verdict: a slot whose wait age exceeded
the max-starvation-age gate (or whose consecutive-skip count exceeded
the skip gate) becomes an SL006 finding carrying the frontier, slot,
age, and skip count — real evidence from a real run.

Approximations (documented, same contract as the lock/heap witnesses):

- Wait ages are per-process monotonic-clock differences; no cross-
  process clock alignment is needed (unlike the trace-merge tier) and
  none is attempted — each process's worst case stands on its own.
- A process killed with ``os._exit`` (the worker-exit fault's real
  mode) never dumps — the drill's witness comes from the coordinator
  and the surviving workers.
- The witness sees dispatch *boundaries*, not device completion: a
  served slot whose dispatch later fails still counts as served. That
  is the right accounting for starvation (the scheduler offered it the
  frontier); failure handling is the watchdog's job.
"""

from __future__ import annotations

import json
import os
import sys
import time

SCHED_WITNESS_VERSION = 1
ENV_FLAG = "POLYKEY_SCHED_WITNESS"
ENV_OUT = "POLYKEY_SCHED_WITNESS_OUT"
DEFAULT_OUT = "/tmp/polykey-sched-witness"

# The witness obeys the discipline it audits: per-frontier state is one
# dict keyed by slot index (bounded by the engine's max_decode_slots),
# and the dump carries only aggregates plus a truncated worst-offender
# list — never an unbounded event log.
_TOP_WAITERS = 8

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))


def _relpath(filename: str) -> str:
    absolute = os.path.abspath(filename)
    if absolute.startswith(_REPO_ROOT + os.sep):
        return absolute[len(_REPO_ROOT) + 1:].replace(os.sep, "/")
    return absolute.replace(os.sep, "/")


class _FrontierState:
    __slots__ = ("notes", "serves", "waiting", "max_wait_age_s",
                 "max_wait_slot", "max_skips", "max_skip_slot")

    def __init__(self) -> None:
        self.notes = 0
        self.serves = 0
        # slot -> [first_wait_monotonic, consecutive_skips]
        self.waiting: dict[int, list] = {}
        self.max_wait_age_s = 0.0
        self.max_wait_slot = -1
        self.max_skips = 0
        self.max_skip_slot = -1


class _Recorder:
    def __init__(self) -> None:
        self.t0 = time.monotonic()
        self.frontiers: dict[str, _FrontierState] = {}

    def note(self, frontier: str, served, waiting) -> None:
        st = self.frontiers.get(frontier)
        if st is None:
            st = self.frontiers[frontier] = _FrontierState()
        now = time.monotonic()
        st.notes += 1
        served = set(served)
        st.serves += len(served)
        # A served slot's wait (if any) ends here; serving wins over
        # waiting when a slot appears in both (chunked prefill mid-
        # flight: it got a range this boundary, it is not starved).
        for i in served:
            st.waiting.pop(i, None)
        for i in waiting:
            if i in served:
                continue
            ent = st.waiting.get(i)
            if ent is None:
                st.waiting[i] = [now, 1]
                continue
            ent[1] += 1
            age = now - ent[0]
            if age > st.max_wait_age_s:
                st.max_wait_age_s = age
                st.max_wait_slot = i
            if ent[1] > st.max_skips:
                st.max_skips = ent[1]
                st.max_skip_slot = i
        # Slots no longer eligible (finished, cancelled, shed) stop
        # waiting — their recorded worst case already counted.
        gone = [i for i in st.waiting if i not in waiting]
        for i in gone:
            del st.waiting[i]

    def snapshot(self) -> dict:
        now = time.monotonic()
        frontiers: dict[str, dict] = {}
        for name, st in sorted(self.frontiers.items()):
            outstanding = sorted(
                ({"slot": i, "wait_age_s": round(now - t, 3), "skips": n}
                 for i, (t, n) in st.waiting.items()),
                key=lambda e: -e["wait_age_s"],
            )[:_TOP_WAITERS]
            # The gate reads the worst EVER observed, not just what is
            # still outstanding at dump time.
            max_age, max_slot = st.max_wait_age_s, st.max_wait_slot
            for e in outstanding:
                if e["wait_age_s"] > max_age:
                    max_age, max_slot = e["wait_age_s"], e["slot"]
            max_skips, skip_slot = st.max_skips, st.max_skip_slot
            for i, (_t, n) in st.waiting.items():
                if n > max_skips:
                    max_skips, skip_slot = n, i
            frontiers[name] = {
                "notes": st.notes,
                "serves": st.serves,
                "max_wait_age_s": round(max_age, 3),
                "max_wait_slot": max_slot,
                "max_consecutive_skips": max_skips,
                "max_skip_slot": skip_slot,
                "outstanding": outstanding,
            }
        return {
            "version": SCHED_WITNESS_VERSION,
            "pid": os.getpid(),
            "argv0": _relpath(sys.argv[0]) if sys.argv else "",
            "elapsed_s": round(now - self.t0, 3),
            "frontiers": frontiers,
        }


_recorder: _Recorder | None = None


def install() -> None:
    """Create the recorder and register the exit-time dump. Idempotent."""
    global _recorder
    if _recorder is not None:
        return
    _recorder = _Recorder()
    import atexit

    atexit.register(dump)


def maybe_install() -> bool:
    """install() iff POLYKEY_SCHED_WITNESS=1; returns whether installed."""
    if os.environ.get(ENV_FLAG, "") == "1":
        install()
        return True
    return False


def installed() -> bool:
    return _recorder is not None


def note(frontier: str, served, waiting) -> None:
    """Record one dispatch boundary (no-op unless installed). `served`
    is the slot indices this frontier dispatched work for; `waiting` is
    the indices that were ELIGIBLE for this frontier but got nothing —
    faulting slots at the restore frontier, pending-prefill slots at
    the prefill frontier. A slot in both counts as served."""
    if _recorder is not None:
        _recorder.note(frontier, served, waiting)


def snapshot() -> dict:
    if _recorder is None:
        return {"version": SCHED_WITNESS_VERSION, "pid": os.getpid(),
                "argv0": "", "elapsed_s": 0.0, "frontiers": {}}
    return _recorder.snapshot()


def dump(out: str | None = None) -> str | None:
    """Write this process's witness JSON. `out` (or
    $POLYKEY_SCHED_WITNESS_OUT, default /tmp/polykey-sched-witness) is a
    DIRECTORY; the file is sched_witness_<pid>.json so concurrent worker
    processes never clobber each other. Returns the written path (None
    when not installed)."""
    if _recorder is None:
        return None
    directory = out or os.environ.get(ENV_OUT, DEFAULT_OUT)
    try:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, f"sched_witness_{os.getpid()}.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(snapshot(), f, indent=2, sort_keys=True)
            f.write("\n")
        return path
    except OSError:
        return None  # a failed witness dump must never fail the run


def load_witness(path: str) -> list[dict]:
    """Load one witness file, or every sched_witness_*.json in a
    directory (the multi-process drill). Returns a list of per-process
    snapshots; raises ValueError on an unreadable/mismatched file."""
    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, name) for name in os.listdir(path)
            if name.startswith("sched_witness_") and name.endswith(".json")
        )
        if not files:
            raise ValueError(f"no sched_witness_*.json files under {path}")
    else:
        files = [path]
    out: list[dict] = []
    for name in files:
        with open(name, encoding="utf-8") as f:
            data = json.load(f)
        if data.get("version") != SCHED_WITNESS_VERSION:
            raise ValueError(
                f"sched witness file {name} has version "
                f"{data.get('version')!r}, expected {SCHED_WITNESS_VERSION}"
            )
        out.append(data)
    return out
